// ccsc_data — native data-preprocessing runtime for the CCSC TPU
// framework.
//
// The reference's data layer is MATLAB (image_helpers/CreateImages.m);
// its local contrast normalization (:299-370) is the per-image hot
// loop when preparing large training sets (the north-star run
// preprocesses ~1k images before any TPU work starts). This library
// implements that path natively: separable Gaussian filtering with
// reflected boundaries (exactly rconv2.m:47-58 semantics — the 2-D
// Gaussian kernel is separable, so two 1-D passes reproduce the full
// 13x13 convolution), the median-floored std normalization, and a
// std::thread worker pool across images.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: make -C native   (produces libccsc_data.so)

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// reflect index into [0, n) with symmetric (half-sample) padding:
// -1 -> 0, -2 -> 1, n -> n-1, n+1 -> n-2 (MATLAB padarray 'symmetric')
inline int reflect(int i, int n) {
  while (i < 0 || i >= n) {
    if (i < 0) i = -i - 1;
    if (i >= n) i = 2 * n - i - 1;
  }
  return i;
}

// 1-D Gaussian taps matching fspecial('gaussian',[k k],sigma) rows
// (the 2-D kernel is the outer product of these, normalized overall).
std::vector<double> gaussian_taps(int size, double sigma) {
  std::vector<double> t(size);
  double r = (size - 1) / 2.0;
  double s = 0.0;
  for (int i = 0; i < size; ++i) {
    double x = i - r;
    t[i] = std::exp(-(x * x) / (2.0 * sigma * sigma));
    s += t[i];
  }
  for (auto& v : t) v /= s;
  return t;
}

// separable same-size convolution with symmetric boundaries
void sep_conv(const double* src, double* dst, int h, int w,
              const std::vector<double>& taps, std::vector<double>& tmp) {
  int r = (int)taps.size() / 2;
  // horizontal pass into tmp
  for (int y = 0; y < h; ++y) {
    for (int x = 0; x < w; ++x) {
      double acc = 0.0;
      for (int k = -r; k <= r; ++k)
        acc += taps[k + r] * src[y * w + reflect(x + k, w)];
      tmp[y * w + x] = acc;
    }
  }
  // vertical pass into dst
  for (int y = 0; y < h; ++y) {
    for (int x = 0; x < w; ++x) {
      double acc = 0.0;
      for (int k = -r; k <= r; ++k)
        acc += taps[k + r] * tmp[reflect(y + k, h) * w + x];
      dst[y * w + x] = acc;
    }
  }
}

void local_cn_one(float* img, int h, int w, const std::vector<double>& taps) {
  const int npx = h * w;
  std::vector<double> dim(npx), lmn(npx), lsq(npx), tmp(npx), sq(npx);
  for (int i = 0; i < npx; ++i) {
    dim[i] = img[i];
    sq[i] = dim[i] * dim[i];
  }
  sep_conv(dim.data(), lmn.data(), h, w, taps, tmp);
  sep_conv(sq.data(), lsq.data(), h, w, taps, tmp);
  std::vector<double> lstd(npx);
  for (int i = 0; i < npx; ++i) {
    double v = lsq[i] - lmn[i] * lmn[i];
    lstd[i] = v > 0.0 ? std::sqrt(v) : 0.0;
  }
  // median floor (CreateImages.m:336-348); median of nonzeros if the
  // median itself is zero
  std::vector<double> sorted(lstd);
  auto mid = sorted.begin() + npx / 2;
  std::nth_element(sorted.begin(), mid, sorted.end());
  double th = *mid;
  if (th == 0.0) {
    std::vector<double> nz;
    nz.reserve(npx);
    for (double v : lstd)
      if (v > 0.0) nz.push_back(v);
    if (!nz.empty()) {
      auto m2 = nz.begin() + nz.size() / 2;
      std::nth_element(nz.begin(), m2, nz.end());
      th = *m2;
    }
  }
  const double eps = 2.220446049250313e-16;
  for (int i = 0; i < npx; ++i) {
    double s = std::max(lstd[i], th);
    if (s == 0.0) s = eps;
    img[i] = (float)((dim[i] - lmn[i]) / s);
  }
}

}  // namespace

extern "C" {

// In-place local contrast normalization of a batch of images.
// imgs: [n, h, w] float32 C-contiguous. Returns 0 on success.
int ccsc_local_cn(float* imgs, int64_t n, int64_t h, int64_t w,
                  int ksize, double sigma, int nthreads) {
  if (!imgs || n <= 0 || h <= 0 || w <= 0 || ksize <= 0 || !(sigma > 0))
    return 1;
  auto taps = gaussian_taps(ksize, sigma);
  if (nthreads <= 0)
    nthreads = (int)std::thread::hardware_concurrency();
  nthreads = std::max(1, std::min<int>(nthreads, (int)n));
  std::atomic<int64_t> next(0);
  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) {
    pool.emplace_back([&]() {
      while (true) {
        int64_t i = next.fetch_add(1);
        if (i >= n) break;
        local_cn_one(imgs + i * h * w, (int)h, (int)w, taps);
      }
    });
  }
  for (auto& th : pool) th.join();
  return 0;
}

// Normalized-convolution Gaussian fill of masked images, threaded:
// out = G*(img .* mask) / max(G*mask, eps) — the smooth_init warm
// start of the reconstruction drivers (the intended offset the
// reference's inpainting driver fails to pass, SURVEY.md section 5;
// Gaussian smoothing per reconstruct_subsampling_hyperspectral.m:46-55).
// imgs/mask: [n, h, w] float32 C-contiguous; imgs overwritten in place.
int ccsc_smooth_fill(float* imgs, const float* mask, int64_t n, int64_t h,
                     int64_t w, int ksize, double sigma, int nthreads) {
  if (!imgs || !mask || n <= 0 || h <= 0 || w <= 0 || ksize <= 0 ||
      !(sigma > 0))
    return 1;
  auto taps = gaussian_taps(ksize, sigma);
  if (nthreads <= 0)
    nthreads = (int)std::thread::hardware_concurrency();
  nthreads = std::max(1, std::min<int>(nthreads, (int)n));
  std::atomic<int64_t> next(0);
  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) {
    pool.emplace_back([&]() {
      const int64_t npx = h * w;
      std::vector<double> bm(npx), m(npx), num(npx), den(npx), tmp(npx);
      while (true) {
        int64_t i = next.fetch_add(1);
        if (i >= n) break;
        float* img = imgs + i * npx;
        const float* mk = mask + i * npx;
        for (int64_t j = 0; j < npx; ++j) {
          m[j] = mk[j];
          bm[j] = img[j] * m[j];
        }
        sep_conv(bm.data(), num.data(), (int)h, (int)w, taps, tmp);
        sep_conv(m.data(), den.data(), (int)h, (int)w, taps, tmp);
        for (int64_t j = 0; j < npx; ++j)
          img[j] = (float)(num[j] / std::max(den[j], 1e-6));
      }
    });
  }
  for (auto& th : pool) th.join();
  return 0;
}

// Batch zero-mean (per image), threaded. imgs: [n, h*w].
int ccsc_zero_mean(float* imgs, int64_t n, int64_t npx, int nthreads) {
  if (!imgs || n <= 0 || npx <= 0) return 1;
  if (nthreads <= 0)
    nthreads = (int)std::thread::hardware_concurrency();
  nthreads = std::max(1, std::min<int>(nthreads, (int)n));
  std::atomic<int64_t> next(0);
  std::vector<std::thread> pool;
  for (int t = 0; t < nthreads; ++t) {
    pool.emplace_back([&]() {
      while (true) {
        int64_t i = next.fetch_add(1);
        if (i >= n) break;
        float* p = imgs + i * npx;
        double mu = 0.0;
        for (int64_t j = 0; j < npx; ++j) mu += p[j];
        mu /= (double)npx;
        for (int64_t j = 0; j < npx; ++j) p[j] = (float)(p[j] - mu);
      }
    });
  }
  for (auto& th : pool) th.join();
  return 0;
}

int ccsc_version() { return 1; }

}  // extern "C"
