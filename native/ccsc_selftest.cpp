// Self-test harness for the native data runtime — built under
// ThreadSanitizer by `make tsan` (the race-detection answer for this
// framework: the reference is single-threaded MATLAB with nothing to
// race, SURVEY.md section 5; our C++ preprocessing pool is the only
// threaded component, so it carries the sanitizer coverage).
//
// Exercises every threaded entry point over a batch large enough that
// the worker pool genuinely interleaves, then checks the results are
// finite and the batch entries processed independently (entry i of a
// duplicated batch must equal entry 0).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
int ccsc_local_cn(float*, int64_t, int64_t, int64_t, int, double, int);
int ccsc_zero_mean(float*, int64_t, int64_t, int);
int ccsc_smooth_fill(float*, const float*, int64_t, int64_t, int64_t, int,
                     double, int);
}

namespace {

constexpr int64_t N = 64, H = 40, W = 40;

// tiny deterministic PRNG so the test needs no libc rand state
uint32_t rng_state = 12345;
float frand() {
  rng_state = rng_state * 1664525u + 1013904223u;
  return (rng_state >> 8) * (1.0f / 16777216.0f);
}

std::vector<float> dup_batch() {
  // one random image duplicated N times: every entry must come out equal
  std::vector<float> one(H * W);
  for (auto& v : one) v = frand();
  std::vector<float> batch(N * H * W);
  for (int64_t i = 0; i < N; ++i)
    std::memcpy(batch.data() + i * H * W, one.data(), H * W * sizeof(float));
  return batch;
}

int check_equal_and_finite(const std::vector<float>& b, const char* what) {
  for (int64_t i = 0; i < N; ++i)
    for (int64_t j = 0; j < H * W; ++j) {
      float v = b[i * H * W + j];
      if (!std::isfinite(v)) {
        std::fprintf(stderr, "%s: non-finite at [%ld,%ld]\n", what,
                     (long)i, (long)j);
        return 1;
      }
      if (v != b[j]) {
        std::fprintf(stderr, "%s: entry %ld differs from entry 0\n", what,
                     (long)i);
        return 1;
      }
    }
  return 0;
}

}  // namespace

int main() {
  int rc = 0;

  auto a = dup_batch();
  rc |= ccsc_local_cn(a.data(), N, H, W, 13, 4.773, 8);
  rc |= check_equal_and_finite(a, "local_cn");

  auto b = dup_batch();
  rc |= ccsc_zero_mean(b.data(), N, H * W, 8);
  rc |= check_equal_and_finite(b, "zero_mean");

  auto c = dup_batch();
  std::vector<float> mask(N * H * W);
  for (int64_t j = 0; j < H * W; ++j) mask[j] = (j % 3 == 0) ? 1.0f : 0.0f;
  for (int64_t i = 1; i < N; ++i)
    std::memcpy(mask.data() + i * H * W, mask.data(), H * W * sizeof(float));
  rc |= ccsc_smooth_fill(c.data(), mask.data(), N, H, W, 13, 4.773, 8);
  rc |= check_equal_and_finite(c, "smooth_fill");

  if (rc == 0) std::printf("ccsc_selftest: OK\n");
  return rc;
}
