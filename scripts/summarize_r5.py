#!/usr/bin/env python
"""Render onchip_r5.jsonl as the PERF.md markdown tables.

The tunnel historically answers in short windows (r4: 31 minutes in a
12-hour round), so the write-up must be quick: this turns whatever the
phase runner recorded — bench arms, bandwidth fit, accuracy probe,
family arms, hs profile, xprof attribution — into paste-ready
markdown. Usage: python scripts/summarize_r5.py [jsonl_path]
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rows(path):
    if not os.path.exists(path):
        return
    for line in open(path):
        try:
            yield json.loads(line)
        except Exception:
            continue


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "onchip_r5.jsonl"
    )
    arms, fams, bw, bwfit, acc, hsp, xp, notes = [], [], [], [], [], [], [], []
    for rec in rows(path):
        if "run" in rec and "result" in rec:
            arms.append(rec)
        elif "family_arm" in rec:
            fams.append(rec)
        elif "bwprobe" in rec:
            bw.append(rec)
        elif "bwprobe_fit" in rec or "bwprobe_verdict" in rec:
            bwfit.append(rec)
        elif "config" in rec and "obj_dev" in str(rec):
            acc.append(rec)
        elif "hs_profile" in rec:
            hsp.append(rec["hs_profile"])
        elif "xprof" in rec:
            xp.append(rec)
        elif "tpu_fused_parity" in rec:
            acc.append(rec)
        elif "note" in rec:
            notes.append(rec)
        else:
            acc.append(rec)  # accuracy-probe lines and anything else

    def is_chip(a):
        m = a["result"].get("metric", "")
        return ", 1 chip" in m and float(a["result"].get("value", 0)) > 0

    # baseline = best real-chip baseline (same filter as pick_tuned /
    # last_onchip_record — a DEGRADED rerun must not replace it)
    base = max(
        (float(a["result"]["value"]) for a in arms
         if a["run"] == "baseline" and is_chip(a)),
        default=None,
    )
    if arms:
        print("## Bench arms (onchip_r5.jsonl)\n")
        print("| Arm | iters/sec | vs r5 baseline | knobs |")
        print("|---|---|---|---|")
        for a in arms:
            r = a["result"]
            v = float(r.get("value", 0))
            rel = f"{v / base:.2f}x" if base and v and is_chip(a) else "-"
            knobs = r.get("knobs") or {}
            kn = ", ".join(
                f"{k}={v2}" for k, v2 in knobs.items()
                if v2 not in (False, "none", "float32", "xla")
            ) or "defaults"
            tag = "" if is_chip(a) else " (NOT ON CHIP)"
            print(f"| {a['run']}{tag} | {v:.4g} | {rel} | {kn} |")
        print()
    if fams:
        print("## Family arms\n")
        print("| Arm | family | iters/sec | notes |")
        print("|---|---|---|---|")
        for f in fams:
            r = f["result"]
            print(
                f"| {f['family_arm']} | {r.get('family', '?')} | "
                f"{r.get('iters_per_sec', '?')} | {r.get('metric', '')} |"
            )
        print()
    if bw or bwfit:
        print("## Bandwidth probe\n")
        if bw:
            print("| Op | moved MB | ms | GB/s |")
            print("|---|---|---|---|")
            for b in bw:
                print(
                    f"| {b['bwprobe']} | {b['moved_mb']} | {b['ms']} | "
                    f"{b['gbps']} |"
                )
        for f in bwfit:
            print()
            print(f"fit: `{json.dumps(f)}`")
        print()
    if hsp:
        print("## HS differential profile\n")
        print("| fft_impl | carry | s/step | d-iter ms | z-iter ms | fixed ms |")
        print("|---|---|---|---|---|---|")
        for h in hsp:
            print(
                f"| {h.get('fft_impl')} | {h.get('carry_freq')} | "
                f"{h.get('step_s_10_10')} | {h.get('per_d_iter_ms')} | "
                f"{h.get('per_z_iter_ms')} | {h.get('fixed_ms')} |"
            )
        print()
        for h in hsp:
            inv = h.get("inverse_ms")
            if inv:
                print(f"per-method Gram-inverse ms: `{json.dumps(inv)}`")
        print()
    if xp:
        print("## xprof attribution (top ops)\n")
        for x in xp:
            if x.get("xprof") != "ok":
                print(f"- {json.dumps(x)}")
                continue
            print(f"plane `{x['plane']}`, line `{x['line']}`, "
                  f"total {x['total_ms']} ms:\n")
            print("| Op | ms | % |")
            print("|---|---|---|")
            for op in x.get("top_ops", [])[:12]:
                print(f"| `{op['op'][:60]}` | {op['ms']} | {op['pct']} |")
        print()
    if acc:
        print("## Accuracy / parity records\n")
        for a in acc:
            print(f"- `{json.dumps(a)[:240]}`")
        print()
    if notes:
        print("## Runner notes\n")
        for n in notes[-20:]:
            print(f"- {n.get('at', '')} {n.get('note', '')}")


if __name__ == "__main__":
    main()
