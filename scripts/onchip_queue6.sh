#!/bin/bash
# Round-4 on-chip queue, phase 6: train the self-owned 3D/4D/HS banks
# on the chip (scripts/family_banks.py — ~50x the CPU rate at the 3D
# reference operating point). Waits for all measurement phases and the
# final pick, then runs once; artifacts land in artifacts_family/.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/onchip_queue6.log

probe() {
  timeout 60 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
x = jnp.ones((128, 128)); float((x @ x).sum())
" > /dev/null 2>&1
}

while pgrep -f "scripts/onchip_queue[1-5]?\.sh" | grep -qv $$ 2>/dev/null; do
  echo "$(date +%H:%M:%S) earlier phase still running" >> "$LOG"
  sleep 180
done

while true; do
  if probe; then
    echo "$(date +%H:%M:%S) phase 6: family banks on chip" >> "$LOG"
    timeout 7200 python scripts/family_banks.py --hs-n 12 \
      --out artifacts_family >> "$LOG" 2>&1 \
      && echo "$(date +%H:%M:%S) family banks DONE" >> "$LOG" \
      || echo "$(date +%H:%M:%S) family banks FAILED" >> "$LOG"
    break
  fi
  echo "$(date +%H:%M:%S) tunnel down" >> "$LOG"
  sleep 240
done
