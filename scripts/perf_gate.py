#!/usr/bin/env python
"""Regression gate over the durable perf ledger (analysis.ledger).

    python scripts/perf_gate.py                      # gate: newest
                                                     # record per key vs
                                                     # its prior history
    python scripts/perf_gate.py --seed-from          # seed the ledger
                                                     # from BENCH_r*.json
                                                     # + onchip_r*.jsonl
    python scripts/perf_gate.py --seed-from A.json B.jsonl ...
    python scripts/perf_gate.py --record REC.json    # gate one external
                                                     # record (CI: the
                                                     # run you just
                                                     # measured) without
                                                     # appending it
    python scripts/perf_gate.py --list               # per-key history
    python scripts/perf_gate.py --list --kind replay # one run family
    python scripts/perf_gate.py --json               # machine-readable

Exit status: 0 = no regression (keys with fewer than
CCSC_PERF_GATE_MIN_HISTORY prior records pass trivially and are
reported as skipped — a young ledger starts gating as history
accrues), 1 = at least one key's judged record fell below its
robust band (median − max(CCSC_PERF_GATE_MAD · 1.4826 · MAD,
CCSC_PERF_GATE_FRAC · median) of the key's prior history).

The ledger path comes from --ledger, else CCSC_PERF_LEDGER, else the
standard resolution (analysis.ledger.default_ledger_path). This is
the CI-runnable end of the performance observatory: run it after any
bench/serve session that appended to the ledger and a silent
slowdown fails the build instead of shipping. Record kinds judged:
learn | bench | serve | solve | replay (traffic-replay sessions,
serve.replay — requests/sec of a captured stream re-served);
--kind restricts gating/listing to one family.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.analysis import ledger as ledger_mod  # noqa: E402


def _fmt_verdict(v) -> str:
    if v.get("skipped"):
        return (
            f"perf-gate: SKIP  {v['key']}  "
            f"({v.get('reason', 'insufficient history')}, "
            f"n={v.get('n_history', 0)})"
        )
    tag = "OK  " if v["ok"] else "REGRESSION"
    rel = v.get("ratio_vs_median")
    rel_s = f"{100 * (rel - 1):+.1f}% vs median" if rel else "n/a"
    return (
        f"perf-gate: {tag}  {v['key']}  "
        f"{v['value']:.6g} {v.get('unit') or ''} ({rel_s}, "
        f"median {v['median']:.6g}, band lo {v['lo']:.6g}, "
        f"n={v['n_history']})"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--ledger", default=None,
        help="ledger JSONL path (default: CCSC_PERF_LEDGER, else "
        "$CCSC_COMPILE_CACHE/ccsc_perf_ledger.jsonl, else repo "
        "perf_ledger.jsonl)",
    )
    ap.add_argument(
        "--seed-from", nargs="*", default=None, metavar="PATH",
        help="seed the ledger from historical artifacts and exit "
        "(no PATHs = the repo's BENCH_r*.json + onchip_r*.jsonl)",
    )
    ap.add_argument(
        "--record", default=None, metavar="REC.json",
        help="gate ONE external record (normalized fields: chip, "
        "kind, value, unit[, workload, shape_key, knobs]) against "
        "the ledger history for its key, without appending",
    )
    ap.add_argument(
        "--mad", type=float, default=None,
        help="band half-width in MAD-sigmas (CCSC_PERF_GATE_MAD, "
        "default 3.0)",
    )
    ap.add_argument(
        "--frac", type=float, default=None,
        help="minimum relative drop treated as regression "
        "(CCSC_PERF_GATE_FRAC, default 0.25)",
    )
    ap.add_argument(
        "--min-history", type=int, default=None,
        help="prior records a key needs before it is judged "
        "(CCSC_PERF_GATE_MIN_HISTORY, default 3)",
    )
    ap.add_argument(
        "--list", action="store_true", dest="list_keys",
        help="print per-key history summaries and exit",
    )
    ap.add_argument(
        "--kind", default=None,
        help="restrict gating/--list to one record kind (learn | "
        "bench | serve | solve | replay)",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit verdicts as JSON",
    )
    args = ap.parse_args(argv)

    led = ledger_mod.Ledger(args.ledger)

    if args.seed_from is not None:
        counts = ledger_mod.seed_all(
            led, paths=args.seed_from or None, repo=REPO
        )
        total = sum(counts.values())
        if args.as_json:
            print(json.dumps({"seeded": counts, "total": total}))
        else:
            for path, n in counts.items():
                print(
                    f"perf-gate: seeded {n:3d} record(s) from "
                    f"{os.path.relpath(path, REPO)}"
                )
            print(
                f"perf-gate: {total} record(s) -> "
                f"{os.path.relpath(led.path) if not os.path.isabs(args.ledger or '') else led.path}"
            )
        return 0

    def _kind_of(key: str) -> str:
        parts = key.split("|")
        return parts[1] if len(parts) > 1 else ""

    if args.list_keys:
        groups = led.by_key()
        if args.kind:
            groups = {
                k: v for k, v in groups.items()
                if _kind_of(k) == args.kind
            }
        rows = []
        for key, recs in sorted(groups.items()):
            band = ledger_mod.robust_band(
                [r["value"] for r in recs],
                mad_k=args.mad, frac=args.frac,
            )
            rows.append(
                {
                    "key": key,
                    "n": len(recs),
                    "unit": recs[-1].get("unit"),
                    "newest": recs[-1]["value"],
                    "median": band["median"],
                    "lo": band["lo"],
                    "degraded": sum(
                        1 for r in recs if r.get("degraded")
                    ),
                }
            )
        if args.as_json:
            print(json.dumps(rows, indent=1))
        else:
            if not rows:
                print("perf-gate: ledger is empty")
            for r in rows:
                deg = (
                    f", {r['degraded']} degraded"
                    if r["degraded"] else ""
                )
                print(
                    f"  {r['key']}\n"
                    f"    n={r['n']}{deg}  newest "
                    f"{r['newest']:.6g} {r['unit'] or ''}  median "
                    f"{r['median']:.6g}  band lo {r['lo']:.6g}"
                )
        return 0

    record = None
    if args.record is not None:
        try:
            with open(args.record, encoding="utf-8") as f:
                record = json.load(f)
        except (OSError, ValueError) as e:
            print(f"perf-gate: cannot read --record: {e}",
                  file=sys.stderr)
            return 2
        if not isinstance(record, dict) or not record.get("chip"):
            print(
                "perf-gate: --record needs a normalized record "
                "(chip, kind, value, unit[, workload, shape_key, "
                "knobs])",
                file=sys.stderr,
            )
            return 2

    try:
        verdicts = ledger_mod.gate(
            led,
            mad_k=args.mad,
            frac=args.frac,
            min_history=args.min_history,
            record=record,
        )
    except ValueError as e:
        # a malformed --record is a usage error (exit 2), never a
        # regression verdict (exit 1) CI would act on
        print(f"perf-gate: {e}", file=sys.stderr)
        return 2
    if args.kind:
        verdicts = [
            v for v in verdicts if _kind_of(v["key"]) == args.kind
        ]
    judged = [v for v in verdicts if not v.get("skipped")]
    bad = [v for v in judged if not v["ok"]]
    skipped = [v for v in verdicts if v.get("skipped")]
    if args.as_json:
        print(
            json.dumps(
                {
                    "ledger": led.path,
                    "verdicts": verdicts,
                    "n_judged": len(judged),
                    "n_regressions": len(bad),
                    "n_skipped": len(skipped),
                },
                indent=1,
            )
        )
    else:
        for v in judged:
            print(_fmt_verdict(v))
        if skipped:
            print(
                f"perf-gate: {len(skipped)} key(s) skipped "
                "(insufficient history — they start gating as "
                "records accrue)"
            )
        if not verdicts:
            print(
                "perf-gate: ledger is empty — seed it "
                "(--seed-from) or arm CCSC_PERF_LEDGER on your "
                "runs"
            )
        print(
            f"perf-gate: {len(judged)} judged, {len(bad)} "
            f"regression(s) ({led.path})"
        )
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
