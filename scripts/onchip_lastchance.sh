#!/bin/bash
# Round-4 last-chance runner: replaces the open-ended phase watchers
# near round end. If the tunnel answers before the deadline, measure
# ONLY the quick second-wave arms (fused kernel + bf16-precision FFT)
# and re-pick bench_tuned.json; exit unconditionally at the deadline
# so the driver's end-of-round bench never shares the tunnel with us
# (two concurrent clients wedge a live tunnel — PERF.md protocol).
set -u
cd "$(dirname "$0")/.."
OUT=onchip_r4.jsonl
LOG=/tmp/onchip_lastchance.log
DEADLINE_EPOCH=$(date -d "16:05" +%s 2>/dev/null || echo 0)

probe() {
  timeout 45 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
x = jnp.ones((128, 128)); float((x @ x).sum())
" > /dev/null 2>&1
}

note() { echo "{\"note\": \"$1\", \"at\": \"$(date +%H:%M:%S)\"}" >> "$OUT"; }

run_bench() {
  local label=$1; shift
  echo "=== $label $(date +%H:%M:%S)" >> "$LOG"
  local line
  line=$(env "$@" CCSC_BENCH_TIMEOUT=600 timeout 900 python bench.py 2>> "$LOG" | tail -1)
  if [ -n "$line" ] && echo "$line" | python -c \
      'import json,sys; json.load(sys.stdin)' > /dev/null 2>&1; then
    echo "{\"run\": \"$label\", \"result\": $line}" >> "$OUT"
  else
    note "$label FAILED/empty"
  fi
}

while true; do
  now=$(date +%s)
  if [ "$DEADLINE_EPOCH" -gt 0 ] && [ "$now" -ge "$DEADLINE_EPOCH" ]; then
    echo "$(date +%H:%M:%S) deadline reached, exiting" >> "$LOG"
    exit 0
  fi
  if probe; then
    note "last-chance window"
    run_bench fused_z_bf16 CCSC_BENCH_FUSEDZ=1 CCSC_BENCH_STORAGE=bfloat16 \
      CCSC_BENCH_FFTIMPL=matmul CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=none
    run_bench fused_z_bf16_all CCSC_BENCH_FUSEDZ=1 CCSC_BENCH_STORAGE=bfloat16 \
      CCSC_BENCH_DSTORAGE=bfloat16 CCSC_BENCH_FFTIMPL=matmul \
      CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=none
    run_bench matmul_bf16prec CCSC_BENCH_FFTIMPL=matmul_bf16 \
      CCSC_BENCH_STORAGE=bfloat16 CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=none
    python scripts/pick_tuned.py >> "$LOG" 2>&1
    note "last-chance complete"
    exit 0
  fi
  echo "$(date +%H:%M:%S) tunnel down" >> "$LOG"
  sleep 180
done
