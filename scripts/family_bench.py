#!/usr/bin/env python
"""Per-family throughput benchmarks (VERDICT r3 next-round #6).

The north-star bench (bench.py) covers only the 2D consensus learner.
This script measures one operating point for each remaining family:

  hs         2-3D hyperspectral masked learner (admm_learn.m shape)
  3d         3D video consensus learner (admm_learn_conv3D_large.m)
  demosaic   2-3D demosaic reconstruction, pad=False, W=31
             (admm_solve_conv23D_weighted_sampling.m, max_it=200 protocol)
  viewsynth  4D view-synth reconstruction, W=25 angular views
             (admm_solve_conv_weighted_sampling_lf.m)

Prints one JSON line per family: {"family", "metric", "iters_per_sec",
"platform", ...}. Families: CCSC_FAMILIES env (comma list, default all).
Sizes are chosen to exercise the real geometry at single-chip scale;
each timed region is fenced by a scalar readback (axon
block_until_ready is a no-op).
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils import env as cenv
from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax
import jax.numpy as jnp


def out(d, **knobs):
    """fft_impl reaches every bench; the other knobs are stamped by
    the benches that actually apply them (kwargs) so records never
    claim a knob their workload ignored."""
    d["fft_impl"] = FFT_IMPL
    for k, v in knobs.items():
        d[k] = v
    d["platform"] = jax.devices()[0].platform
    print(json.dumps(d), flush=True)


def bench_hs():
    """Masked hyperspectral learner: k=100 filters 11x11x31, n=2 cubes
    96^2 x 31 (learn_hyperspectral.m protocol: max_it_d=max_it_z=10)."""
    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked

    n, side, bands, k = 2, 96, 31, 100
    iters = cenv.env_int("CCSC_FAMILY_ITERS")
    b = jax.random.uniform(
        jax.random.PRNGKey(0), (n, bands, side, side), jnp.float32
    )
    geom = ProblemGeom((11, 11), k, (bands,))
    # warm call compiles the jitted step (excluded from the rate, like
    # the other benches); the timed call then reuses the jit cache
    warm = LearnConfig(
        max_it=1, max_it_d=10, max_it_z=10, tol=0.0, verbose="none",
        fft_impl=FFT_IMPL, storage_dtype=STORAGE, carry_freq=CARRY,
    )
    learn_masked(b, geom, warm)
    cfg = LearnConfig(
        max_it=iters, max_it_d=10, max_it_z=10, tol=0.0, verbose="none",
        fft_impl=FFT_IMPL, storage_dtype=STORAGE, carry_freq=CARRY,
    )
    t0 = time.perf_counter()
    res = learn_masked(b, geom, cfg)
    dt = time.perf_counter() - t0
    # the rollback guard can end the run early: rate uses the REALIZED
    # iteration count
    done = max(1, len(res.trace["obj_vals_z"]))
    solver_t = res.trace["tim_vals"][-1]
    ips = done / solver_t if solver_t > 0 else done / dt
    out(
        {
            "family": "hs_masked_learner",
            "metric": f"outer iters/sec (k={k} 11x11x{bands}, n={n}x{side}^2)",
            "iters_per_sec": round(ips, 4),
            "iters_done": done,
            "wall_s": round(dt, 1),
        },
        storage_dtype=STORAGE,
        carry_freq=CARRY,
    )


def bench_3d():
    """3D video consensus learner: k=49 11^3 filters, n=8 volumes 50^3,
    4 blocks (learn_kernels_3D.m geometry at single-chip scale)."""
    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
    from ccsc_code_iccv2017_tpu.parallel import consensus
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    blocks, ni, side, k = 4, 2, 50, 49
    iters = cenv.env_int("CCSC_FAMILY_ITERS")
    geom = ProblemGeom((11, 11, 11), k)
    cfg = LearnConfig(
        max_it=iters, max_it_d=5, max_it_z=10, num_blocks=blocks,
        rho_d=5000.0, rho_z=1.0, verbose="none", fft_impl=FFT_IMPL,
        storage_dtype=STORAGE,
    )
    fg = common.FreqGeom.create(geom, (side, side, side), fft_impl=FFT_IMPL)
    state = learn_mod.init_state(
        jax.random.PRNGKey(0), geom, fg, blocks, ni,
        z_dtype=jnp.dtype(STORAGE),
    )
    b_blocks = jax.random.normal(
        jax.random.PRNGKey(1), (blocks, ni, side, side, side), jnp.float32
    )
    step = consensus.make_outer_step(geom, cfg, fg, mesh=None)
    try:
        compiled = step.lower(state, b_blocks).compile()
    except Exception:
        compiled = step
    s1, m0 = compiled(state, b_blocks)
    float(m0.d_diff)
    t0 = time.perf_counter()
    cur = s1
    for _ in range(iters):
        cur, m = compiled(cur, b_blocks)
    float(m.d_diff)
    dt = time.perf_counter() - t0
    rec = {
        "family": "3d_consensus_learner",
        "metric": f"outer iters/sec (k={k} 11^3, n={blocks * ni}x{side}^3, "
        f"{blocks} blocks)",
        "iters_per_sec": round(iters / dt, 4),
    }
    cost = (
        perfmodel.compiled_cost(compiled) if compiled is not step else None
    )
    if cost:
        u = perfmodel.utilization(cost, iters / dt)
        rec.update(
            mfu=round(u["mfu_vs_bf16_peak"], 5),
            hbm_frac=round(u["hbm_frac"], 4),
        )
    out(rec, storage_dtype=STORAGE)


def _bench_recon(family, geom, k_shape, side, reduce_shape, lam_res):
    """Shared reconstruction timing: fixed trip count (tol=0), one
    warm call for compile, then timed calls."""
    from ccsc_code_iccv2017_tpu.config import SolveConfig
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
        reconstruct,
    )

    max_it = cenv.env_int("CCSC_FAMILY_RECON_ITERS")
    d = jax.random.normal(jax.random.PRNGKey(2), k_shape, jnp.float32)
    d = d / jnp.sqrt(
        jnp.sum(d * d, axis=tuple(range(1, d.ndim)), keepdims=True)
    )
    b = jax.random.uniform(
        jax.random.PRNGKey(3), (1, *reduce_shape, side, side), jnp.float32
    )
    mask = (
        jax.random.uniform(jax.random.PRNGKey(4), b.shape) > 0.7
    ).astype(jnp.float32)
    prob = ReconstructionProblem(geom, pad=False)
    cfg = SolveConfig(
        lambda_residual=lam_res, lambda_prior=1.0, max_it=max_it,
        tol=0.0, verbose="none",
        fft_impl=FFT_IMPL,
    )
    r = reconstruct(b * mask, d, prob, cfg, mask=mask)  # compile + run
    float(jnp.sum(r.recon))
    t0 = time.perf_counter()
    r = reconstruct(b * mask, d, prob, cfg, mask=mask)
    float(jnp.sum(r.recon))
    dt = time.perf_counter() - t0
    out(
        {
            "family": family,
            "metric": f"ADMM iters/sec (k={k_shape[0]}, {side}^2, "
            f"W={int(jnp.prod(jnp.array(reduce_shape)) if reduce_shape else 1)}, "
            f"max_it={max_it})",
            "iters_per_sec": round(max_it / dt, 4),
        }
    )


def bench_demosaic():
    from ccsc_code_iccv2017_tpu.config import ProblemGeom

    bands = 31
    _bench_recon(
        "demosaic_recon",
        ProblemGeom((11, 11), 100, (bands,)),
        (100, bands, 11, 11),
        96,
        (bands,),
        100000.0,
    )


def bench_viewsynth():
    from ccsc_code_iccv2017_tpu.config import ProblemGeom

    _bench_recon(
        "viewsynth_recon",
        ProblemGeom((11, 11), 49, (5, 5)),
        (49, 5, 5, 11, 11),
        96,
        (5, 5),
        10000.0,
    )


FFT_IMPL = cenv.env_str("CCSC_FAMILY_FFTIMPL")
STORAGE = cenv.env_str("CCSC_FAMILY_STORAGE")
CARRY = cenv.env_flag("CCSC_FAMILY_CARRY")


FAMILIES = {
    "hs": bench_hs,
    "3d": bench_3d,
    "demosaic": bench_demosaic,
    "viewsynth": bench_viewsynth,
}


def main():
    names = (cenv.env_str("CCSC_FAMILIES") or ",".join(FAMILIES)).split(",")
    for name in names:
        name = name.strip()
        if name:
            FAMILIES[name]()


if __name__ == "__main__":
    main()
