#!/usr/bin/env python
"""Quality gate over the durable ledger's ``kind=quality`` records
(serve.quality shadow scoring).

    python scripts/quality_gate.py --candidate DIGEST
                                                 # judge one candidate
                                                 # bank digest vs the
                                                 # live quality history
    python scripts/quality_gate.py --candidate DIGEST --bank beta
                                                 # restrict to one
                                                 # bank id's records
    python scripts/quality_gate.py --list        # per-key quality
                                                 # history summaries
    python scripts/quality_gate.py --json        # machine-readable

A candidate's ``kind=quality`` records (appended by
``serve.quality.score_bank`` — shadow-replaying a captured segment
through the candidate offline) are judged against every OTHER
digest's records under the same ledger key — the quality the
currently-published banks actually served. The band is perf_gate's
robust-band math with the relative frac floor replaced by an
ABSOLUTE dB floor (``--db`` / ``CCSC_QUALITY_GATE_DB``): 25% of a
30 dB median is 7.5 dB, far past any regression worth catching.

Exit status: 0 = no regression (keys with live history thinner than
--min-history / CCSC_PERF_GATE_MIN_HISTORY pass trivially and are
reported as skipped — a young observatory starts gating as scores
accrue), 1 = the candidate fell below the live band on at least one
key, 2 = usage error (no such candidate in the ledger, unreadable
ledger).

This is the CI-runnable end of the quality observatory and the same
judgment ``ServeFleet.publish_bank(..., quality_check=True)`` (or
``CCSC_QUALITY_GATE=1``) applies inline before a hot-swap.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.analysis import ledger as ledger_mod  # noqa: E402
from ccsc_code_iccv2017_tpu.serve import quality as quality_mod  # noqa: E402


def _fmt_verdict(v) -> str:
    if v.get("skipped"):
        return (
            f"quality-gate: SKIP  {v['key']}  "
            f"({v.get('reason', 'insufficient history')}, "
            f"n={v.get('n_history', 0)})"
        )
    tag = "OK  " if v["ok"] else "REGRESSION"
    return (
        f"quality-gate: {tag}  {v['key']}  "
        f"{v['value']:.2f} dB ({v.get('delta_db', 0.0):+.2f} dB vs "
        f"live median {v['median']:.2f} dB, band lo "
        f"{v['lo']:.2f} dB, n={v['n_history']})"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--ledger", default=None,
        help="ledger JSONL path (default: CCSC_PERF_LEDGER, else "
        "the standard resolution — the ONE ledger perf_gate reads)",
    )
    ap.add_argument(
        "--candidate", default=None, metavar="DIGEST",
        help="candidate bank content digest (serve.registry."
        "bank_digest) to judge against the live quality history",
    )
    ap.add_argument(
        "--bank", default=None, metavar="BANK_ID",
        help="restrict judgment to records scored for one bank id "
        "(score_bank's knobs.bank; 'default' = the pinned bank)",
    )
    ap.add_argument(
        "--mad", type=float, default=None,
        help="band half-width in MAD-sigmas (CCSC_PERF_GATE_MAD, "
        "default 3.0)",
    )
    ap.add_argument(
        "--db", type=float, default=None,
        help="absolute dB floor of the band — a candidate more than "
        "this far below the live median regresses regardless of "
        "spread (CCSC_QUALITY_GATE_DB, default 1.0)",
    )
    ap.add_argument(
        "--min-history", type=int, default=None,
        help="live records a key needs before the candidate is "
        "judged (CCSC_PERF_GATE_MIN_HISTORY, default 3)",
    )
    ap.add_argument(
        "--list", action="store_true", dest="list_keys",
        help="print per-key quality history summaries and exit",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit verdicts as JSON",
    )
    args = ap.parse_args(argv)

    led = ledger_mod.Ledger(args.ledger)

    if args.list_keys:
        rows = []
        for key, recs in sorted(led.by_key().items()):
            recs = [
                r for r in recs if r.get("kind") == "quality"
            ]
            if not recs:
                continue
            band = quality_mod.quality_band(
                [r["value"] for r in recs],
                mad_k=args.mad, db=args.db,
            )
            digests = {}
            for r in recs:
                dg = r.get("digest") or "?"
                digests[dg] = digests.get(dg, 0) + 1
            rows.append(
                {
                    "key": key,
                    "n": len(recs),
                    "newest_db": recs[-1]["value"],
                    "median_db": band["median"] if band else None,
                    "lo_db": band["lo"] if band else None,
                    "digests": digests,
                }
            )
        if args.as_json:
            print(json.dumps(rows, indent=1))
        else:
            if not rows:
                print(
                    "quality-gate: no kind=quality records — score "
                    "a bank first (serve.quality.score_bank)"
                )
            for r in rows:
                dgs = ", ".join(
                    f"{dg[:12]}x{n}"
                    for dg, n in sorted(r["digests"].items())
                )
                print(
                    f"  {r['key']}\n"
                    f"    n={r['n']}  newest "
                    f"{r['newest_db']:.2f} dB  median "
                    f"{(r['median_db'] or 0.0):.2f} dB  band lo "
                    f"{(r['lo_db'] or 0.0):.2f} dB  [{dgs}]"
                )
        return 0

    if not args.candidate:
        print(
            "quality-gate: --candidate DIGEST is required "
            "(or --list)",
            file=sys.stderr,
        )
        return 2

    verdicts = quality_mod.judge_candidate(
        led,
        args.candidate,
        bank_id=args.bank,
        mad_k=args.mad,
        db=args.db,
        min_history=args.min_history,
    )
    if not verdicts:
        print(
            f"quality-gate: candidate {args.candidate} has no "
            f"kind=quality record in {led.path} — score it first "
            "(serve.quality.score_bank)",
            file=sys.stderr,
        )
        return 2
    judged = [v for v in verdicts if not v.get("skipped")]
    bad = [v for v in judged if not v["ok"]]
    skipped = [v for v in verdicts if v.get("skipped")]
    if args.as_json:
        print(
            json.dumps(
                {
                    "ledger": led.path,
                    "candidate": args.candidate,
                    "verdicts": verdicts,
                    "n_judged": len(judged),
                    "n_regressions": len(bad),
                    "n_skipped": len(skipped),
                },
                indent=1,
            )
        )
    else:
        for v in judged:
            print(_fmt_verdict(v))
        if skipped:
            print(
                f"quality-gate: {len(skipped)} key(s) skipped "
                "(live history too thin — they start gating as "
                "scores accrue)"
            )
        print(
            f"quality-gate: {len(judged)} judged, {len(bad)} "
            f"regression(s) ({led.path})"
        )
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
