#!/usr/bin/env python
"""Pick the fastest real-TPU arm from the NEWEST onchip_r*.jsonl that
holds any valid record, and persist its knobs as bench_tuned.json
(bench.py applies them automatically on TPU; env vars still override).
Requires a successful baseline to compare against; when the baseline
wins, any stale tuned file is removed. Older round files are never
mixed in — their arms ran older code on an older tunnel.

Single source of truth for knob defaults — the queue phases append
records, this script decides.

The same run also seeds the tuned-knob STORE (tune.store): every
valid record of the round lands as a ranked per-(chip, shape-bucket)
entry in tuned_knobs.json, which is what learners/engines started
with ``--tune auto`` and bench.py consult first — bench_tuned.json
is kept as the read-compat migration shim for the flat-file flow.
scripts/onchip_queue.sh re-picks after every measured arm, so both
artifacts stay current through a tunnel window.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TUNED = os.path.join(REPO, "bench_tuned.json")
# tuned STORE path; None = derive from REPO at runtime (tests patch
# REPO, and the store must follow it into the sandbox)
STORE = None

DEFAULTS = {
    "fft_pad": "none",
    "storage_dtype": "float32",
    "d_storage_dtype": "float32",
    "use_pallas": False,
    "fft_impl": "xla",
    "fused_z": False,
    "fused_z_precision": "highest",
    "herm_inv": "cholesky",
    # chunked/donated outer driver (r6): trajectory-exact execution
    # knobs, no accuracy-gate entry needed (tests/test_outer_chunk.py)
    "outer_chunk": 1,
    "donate_state": False,
}

# Accuracy gate (r5): the tuned default must stay in the "small
# perturbation" accuracy class PERF.md documents (bf16 storage, 0.4%),
# so a knob whose on-chip accuracy-probe record shows more than
# ACC_BOUND objective-trajectory deviation is ineligible for the
# DEFAULT config — it remains measurable as an explicit env-var arm.
# (r5 evidence: fft_impl='matmul_bf16' bought 8% speed at 2.6%
# deviation vs 8.6e-7 for 'matmul'; speed alone must not pick it.)
# Knobs without a record pass — the gate is evidence-driven, and the
# accuracy phase runs right after the arms phase in the same queue.
ACC_BOUND = 0.01
KNOB_TO_CONFIG = {
    ("fft_impl", "matmul"): "matmul",
    ("fft_impl", "matmul_high"): "matmul_high",
    ("fft_impl", "matmul_bf16"): "matmul_bf16prec",
    ("storage_dtype", "bfloat16"): "bf16_storage",
    ("d_storage_dtype", "bfloat16"): "d_bf16_storage",
    ("fused_z", True): "fused_z",
    ("fused_z_precision", "high"): "fused_z_high",
    ("fused_z_precision", "default"): "fused_z_default",
    ("herm_inv", "schur"): "herm_schur",
}


def _accuracy_devs(path):
    devs = {}
    for line in open(path):
        try:
            rec = json.loads(line)
        except Exception:
            continue
        if rec.get("config") and "max_rel_obj_dev_vs_ref" in rec:
            devs[rec["config"]] = float(rec["max_rel_obj_dev_vs_ref"])
    return devs


def _accuracy_ok(knobs, devs):
    """True unless some non-default knob has a measured deviation
    record above ACC_BOUND (per-knob gate; combo records are strictly
    more pessimistic only for same-sign drifts, and every shipped combo
    is also probed individually)."""
    for key, val in knobs.items():
        if val == DEFAULTS.get(key):
            continue
        dev = devs.get(KNOB_TO_CONFIG.get((key, val), ""))
        if dev is not None and dev > ACC_BOUND:
            return False
    return True


def _valid_runs(path):
    for line in open(path):
        try:
            rec = json.loads(line)
        except Exception:
            continue
        res = rec.get("result") or {}
        v = float(res.get("value", 0.0))
        if not rec.get("run") or "DEGRADED" in res.get("metric", "") \
                or v <= 0:
            continue
        # the serving arms (CCSC_BENCH_SERVE) measure requests/sec of
        # a DIFFERENT workload with serve-specific knobs — they must
        # never win the learner-knob pick (records without a unit
        # field predate the serving arm and are all north-star runs)
        if res.get("unit", "outer_iters/sec") != "outer_iters/sec":
            continue
        yield rec["run"], v, res.get("knobs") or {}


def _seed_store(current_round):
    """Mirror the round's valid arms into the tuned-knob store
    (tune.store — the per-(chip, shape-bucket) ranking that --tune
    auto and bench.py read). Best-effort: a record whose metric does
    not name the north-star shape, or an unimportable package, must
    not fail the flat-file pick this script has always done."""
    try:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        from ccsc_code_iccv2017_tpu.tune import store as ts

        store = ts.TunedStore(
            STORE or os.path.join(REPO, "tuned_knobs.json")
        )
        n = ts.seed_from_onchip(store, current_round)
        if n:
            store.save()
        print(f"tuned store: {n} arm(s) recorded -> {store.path}")
    except Exception as e:  # pragma: no cover - defensive
        print(f"tuned store update skipped: {e}")


def main():
    import glob

    # ONLY the newest round file with any valid record: arms measured
    # by an older round ran older code on an older tunnel and must not
    # contaminate the pick (each round's queue measures its own
    # baseline first, so the newest file is self-contained).
    # mtime order, not lexicographic ('onchip_r10' would sort before
    # 'onchip_r4'); matches bench.py's last_onchip_record ordering
    files = sorted(
        glob.glob(os.path.join(REPO, "onchip_r*.jsonl")),
        key=os.path.getmtime,
    )
    current = None
    for path in reversed(files):
        if any(True for _ in _valid_runs(path)):
            current = path
            break
    if current is None:
        # no records (fresh checkout / rotated files): defaults
        if os.path.exists(TUNED):
            os.remove(TUNED)
        print("tuned: defaults (no records)")
        return 0
    _seed_store(current)
    devs = _accuracy_devs(current)
    best, best_v, best_k, base_v = None, -1.0, {}, None
    for run, v, knobs in _valid_runs(current):
        if run == "baseline":
            base_v = v if base_v is None else max(base_v, v)
        if not _accuracy_ok(knobs, devs):
            print(f"tuned: skipping {run}@{v} (accuracy gate)")
            continue
        if v > best_v:
            best, best_v, best_k = run, v, knobs
    # herm_inv is never stripped: since the library's unset default
    # became platform/size-aware ('auto' -> schur on TPU in the
    # measured window), omitting 'cholesky' from the tuned file would
    # make bench.py execute a different Gram-inverse path than the arm
    # that was measured (bench records are authoritative for what ran)
    tuned = {
        k: v
        for k, v in best_k.items()
        if k == "herm_inv" or v != DEFAULTS.get(k)
    }
    if base_v is None or best in (None, "baseline") or best_v <= base_v \
            or not tuned:
        if os.path.exists(TUNED):
            os.remove(TUNED)
        print(f"tuned: defaults (baseline={base_v}, best={best}@{best_v})")
        return 0
    with open(TUNED, "w") as f:
        json.dump(tuned, f)
    print(f"tuned: {best} @ {best_v} it/s -> {tuned}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
