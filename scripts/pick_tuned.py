#!/usr/bin/env python
"""Pick the fastest real-TPU arm from the NEWEST onchip_r*.jsonl that
holds any valid record, and persist its knobs as bench_tuned.json
(bench.py applies them automatically on TPU; env vars still override).
Requires a successful baseline to compare against; when the baseline
wins, any stale tuned file is removed. Older round files are never
mixed in — their arms ran older code on an older tunnel.

Single source of truth for knob defaults — the queue phases append
records, this script decides.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TUNED = os.path.join(REPO, "bench_tuned.json")

DEFAULTS = {
    "fft_pad": "none",
    "storage_dtype": "float32",
    "d_storage_dtype": "float32",
    "use_pallas": False,
    "fft_impl": "xla",
    "fused_z": False,
}


def _valid_runs(path):
    for line in open(path):
        try:
            rec = json.loads(line)
        except Exception:
            continue
        res = rec.get("result") or {}
        v = float(res.get("value", 0.0))
        if not rec.get("run") or "DEGRADED" in res.get("metric", "") \
                or v <= 0:
            continue
        yield rec["run"], v, res.get("knobs") or {}


def main():
    import glob

    # ONLY the newest round file with any valid record: arms measured
    # by an older round ran older code on an older tunnel and must not
    # contaminate the pick (each round's queue measures its own
    # baseline first, so the newest file is self-contained).
    # mtime order, not lexicographic ('onchip_r10' would sort before
    # 'onchip_r4'); matches bench.py's last_onchip_record ordering
    files = sorted(
        glob.glob(os.path.join(REPO, "onchip_r*.jsonl")),
        key=os.path.getmtime,
    )
    current = None
    for path in reversed(files):
        if any(True for _ in _valid_runs(path)):
            current = path
            break
    if current is None:
        # no records (fresh checkout / rotated files): defaults
        if os.path.exists(TUNED):
            os.remove(TUNED)
        print("tuned: defaults (no records)")
        return 0
    best, best_v, best_k, base_v = None, -1.0, {}, None
    for run, v, knobs in _valid_runs(current):
        if run == "baseline":
            base_v = v if base_v is None else max(base_v, v)
        if v > best_v:
            best, best_v, best_k = run, v, knobs
    tuned = {k: v for k, v in best_k.items() if v != DEFAULTS.get(k)}
    if base_v is None or best in (None, "baseline") or best_v <= base_v \
            or not tuned:
        if os.path.exists(TUNED):
            os.remove(TUNED)
        print(f"tuned: defaults (baseline={base_v}, best={best}@{best_v})")
        return 0
    with open(TUNED, "w") as f:
        json.dump(tuned, f)
    print(f"tuned: {best} @ {best_v} it/s -> {tuned}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
