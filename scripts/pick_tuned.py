#!/usr/bin/env python
"""Pick the fastest real-TPU arm from onchip_r4.jsonl and persist its
knobs as bench_tuned.json (bench.py applies them automatically on TPU;
env vars still override). Requires a successful baseline to compare
against; when the baseline wins, any stale tuned file is removed.

Single source of truth for knob defaults — the queue phases append
records, this script decides.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "onchip_r4.jsonl")
TUNED = os.path.join(REPO, "bench_tuned.json")

DEFAULTS = {
    "fft_pad": "none",
    "storage_dtype": "float32",
    "d_storage_dtype": "float32",
    "use_pallas": False,
    "fft_impl": "xla",
    "fused_z": False,
}


def main():
    best, best_v, best_k, base_v = None, -1.0, {}, None
    if not os.path.exists(OUT):
        # no records (fresh checkout / rotated file): defaults
        if os.path.exists(TUNED):
            os.remove(TUNED)
        print("tuned: defaults (no records)")
        return 0
    for line in open(OUT):
        try:
            rec = json.loads(line)
        except Exception:
            continue
        res = rec.get("result") or {}
        metric = res.get("metric", "")
        v = float(res.get("value", 0.0))
        if not rec.get("run") or "DEGRADED" in metric or v <= 0:
            continue
        if rec["run"] == "baseline":
            base_v = v if base_v is None else max(base_v, v)
        if v > best_v:
            best, best_v, best_k = rec["run"], v, res.get("knobs") or {}
    tuned = {k: v for k, v in best_k.items() if v != DEFAULTS.get(k)}
    if base_v is None or best in (None, "baseline") or best_v <= base_v \
            or not tuned:
        if os.path.exists(TUNED):
            os.remove(TUNED)
        print(f"tuned: defaults (baseline={base_v}, best={best}@{best_v})")
        return 0
    with open(TUNED, "w") as f:
        json.dump(tuned, f)
    print(f"tuned: {best} @ {best_v} it/s -> {tuned}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
