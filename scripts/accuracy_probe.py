#!/usr/bin/env python
"""On-chip trajectory-accuracy probe for the execution-strategy knobs.

CPU tests can bound fft_impl='matmul' (exact-precision matmuls) but
NOT 'matmul_bf16' (DEFAULT precision truncates to bf16 only on the
MXU) or the real-mosaic fused_z kernel (interpret mode runs f32).
This probe runs one small-but-representative consensus learn per
config ON THE CHIP with a fixed seed and reports each config's
objective-trajectory deviation from the f32 jnp.fft reference —
the accuracy half of the PERF.md knob table.

Prints one JSON line per config plus the reference.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax
import jax.numpy as jnp
import numpy as np

from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn


def main():
    n = int(os.environ.get("AP_N", 16))
    size = int(os.environ.get("AP_SIZE", 48))
    k = int(os.environ.get("AP_K", 16))
    outers = int(os.environ.get("AP_ITERS", 5))
    rng = np.random.default_rng(0)
    b = jnp.asarray(rng.standard_normal((n, size, size)).astype(np.float32))
    geom = ProblemGeom((11, 11), k)
    base = dict(
        max_it=outers, max_it_d=5, max_it_z=10, num_blocks=2,
        rho_d=5000.0, rho_z=1.0, verbose="none", track_objective=True,
    )
    configs = {
        "reference_xla_f32": {},
        "matmul": {"fft_impl": "matmul"},
        "matmul_bf16prec": {"fft_impl": "matmul_bf16"},
        "bf16_storage": {"storage_dtype": "bfloat16"},
        "d_bf16_storage": {"d_storage_dtype": "bfloat16"},
        "fused_z": {"fused_z": True},
        "fused_z_bf16": {"fused_z": True, "storage_dtype": "bfloat16"},
        "fused_z_bf16_all": {
            "fused_z": True,
            "storage_dtype": "bfloat16",
            "d_storage_dtype": "bfloat16",
        },
        "matmul_high": {"fft_impl": "matmul_high"},
        "fused_z_high": {"fused_z": True, "fused_z_precision": "high"},
        "fused_z_default": {
            "fused_z": True, "fused_z_precision": "default",
        },
        # env-level switch (trace-time), not a LearnConfig field
        "herm_schur": {"_env": {"CCSC_HERM_INV": "schur"}},
    }
    ref = None
    for name, kw in configs.items():
        kw = dict(kw)
        env = kw.pop("_env", {})
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            res = learn(
                b, geom, LearnConfig(**base, **kw),
                key=jax.random.PRNGKey(0),
            )
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        obj = np.asarray(res.trace["obj_vals_z"], np.float64)
        row = {"config": name, "obj_final": float(obj[-1]),
               "platform": jax.devices()[0].platform}
        if ref is None:
            ref = obj
        else:
            m = min(len(ref), len(obj))
            row["max_rel_obj_dev_vs_ref"] = float(
                np.max(np.abs(obj[:m] - ref[:m]) / np.abs(ref[:m]))
            )
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
