#!/bin/bash
# Round-4 on-chip queue, phase 3: items stranded by the tunnel wedge
# during the phase-1 profile arm (the profile itself — now fixed to
# pass zkern as an argument instead of a jit-captured constant — plus
# the dispatch-overhead probe and the matmul_bf16 precision arm).
#
# Arms are read from scripts/onchip_arms.txt (one "label env..." per
# line) so later work can append arms without touching a running
# script. Waits for any other queue phase to exit first (single-client
# tunnel).
set -u
cd "$(dirname "$0")/.."
OUT=onchip_r4.jsonl
LOG=/tmp/onchip_queue3.log
ARMS=scripts/onchip_arms.txt

probe() {
  timeout 60 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
x = jnp.ones((128, 128)); float((x @ x).sum())
" > /dev/null 2>&1
}

note() { echo "{\"note\": \"$1\", \"at\": \"$(date +%H:%M:%S)\"}" >> "$OUT"; }

run_bench() { # label, env pairs...
  local label=$1; shift
  echo "=== $label $(date +%H:%M:%S)" >> "$LOG"
  local line
  line=$(env "$@" CCSC_BENCH_TIMEOUT=2000 timeout 4000 python bench.py 2>> "$LOG" | tail -1)
  if [ -n "$line" ] && echo "$line" | python -c \
      'import json,sys; json.load(sys.stdin)' > /dev/null 2>&1; then
    echo "{\"run\": \"$label\", \"result\": $line}" >> "$OUT"
  else
    note "$label FAILED/empty"
  fi
}

while pgrep -f "scripts/onchip_queue.sh|scripts/onchip_queue2.sh" \
    | grep -qv $$ 2>/dev/null; do
  echo "$(date +%H:%M:%S) earlier phase still running" >> "$LOG"
  sleep 120
done

while true; do
  if probe; then
    note "phase 3 start"
    if [ -f "$ARMS" ]; then
      while read -r label envs; do
        [ -z "$label" ] && continue
        case "$label" in \#*) continue ;; esac
        # shellcheck disable=SC2086
        run_bench "$label" $envs
      done < "$ARMS"
    fi
    echo "=== dispatch_probe $(date +%H:%M:%S)" >> "$LOG"
    timeout 1200 python scripts/dispatch_probe.py >> "$OUT" 2>> "$LOG" \
      || note "dispatch_probe FAILED"
    note "phase 3 complete"
    break
  fi
  echo "$(date +%H:%M:%S) tunnel down" >> "$LOG"
  sleep 240
done
