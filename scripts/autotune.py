#!/usr/bin/env python
"""On-chip knob autotuner CLI: sweep, seed, inspect, validate.

Modes (one per invocation):

--dry-run          Validate the candidate arm space WITHOUT a chip:
                   enumerate every arm for both kinds, apply each to a
                   default config (constructor validation), and print
                   the table + store path + code fingerprint. No
                   backend is initialized and no workload runs — safe
                   on any CI host.
--seed-from PATH   Seed the tuned store from an on-chip bench round
                   file (onchip_r*.jsonl): every real-chip learner
                   record becomes a ranked store entry keyed by its
                   ACTUAL chip. DEGRADED/FAILED rows are refused.
--list             Print the store's entries (chip/kind/shape ranked
                   arms, guard verdicts, demotions).
--sweep KIND       Time the candidate arms on the ACTUAL chip at the
                   given shape (learn: --n/--size/--k/--support/
                   --blocks; solve: --size/--k/--support) and persist
                   the ranking. This is what LearnConfig/ServeConfig
                   tune='sweep' runs at startup, as a standalone tool.

After a sweep or seed, any learner/engine started with ``--tune auto``
on the same chip + shape bucket picks the fastest recorded arm behind
the numerics guard. Store path: --store > CCSC_TUNE_STORE >
$CCSC_COMPILE_CACHE/ccsc_tuned_knobs.json > repo tuned_knobs.json.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_parser():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--store", default=None, help="tuned store path")
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument(
        "--dry-run", action="store_true",
        help="validate the arm space without a chip (no jax import)",
    )
    mode.add_argument(
        "--seed-from", default=None, metavar="JSONL",
        help="seed the store from an onchip_r*.jsonl round file",
    )
    mode.add_argument(
        "--list", action="store_true", help="print the store contents"
    )
    mode.add_argument(
        "--sweep", default=None, choices=["learn", "solve"],
        help="time the candidate arms on the actual chip",
    )
    p.add_argument("--workload", default=None,
                   help="workload token (default consensus2d / solve2d)")
    p.add_argument("--n", type=int, default=16, help="sweep: images")
    p.add_argument("--size", type=int, default=32,
                   help="sweep: spatial side")
    p.add_argument("--k", type=int, default=16, help="sweep: filters")
    p.add_argument("--support", type=int, default=7,
                   help="sweep: filter support")
    p.add_argument("--blocks", type=int, default=2,
                   help="sweep(learn): consensus blocks")
    p.add_argument("--iters", type=int, default=2,
                   help="sweep: timed iterations/solves per arm")
    return p


def _dry_run():
    # pure-python validation: no backend init, no device, no workload
    import dataclasses

    from ccsc_code_iccv2017_tpu import config
    from ccsc_code_iccv2017_tpu.tune import space, store as ts

    n_bad = 0
    for kind, cls, workload in (
        ("learn", config.LearnConfig, "consensus2d"),
        ("solve", config.SolveConfig, "solve2d"),
    ):
        unclassified, missing = space.classify_drift(kind, cls)
        if unclassified or missing:
            print(
                f"DRIFT in {kind}: unclassified fields "
                f"{sorted(unclassified)}, declared-but-missing "
                f"{sorted(missing)}"
            )
            n_bad += 1
        arms = space.default_arms(kind, workload)
        print(f"{kind} ({workload}): {len(arms)} candidate arms")
        cfg = cls() if kind == "learn" else cls()
        for arm in arms:
            armed, env, dropped = space.apply_arm(
                cfg, arm, kind, workload
            )
            dataclasses.asdict(armed)  # constructor already validated
            note = f" env={env}" if env else ""
            note += f" dropped={dropped}" if dropped else ""
            print(f"  {space.arm_label(arm)}{note}")
    print(f"code fingerprint: {space.code_fingerprint()}")
    print(f"store path: {ts.default_store_path()}")
    if n_bad:
        print("DRY RUN FAILED: knob space drift detected")
    return 1 if n_bad else 0


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.dry_run:
        return _dry_run()

    from ccsc_code_iccv2017_tpu.tune import store as ts

    store = ts.TunedStore(args.store)
    if args.seed_from:
        n = ts.seed_from_onchip(
            store, args.seed_from,
            workload=args.workload or "consensus2d",
        )
        store.save()
        print(f"seeded {n} arm(s) from {args.seed_from} -> {store.path}")
        return 0
    if args.list:
        data = store._data
        if not data:
            print(f"(store empty: {store.path})")
            return 0
        for key in sorted(data):
            print(key)
            for e in data[key]:
                flags = []
                if e.get("demoted"):
                    flags.append(
                        f"DEMOTED({e.get('demote_reason', '')})"
                    )
                g = e.get("guard")
                if g:
                    flags.append(
                        f"guard={'ok' if g.get('ok') else 'FAIL'}"
                        f"@{g.get('dev'):.3g}"
                    )
                print(
                    f"  {e.get('value'):>10.4g} {e.get('unit'):<16} "
                    f"[{json.dumps(e.get('arm'))}] "
                    f"{e.get('source', '')} {' '.join(flags)}"
                )
        return 0

    # ---- sweep on the actual chip -----------------------------------
    from ccsc_code_iccv2017_tpu.utils.platform import (
        honor_jax_platforms_env,
    )

    honor_jax_platforms_env()
    from ccsc_code_iccv2017_tpu.config import (
        LearnConfig, ProblemGeom, SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.tune import autotune

    def emit(type_, **fields):
        print(json.dumps({"type": type_, **fields}))

    if args.sweep == "learn":
        geom = ProblemGeom((args.support, args.support), args.k)
        cfg = LearnConfig(num_blocks=args.blocks, verbose="none")
        autotune.sweep_learn(
            cfg, geom, (args.n, args.size, args.size),
            workload=args.workload or "consensus2d",
            store=store, emit=emit, iters=args.iters,
        )
    else:
        geom = ProblemGeom((args.support, args.support), args.k)
        cfg = SolveConfig(
            max_it=max(args.iters * 5, 10), verbose="none"
        )
        autotune.sweep_solve(
            cfg, geom, (args.size, args.size),
            workload=args.workload or "solve2d",
            store=store, emit=emit, reps=args.iters,
        )
    print(f"sweep recorded -> {store.path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
