#!/bin/bash
# Round-4 on-chip queue, phase 5: after every measurement phase has
# exited, re-pick bench_tuned.json over ALL recorded arms with the
# full knob vocabulary (scripts/pick_tuned.py) so the driver's
# end-of-round bench run adopts the measured winner.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/onchip_queue5.log

while pgrep -f "scripts/onchip_queue[1-4]?\.sh" | grep -qv $$ 2>/dev/null; do
  echo "$(date +%H:%M:%S) earlier phase still running" >> "$LOG"
  sleep 180
done
python scripts/pick_tuned.py >> "$LOG" 2>&1
echo "$(date +%H:%M:%S) final pick done" >> "$LOG"
