#!/usr/bin/env python
"""Chaos smoke: every injectable fault point exercised end-to-end on a
tiny 2D learn, on CPU, in under a minute — the CI proof that the
resilience layer (utils.resilience / utils.faults / hardened
utils.checkpoint) actually recovers, not just compiles.

Scenarios (each sets its fault via the CCSC_FAULT_* env points and
restores them):

  nan_recovery        injected NaN at iteration 2 -> rho-backoff retry,
                      run completes, trace records the recovery
  nan_recovery_chunk  same, inside an outer_chunk=2 scan (recovery at
                      the readback fence)
  nan_stop_default    recovery disabled -> historical stop-and-keep
  ckpt_save_crash     raise mid-checkpoint.save -> previous snapshot
                      generation intact and loadable
  corrupt_fallback    torn newest snapshot -> resume from the previous
                      rotation
  sigterm_checkpoint  SIGTERM at iteration 1 -> clean checkpoint-and-
                      exit at the boundary, checkpoint resumable
  hang_watchdog       injected hang at iteration 2 (CCSC_FAULT_HANG_IT,
                      sleeping inside the fence) -> the dispatch
                      watchdog (utils.watchdog, event mode) records a
                      `stall` event and the run still completes
  fleet_kill          a serving-fleet replica is killed mid-stream
                      (CCSC_FAULT_ENGINE_KILL_REQ, serve.ServeFleet):
                      its requests are requeued onto the survivor,
                      every request completes exactly once, and the
                      casualty's restart is visible in the obs stream
  replay_parity       a stream served UNDER kill/hang faults with
                      workload capture on (serve.capture) is replayed
                      at max speed against a clean fleet
                      (serve.replay): zero lost requests and every
                      replayed result bit-identical to its recorded
                      outcome — faults must not leak into the served
                      bytes, and the capture must be a faithful
                      oracle
  bank_swap           zero-downtime hot-swap under fire: a 2-replica
                      fleet serves sustained two-tenant traffic while
                      one tenant's bank is republished under a new
                      digest (serve.registry) AND a replica kill
                      fault fires mid-swap — zero lost requests, the
                      cutover visible as a bank_swap event with both
                      digests, pre-swap results bit-identical to a
                      fresh old-bank engine and post-swap results to
                      a fresh new-bank engine
  bank_rot            (script mode only) quality-observatory chaos: a
                      DEGRADED bank (atoms collapsed to one blur) is
                      hot-swapped under two-tenant traffic — the
                      golden probes flag the rot digest within ~one
                      probe interval (quality_probe_breach), the
                      drift watch flags the served-dB excursion vs
                      the seeded ledger history (quality_drift), the
                      demotion advisory names the prior digest and
                      acting on it swaps the good bank back; zero
                      lost requests, pre/post results bit-identical
                      to fresh engines, zero new XLA compiles
  host_kill           (script mode only) whole-host chaos: 2 federated
                      fleet PROCESSES drain a shared file-lease queue
                      (serve.dqueue / serve.federation); one is
                      SIGKILLed mid-stream while holding leases. The
                      survivor's reaper requeues the dead host's
                      leases, the stream finishes with zero lost
                      requests, and every delivered result is
                      bit-identical to the capture oracle's recorded
                      outcome digests (serve.capture)
  scale_up            (script mode only) pre-warmed elasticity under
                      fire: while a saturating request stream drains
                      through one federated host, a SECOND host joins
                      mid-stream from a warm compiled-artifact store
                      (serve.artifacts) with staged warmup on — its
                      hot bucket is FETCHED (not compiled), it serves
                      its first request before its coldest bucket
                      finishes building in the background, p99 stays
                      bounded, and zero requests are lost
  autoscale           (script mode only) self-driving capacity under
                      a diurnal replay (serve.controller): the
                      controller browns out and grows 1 -> 2 at the
                      peak (the grown replica warming FROM the
                      artifact store), shrinks back at the trough —
                      zero lost, bounded p99 — then an injected
                      sensor blackout holds capacity (never a blind
                      scale-down) and a wedged actuator opens the
                      circuit breaker while the queue still drains
  sigterm_subprocess  (script mode only) the same against a real child
                      process: exit code 0 + valid checkpoint
  supervise_restart   (script mode only) scripts/supervise.py restarts
                      a SIGTERM'd child from its checkpoint and the
                      supervised run completes (trace: preempted ->
                      completed, fault fire-once across restarts)

Usage:
    JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

Exit code 0 iff every scenario passed. tests/test_resilience.py runs
``run(subprocess_scenarios=False)`` on every verify pass.

Static companion: ``python scripts/lint.py`` proves the source-level
discipline the same subsystems depend on (jit purity, donation
safety, lock ordering, obs schema, the CCSC_* env registry) —
chaos proves the runtime paths, lint proves the code shape; CI runs
both (tests/test_resilience.py + tests/test_analysis.py).
"""
from __future__ import annotations

import contextlib
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


@contextlib.contextmanager
def _fault(**env):
    from ccsc_code_iccv2017_tpu.utils import faults

    old = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    faults.reset()
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        faults.reset()


def _tiny_problem():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom

    b = jnp.asarray(
        np.asarray(
            jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)),
            np.float32,
        )
    )
    geom = ProblemGeom((3, 3), 4)

    def cfg(**kw):
        base = dict(
            max_it=3, max_it_d=2, max_it_z=2, num_blocks=2,
            rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none",
            track_objective=True,
        )
        base.update(kw)
        return LearnConfig(**base)

    return b, geom, cfg


def scenario_nan_recovery():
    import jax
    import numpy as np

    from ccsc_code_iccv2017_tpu.models.learn import learn

    b, geom, cfg = _tiny_problem()
    with _fault(CCSC_FAULT_NAN_IT=2):
        res = learn(b, geom, cfg(max_recoveries=1),
                    key=jax.random.PRNGKey(0))
    recs = res.trace.get("recoveries", [])
    ok = (
        len(recs) == 1
        and recs[0]["iteration"] == 2
        and len(res.trace["obj_vals_z"]) == 4
        and bool(np.isfinite(res.trace["obj_vals_z"]).all())
    )
    return ok, f"recoveries={recs}, trace_len={len(res.trace['obj_vals_z'])}"


def scenario_nan_recovery_chunk():
    import jax
    import numpy as np

    from ccsc_code_iccv2017_tpu.models.learn import learn

    b, geom, cfg = _tiny_problem()
    with _fault(CCSC_FAULT_NAN_IT=2):
        res = learn(b, geom, cfg(max_recoveries=1, outer_chunk=2),
                    key=jax.random.PRNGKey(0))
    recs = res.trace.get("recoveries", [])
    ok = (
        len(recs) == 1
        and len(res.trace["obj_vals_z"]) == 4
        and bool(np.isfinite(res.trace["obj_vals_z"]).all())
    )
    return ok, f"recoveries={recs}, trace_len={len(res.trace['obj_vals_z'])}"


def scenario_nan_stop_default():
    import jax

    from ccsc_code_iccv2017_tpu.models.learn import learn

    b, geom, cfg = _tiny_problem()
    with _fault(CCSC_FAULT_NAN_IT=2):
        res = learn(b, geom, cfg(), key=jax.random.PRNGKey(0))
    ok = (
        "recoveries" not in res.trace
        and len(res.trace["obj_vals_z"]) == 2  # obj0 + iteration 1
    )
    return ok, f"trace_len={len(res.trace['obj_vals_z'])}"


def scenario_ckpt_save_crash():
    from collections import namedtuple

    import numpy as np

    from ccsc_code_iccv2017_tpu.utils import checkpoint as ckpt
    from ccsc_code_iccv2017_tpu.utils import faults

    St = namedtuple("St", ["a"])
    with tempfile.TemporaryDirectory() as d:
        ckpt.save(d, St(np.ones(3)), {"x": [1]}, 1, fingerprint="fp")
        crashed = False
        with _fault(CCSC_FAULT_CKPT_SAVE=1):
            try:
                ckpt.save(d, St(np.full(3, 9.0)), {"x": [1, 2]}, 2,
                          fingerprint="fp")
            except faults.InjectedFault:
                crashed = True
        fields, trace, it = ckpt.load(d, expect_fingerprint="fp")
        ok = crashed and it == 1 and trace == {"x": [1]} and bool(
            (fields["a"] == 1.0).all()
        )
    return ok, f"crashed={crashed}, resumed_it={it}"


def scenario_corrupt_fallback():
    import warnings
    from collections import namedtuple

    import numpy as np

    from ccsc_code_iccv2017_tpu.utils import checkpoint as ckpt

    St = namedtuple("St", ["a"])
    with tempfile.TemporaryDirectory() as d:
        ckpt.save(d, St(np.ones(3)), {"x": [1]}, 1)
        ckpt.save(d, St(np.full(3, 2.0)), {"x": [1, 2]}, 2)
        with open(os.path.join(d, "ccsc_state.npz"), "r+b") as fh:
            fh.truncate(10)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            fields, trace, it = ckpt.load(d)
        ok = it == 1 and bool((fields["a"] == 1.0).all())
    return ok, f"resumed_it={it}"


def scenario_sigterm_checkpoint():
    import jax

    from ccsc_code_iccv2017_tpu.models.learn import learn
    from ccsc_code_iccv2017_tpu.utils import checkpoint as ckpt

    b, geom, cfg = _tiny_problem()
    with tempfile.TemporaryDirectory() as d:
        with _fault(CCSC_FAULT_SIGTERM_IT=1):
            res = learn(b, geom, cfg(), key=jax.random.PRNGKey(0),
                        checkpoint_dir=d, checkpoint_every=1)
        snap = ckpt.load(d)
        ok = (
            res.trace.get("preemptions") == [1]
            and snap is not None
            and snap[2] == 1
        )
    return ok, f"preemptions={res.trace.get('preemptions')}"


def scenario_hang_watchdog():
    import jax

    from ccsc_code_iccv2017_tpu.models.learn import learn
    from ccsc_code_iccv2017_tpu.utils import obs

    b, geom, cfg = _tiny_problem()
    with tempfile.TemporaryDirectory() as mdir:
        with _fault(
            CCSC_FAULT_HANG_IT=2,
            CCSC_FAULT_HANG_S="1.5",
            CCSC_WATCHDOG_ACTION="event",
            CCSC_WATCHDOG_MIN_S="0.5",
            CCSC_WATCHDOG_COMPILE_S="120",
        ):
            res = learn(
                b, geom, cfg(watchdog=True, metrics_dir=mdir),
                key=jax.random.PRNGKey(0),
            )
        events = obs.read_events(mdir)
        stalls = [e for e in events if e["type"] == "stall"]
        fired = [e for e in events if e["type"] == "fault_fired"]
        ok = (
            len(stalls) >= 1
            and any(f.get("fault") == "hang" for f in fired)
            and len(res.trace["obj_vals_z"]) == 4  # run completed
        )
    return ok, f"stalls={len(stalls)}, trace_len={len(res.trace['obj_vals_z'])}"


def scenario_fleet_kill():
    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet
    from ccsc_code_iccv2017_tpu.utils import obs

    r = np.random.default_rng(0)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    geom = ProblemGeom((3, 3), 4)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none",
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    with tempfile.TemporaryDirectory() as mdir:
        with _fault(
            CCSC_FAULT_ENGINE_KILL_REQ=2,
            CCSC_FAULT_ENGINE_KILL_REPLICA="0",
        ):
            fleet = ServeFleet(
                d, ReconstructionProblem(geom), cfg, scfg,
                FleetConfig(
                    replicas=2, metrics_dir=mdir, min_queue_depth=64,
                    restart_backoff_s=0.05, verbose="none",
                ),
            )
            futs = []
            for i in range(8):
                x = r.random((12, 12)).astype(np.float32)
                m = (r.random((12, 12)) < 0.5).astype(np.float32)
                futs.append(fleet.submit(x * m, mask=m, key=f"k{i}"))
            results = [f.result(timeout=180) for f in futs]
            fleet.close()
        events = obs.read_events(mdir)
        dead = [e for e in events if e["type"] == "fleet_replica_dead"]
        served = [e for e in events if e["type"] == "fleet_request"]
        keys = [e["key"] for e in served]
        ok = (
            len(results) == 8
            and len(dead) == 1
            and dead[0]["replica_id"] == 0
            and sorted(keys) == sorted(f"k{i}" for i in range(8))
            and len(keys) == len(set(keys))  # exactly once each
            and any(e["type"] == "fleet_requeue" for e in events)
        )
    return ok, (
        f"served={len(results)}, dead={len(dead)}, "
        f"unique_keys={len(set(keys))}/8"
    )


def scenario_replay_parity():
    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet
    from ccsc_code_iccv2017_tpu.serve.replay import ReplayDriver

    r = np.random.default_rng(0)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    geom = ProblemGeom((3, 3), 4)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_psnr=True, track_objective=True,
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )

    def fleet_cfg(mdir, cap=None):
        return FleetConfig(
            replicas=2, metrics_dir=mdir, capture_dir=cap,
            min_queue_depth=64, restart_backoff_s=0.05,
            verbose="none",
        )

    with tempfile.TemporaryDirectory() as root:
        cap = os.path.join(root, "capture")
        # serve under a mid-stream replica kill, capture armed
        with _fault(
            CCSC_FAULT_ENGINE_KILL_REQ=2,
            CCSC_FAULT_ENGINE_KILL_REPLICA="0",
        ):
            fleet = ServeFleet(
                d, ReconstructionProblem(geom), cfg, scfg,
                fleet_cfg(os.path.join(root, "m-serve"), cap),
            )
            futs = []
            for i in range(8):
                x = r.random((12, 12)).astype(np.float32)
                m = (r.random((12, 12)) < 0.5).astype(np.float32)
                futs.append(
                    fleet.submit(x * m, mask=m, x_orig=x, key=f"k{i}")
                )
            n_served = len([f.result(timeout=180) for f in futs])
            fleet.close()
        # replay at max speed against a CLEAN fleet ("" = capture
        # explicitly off even if CCSC_CAPTURE_DIR is armed globally)
        fresh = ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            fleet_cfg(os.path.join(root, "m-replay"), cap=""),
        )
        try:
            rep = ReplayDriver(
                cap, metrics_dir=os.path.join(root, "m-replay")
            ).replay(fresh, speed=0.0, mode="open")
        finally:
            fresh.close()
        ok = (
            n_served == 8
            and rep["n_replayed"] == 8
            and rep["n_lost"] == 0
            and rep["n_mismatched"] == 0
            and rep["n_exact"] == 8
        )
    return ok, (
        f"served={n_served}, replayed={rep['n_replayed']}, "
        f"exact={rep['n_exact']}, lost={rep['n_lost']}, "
        f"mismatched={rep['n_mismatched']}"
    )


def scenario_bank_swap():
    """Zero-downtime hot-swap under fire: a 2-replica fleet serves
    sustained two-tenant traffic; mid-stream, tenant beta's bank is
    republished under a new digest WHILE a replica kill fault fires.
    Must hold: zero lost requests, the cutover visible as a
    fleet-scope ``bank_swap`` with both digests, every pre-swap beta
    result bit-identical to a fresh old-bank engine, every post-swap
    beta result bit-identical to a fresh new-bank engine, and tenant
    alpha's results untouched throughout."""
    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
        TenantSpec,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import CodecEngine, ServeFleet
    from ccsc_code_iccv2017_tpu.utils import obs

    def bank(seed):
        r = np.random.default_rng(seed)
        d = r.normal(size=(4, 3, 3)).astype(np.float32)
        d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
        return d

    d_alpha, d_beta0, d_beta1 = bank(0), bank(1), bank(2)
    geom = ProblemGeom((3, 3), 4)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_objective=True,
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    tenants = (
        TenantSpec(tenant="alpha", bank_id="bank-alpha"),
        TenantSpec(tenant="beta", bank_id="bank-beta"),
    )
    r = np.random.default_rng(3)
    reqs = []
    for _ in range(8):
        x = r.random((12, 12)).astype(np.float32)
        m = (r.random((12, 12)) < 0.5).astype(np.float32)
        reqs.append((x * m, m))
    tenant_of = lambda i: "alpha" if i % 2 == 0 else "beta"
    with tempfile.TemporaryDirectory() as mdir:
        with _fault(
            CCSC_FAULT_ENGINE_KILL_REQ=2,
            CCSC_FAULT_ENGINE_KILL_REPLICA="0",
        ):
            fleet = ServeFleet(
                d_alpha, ReconstructionProblem(geom), cfg, scfg,
                FleetConfig(
                    replicas=2, metrics_dir=mdir, min_queue_depth=64,
                    restart_backoff_s=0.05, verbose="none",
                    tenants=tenants,
                ),
            )
            fleet.publish_bank("bank-alpha", d_alpha)
            fleet.publish_bank("bank-beta", d_beta0)
            pre = [
                fleet.submit(b, mask=m, tenant=tenant_of(i),
                             key=f"pre{i}")
                for i, (b, m) in enumerate(reqs)
            ]
            # the hot-swap lands while the pre-batch is in flight and
            # the kill fault is armed — the republished digest must
            # not retarget admitted work, and the casualty's requeues
            # must still serve their admission-time digest
            old_dg, new_dg = fleet.publish_bank(
                "bank-beta", d_beta1, tenant="beta"
            )
            post = [
                fleet.submit(b, mask=m, tenant=tenant_of(i),
                             key=f"post{i}")
                for i, (b, m) in enumerate(reqs)
            ]
            pre_r = [f.result(timeout=180) for f in pre]
            post_r = [f.result(timeout=180) for f in post]
            fleet.close()
        events = obs.read_events(mdir, recursive=True)
        dead = [
            e for e in events if e["type"] == "fleet_replica_dead"
        ]
        swaps = [
            e for e in events
            if e["type"] == "bank_swap"
            and e.get("replica_id") is None
            and e.get("bank_id") == "bank-beta"
            and e.get("old_digest") == old_dg
            and e.get("new_digest") == new_dg
            and e.get("old_digest") is not None
        ]

    # bit-parity oracles: fresh single-bank engines
    def oracle(d, items):
        eng = CodecEngine(
            d, ReconstructionProblem(geom), cfg, scfg
        )
        try:
            return [eng.reconstruct(b, mask=m) for b, m in items]
        finally:
            eng.close()

    alpha_items = [reqs[i] for i in range(8) if i % 2 == 0]
    beta_items = [reqs[i] for i in range(8) if i % 2 == 1]
    o_alpha = oracle(d_alpha, alpha_items)
    o_beta0 = oracle(d_beta0, beta_items)
    o_beta1 = oracle(d_beta1, beta_items)
    alpha_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(
            [pre_r[i] for i in range(8) if i % 2 == 0]
            + [post_r[i] for i in range(8) if i % 2 == 0],
            o_alpha + o_alpha,
        )
    )
    beta_pre_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(
            [pre_r[i] for i in range(8) if i % 2 == 1], o_beta0
        )
    )
    beta_post_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(
            [post_r[i] for i in range(8) if i % 2 == 1], o_beta1
        )
    )
    ok = (
        len(pre_r) == 8
        and len(post_r) == 8
        and len(dead) == 1
        and len(swaps) == 1
        and alpha_ok
        and beta_pre_ok
        and beta_post_ok
    )
    return ok, (
        f"served={len(pre_r) + len(post_r)}/16, dead={len(dead)}, "
        f"swap={old_dg}->{new_dg} (events={len(swaps)}), "
        f"alpha_parity={alpha_ok}, beta_pre={beta_pre_ok}, "
        f"beta_post={beta_post_ok}"
    )


def scenario_gray_replica():
    """Hedged attempts bound the tail against a GRAY replica — slow
    but alive, the pathology the watchdog cannot see. A 2-replica
    fleet serves a saturating two-tenant stream while replica 0 runs
    every request ~10x slow (CCSC_FAULT_ENGINE_SLOW_*, deliberately
    far under the watchdog floor). Must hold: zero lost requests and
    exactly-once delivery; the hedged fleet's p99 stays within 3x a
    healthy no-fault baseline on the same stream while an unhedged
    control run under the same fault exceeds it; every delivered
    result is bit-identical to a bare single-engine oracle; the
    watchdog stays SILENT (zero stall records, zero replica deaths);
    hedge volume respects hedge_max_frac; and every decided hedge
    pair reassembles on the stream — winner delivered once, loser
    suppressed as ``hedge_lost``. The thresholds self-calibrate from
    the measured healthy p99 so the scenario holds on fast and slow
    machines alike."""
    import time as _time

    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
        TenantSpec,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import CodecEngine, ServeFleet
    from ccsc_code_iccv2017_tpu.serve import slo as _slo
    from ccsc_code_iccv2017_tpu.utils import obs

    r = np.random.default_rng(0)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    geom = ProblemGeom((3, 3), 4)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none",
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    tenants = (TenantSpec(tenant="alpha"), TenantSpec(tenant="beta"))
    N = 16
    r2 = np.random.default_rng(3)
    reqs = []
    for _ in range(N):
        x = r2.random((12, 12)).astype(np.float32)
        m = (r2.random((12, 12)) < 0.5).astype(np.float32)
        reqs.append((x * m, m))
    tenant_of = lambda i: "alpha" if i % 2 == 0 else "beta"

    def serve(mdir, **cfg_kw):
        base = dict(
            replicas=2, metrics_dir=mdir, min_queue_depth=64,
            restart_backoff_s=0.05, verbose="none", tenants=tenants,
            # fast monitor ticks: the hedge plane must react at the
            # measured-latency scale, not at human heartbeat scale
            health_interval_s=0.005,
        )
        base.update(cfg_kw)
        fleet = ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            FleetConfig(**base),
        )
        try:
            futs = [
                fleet.submit(b, mask=m, tenant=tenant_of(i),
                             key=f"k{i}")
                for i, (b, m) in enumerate(reqs)
            ]
            out = [f.result(timeout=180) for f in futs]
        finally:
            fleet.close()  # joins workers: straggler losers settle
        events = obs.read_events(mdir, recursive=True)
        lat = _slo.Histogram.of(
            e["latency_ms"] for e in events
            if e["type"] == "fleet_request"
        )
        p99 = lat.percentile(0.99)
        return out, (float("inf") if p99 is None else p99), events

    with tempfile.TemporaryDirectory() as root:
        # 1) healthy baseline on the same stream — no fault; its p99
        # calibrates the fault magnitude and the hedge threshold
        _, p99_healthy, _ = serve(os.path.join(root, "m-healthy"))
        bound = 3.0 * p99_healthy
        # "10x slow" relative to what this machine actually serves,
        # capped so a 2-request batch of sleeps stays far under the
        # 30 s watchdog floor
        slow_s = min(max(10.0 * p99_healthy / 1e3, 0.5), 8.0)
        hedge_ms = max(1.0 * p99_healthy, 25.0)
        fault_env = dict(
            CCSC_FAULT_ENGINE_SLOW_REQ=1,
            CCSC_FAULT_ENGINE_SLOW_S=slow_s,
            CCSC_FAULT_ENGINE_SLOW_REPLICA="0",
        )
        # 2) hedged fleet under the gray fault
        with _fault(**fault_env):
            t0 = _time.monotonic()
            hedged, p99_hedged, events = serve(
                os.path.join(root, "m-hedged"),
                hedge_after_ms=hedge_ms, hedge_max_frac=0.25,
            )
            hedged_wall = _time.monotonic() - t0
        # 3) unhedged control under the same fault: the tail the
        # fleet eats WITHOUT the hedge plane
        with _fault(**fault_env):
            _, p99_control, _ = serve(
                os.path.join(root, "m-control"), hedge_max_frac=0.0,
            )

    # bit-parity oracle: a bare single engine over the same bytes —
    # a hedged duplicate runs the same plan on the same bank, so the
    # winner's recon must be bit-identical no matter which attempt won
    eng = CodecEngine(d, ReconstructionProblem(geom), cfg, scfg)
    try:
        oracle = [eng.reconstruct(b, mask=m) for b, m in reqs]
    finally:
        eng.close()
    parity = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(hedged, oracle)
    )

    served = [e for e in events if e["type"] == "fleet_request"]
    keys = [e["key"] for e in served]
    spawns = {e["key"] for e in events if e["type"] == "hedge_spawn"}
    wins = {e["key"] for e in events if e["type"] == "hedge_win"}
    losses = {e["key"] for e in events if e["type"] == "hedge_lost"}
    stalls = [
        e for e in events
        if e["type"] in ("stall", "fleet_replica_dead")
    ]
    ok = (
        len(hedged) == N                      # zero lost
        and sorted(keys) == sorted(f"k{i}" for i in range(N))
        and len(keys) == len(set(keys))       # exactly once each
        and parity
        and not stalls                        # gray, not dead
        and len(spawns) >= 1                  # hedging actually fired
        and len(spawns) <= 0.25 * N           # hedge_max_frac cap
        and wins <= spawns
        and losses <= spawns
        and wins == losses                    # every decided pair:
                                              # winner + suppressed
                                              # loser, both on stream
        and p99_hedged <= bound
        and p99_control > bound
    )
    return ok, (
        f"p99 healthy={p99_healthy:.0f}ms hedged={p99_hedged:.0f}ms "
        f"control={p99_control:.0f}ms (bound {bound:.0f}ms, "
        f"slow_s={slow_s:.2f}), hedges={len(spawns)} "
        f"wins={len(wins)} lost={len(losses)}, parity={parity}, "
        f"stalls={len(stalls)}, wall={hedged_wall:.1f}s"
    )


def scenario_bank_rot():
    """Quality-observatory chaos (serve.quality): a fleet serves
    two-tenant traffic when one tenant's bank is hot-swapped for a
    DEGRADED one (every atom collapsed to the same blur — the
    degenerate-retrain rot the probe plane exists to catch). Must
    hold: the golden probes flag the rot digest within ~one probe
    interval (``quality_probe_breach``), the drift watch flags the
    served-dB excursion against the seeded ledger history
    (``quality_drift``), a demotion advisory names the prior digest
    as the rollback target, acting on it swaps the good bank back,
    zero requests are lost throughout, pre-rot and post-demotion
    results are bit-identical to a fresh good-bank engine, and the
    whole episode triggers ZERO new XLA compiles (plan builds on the
    rot digest are jitted; the bucket programs are digest-canonical).
    """
    import time

    import numpy as np

    from ccsc_code_iccv2017_tpu.analysis import ledger as ledger_mod
    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
        TenantSpec,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import CodecEngine, ServeFleet
    from ccsc_code_iccv2017_tpu.serve import quality as quality_mod
    from ccsc_code_iccv2017_tpu.utils import obs

    geom = ProblemGeom(spatial_support=(5, 5), num_filters=8)

    def norm(d):
        return d / np.linalg.norm(
            d.reshape(8, -1), axis=1
        ).reshape(8, 1, 1)

    r = np.random.default_rng(1)
    d_good = norm(r.standard_normal((8, 5, 5)).astype(np.float32))
    rr = np.random.default_rng(99)
    d_rot = norm(
        np.stack([
            np.ones((5, 5), np.float32)
            + 0.01 * rr.standard_normal((5, 5)).astype(np.float32)
            for _ in range(8)
        ])
    )
    # max_it matters: at 3 iterations every bank reconstructs equally
    # badly; by 16 the solve exploits the bank's structure and the
    # good-vs-rot dB gap opens past the probe tolerance. track_psnr:
    # verbose="none" untracks PSNR by default, and an untracked
    # delivery (psnr=None) never reaches the drift watch
    cfg = SolveConfig(
        max_it=16, tol=0.0, verbose="none", track_psnr=True,
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    tenants = (
        TenantSpec(tenant="alpha", bank_id="bank-live"),
        TenantSpec(tenant="beta"),  # rides the pinned default bank
    )
    radius = geom.psf_radius
    # served content synthesized THROUGH the good bank: the only
    # content whose served dB actually ranks banks (quality.synth_probe)
    xs_a = [
        quality_mod.synth_probe(d_good, (12, 12), seed=100 + i)
        for i in range(6)
    ]
    xs_b = [
        quality_mod.synth_probe(d_good, (12, 12), seed=200 + i)
        for i in range(3)
    ]

    # bit-parity oracles + the good bank's served-dB baseline that
    # seeds the drift watch's ledger history
    def oracle(d, items):
        eng = CodecEngine(d, ReconstructionProblem(geom), cfg, scfg)
        try:
            return [eng.reconstruct(x) for x in items]
        finally:
            eng.close()

    o_alpha = oracle(d_good, xs_a)
    o_beta = oracle(d_good, xs_b)
    o_rot = oracle(d_rot, xs_a)
    good_dbs = [
        quality_mod.valid_region_psnr(res.recon, x, radius)
        for res, x in zip(o_alpha, xs_a)
    ]

    probe_interval = 0.35
    with tempfile.TemporaryDirectory() as tmp:
        mdir = os.path.join(tmp, "metrics")
        pdir = os.path.join(tmp, "probes")
        lpath = os.path.join(tmp, "ledger.jsonl")
        led = ledger_mod.Ledger(lpath)
        for db in good_dbs:
            rec = ledger_mod.normalize_record(
                kind="quality", value=round(float(db), 4), unit="db",
                knobs={"bank": "bank-live"}, source="chaos_seed",
                **quality_mod._quality_key_fields(geom, scfg.buckets),
            )
            led.append(rec)
        # drift window 3: the scenario serves 6 rot-digest requests;
        # the default window of 5 needs a longer excursion than this
        # smoke's traffic to pull the rolling median under the band
        with _fault(
            CCSC_PERF_LEDGER=lpath, CCSC_QUALITY_DRIFT_WINDOW=3,
        ):
            fleet = ServeFleet(
                d_good, ReconstructionProblem(geom), cfg, scfg,
                FleetConfig(
                    replicas=2, metrics_dir=mdir, min_queue_depth=64,
                    restart_backoff_s=0.05, verbose="none",
                    tenants=tenants, probe_dir=pdir,
                    probe_interval_s=probe_interval,
                ),
            )
            old_dg, _ = None, None
            _, good_dg = fleet.publish_bank("bank-live", d_good)
            # pre-rot traffic: both tenants, ground truth attached so
            # the monitor folds served dB
            pre = [
                fleet.submit(x, x_orig=x, tenant="alpha",
                             key=f"pre-a{i}")
                for i, x in enumerate(xs_a)
            ] + [
                fleet.submit(x, x_orig=x, tenant="beta",
                             key=f"pre-b{i}")
                for i, x in enumerate(xs_b)
            ]
            pre_r = [f.result(timeout=180) for f in pre]
            # idle gap: let the probe sweeps seal references for the
            # default bank and link bank-live to the shared digest
            deadline = time.time() + 20 * probe_interval
            while time.time() < deadline:
                evs = obs.read_events(mdir, recursive=True)
                if any(
                    e.get("type") == "quality_probe"
                    and e.get("bank_id") == "bank-live"
                    for e in evs
                ):
                    break
                time.sleep(0.1)
            # ROT: the degraded bank lands on bank-live
            t_rot = time.time()
            _, rot_dg = fleet.publish_bank("bank-live", d_rot)
            # queue stays idle -> the next probe sweep must flag it
            advice = []
            deadline = time.time() + 20 * probe_interval
            while time.time() < deadline:
                advice = fleet.quality_advice()
                if advice:
                    break
                time.sleep(0.05)
            t_detect = time.time() - t_rot
            # rot-digest traffic: drift watch judges the served dB
            # against the seeded good-bank history
            mid = [
                fleet.submit(x, x_orig=x, tenant="alpha",
                             key=f"mid-a{i}")
                for i, x in enumerate(xs_a)
            ]
            mid_r = [f.result(timeout=180) for f in mid]
            # act on the advisory: swap the retained good bank back
            # (the fleet never swaps on its own — the operator, or the
            # controller harness, consumes quality_advice())
            _, back_dg = fleet.publish_bank("bank-live", d_good)
            post = [
                fleet.submit(x, x_orig=x, tenant="alpha",
                             key=f"post-a{i}")
                for i, x in enumerate(xs_a)
            ] + [
                fleet.submit(x, x_orig=x, tenant="beta",
                             key=f"post-b{i}")
                for i, x in enumerate(xs_b)
            ]
            post_r = [f.result(timeout=180) for f in post]
            fleet.close()
        events = obs.read_events(mdir, recursive=True)

    breaches = [
        e for e in events
        if e.get("type") == "quality_probe_breach"
        and e.get("digest") == rot_dg
    ]
    drifts = [
        e for e in events
        if e.get("type") == "quality_drift"
        and e.get("digest") == rot_dg
    ]
    compiles_after = [
        e for e in events
        if e.get("kind") == "compile" and e.get("t", 0) > t_rot
    ]
    adv = [
        a for a in advice
        if a.get("bank_id") == "bank-live"
        and a.get("reason") == "probe"
        and a.get("from_digest") == rot_dg
    ]
    advice_ok = bool(adv) and adv[0].get("to_digest") == good_dg
    # one probe interval + the sweep's own solve time
    detect_ok = bool(adv) and t_detect <= probe_interval + 2.0
    n_a = len(xs_a)
    alpha_pre_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(pre_r[:n_a], o_alpha)
    )
    beta_pre_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(pre_r[n_a:], o_beta)
    )
    rot_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(mid_r, o_rot)
    )
    alpha_post_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(post_r[:n_a], o_alpha)
    )
    beta_post_ok = all(
        np.array_equal(got.recon, want.recon)
        for got, want in zip(post_r[n_a:], o_beta)
    )
    served = len(pre_r) + len(mid_r) + len(post_r)
    ok = (
        served == 2 * (len(xs_a) + len(xs_b)) + len(xs_a)
        and len(breaches) >= 1
        and len(drifts) >= 1
        and advice_ok
        and detect_ok
        and back_dg == good_dg
        and rot_dg != good_dg
        and len(compiles_after) == 0
        and alpha_pre_ok
        and beta_pre_ok
        and rot_ok
        and alpha_post_ok
        and beta_post_ok
    )
    return ok, (
        f"served={served}/{2 * (len(xs_a) + len(xs_b)) + len(xs_a)}, "
        f"probe_breach={len(breaches)}, drift={len(drifts)}, "
        f"advice={'ok' if advice_ok else advice}, "
        f"detect={t_detect:.2f}s (interval {probe_interval}s), "
        f"demote={rot_dg[:8]}->{back_dg[:8]}, "
        f"compiles_after_rot={len(compiles_after)}, "
        f"parity: alpha_pre={alpha_pre_ok} beta_pre={beta_pre_ok} "
        f"rot={rot_ok} alpha_post={alpha_post_ok} "
        f"beta_post={beta_post_ok}"
    )


def _host_kill_child_code(qdir, bank_path, mdir, host_id):
    """Source of one federated host process (shared by the chaos
    scenario and tests/test_federation.py): join the pool at qdir,
    drain until sealed, leave cleanly."""
    return f"""
import numpy as np
from ccsc_code_iccv2017_tpu.config import (
    FleetConfig, ProblemGeom, ServeConfig, SolveConfig)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem)
from ccsc_code_iccv2017_tpu.serve.federation import FederatedHost
d = np.load({bank_path!r})
geom = ProblemGeom((3, 3), 4)
cfg = SolveConfig(lambda_residual=5.0, lambda_prior=0.3, max_it=3,
                  tol=0.0, verbose="none", track_psnr=True,
                  track_objective=True)
scfg = ServeConfig(buckets=((2, (12, 12)),), max_wait_ms=2.0,
                   verbose="none")
host = FederatedHost(
    {qdir!r}, d, ReconstructionProblem(geom), cfg, scfg,
    FleetConfig(replicas=1, min_queue_depth=64,
                restart_backoff_s=0.05, verbose="none"),
    host={host_id!r}, metrics_dir={mdir!r},
    heartbeat_s=0.2, ttl_s=1.5, skew_s=0.3, verbose="none",
)
print("JOINED", flush=True)
while not host.serve_until_sealed(timeout=5.0):
    pass
host.close()
"""


def scenario_host_kill():
    import signal
    import time

    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet
    from ccsc_code_iccv2017_tpu.serve import capture as cap
    from ccsc_code_iccv2017_tpu.serve.federation import (
        FederatedFrontend,
    )

    r = np.random.default_rng(0)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    geom = ProblemGeom((3, 3), 4)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_psnr=True, track_objective=True,
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    n_req = 8
    with tempfile.TemporaryDirectory() as root:
        # 1) the ORACLE: serve the stream once on a plain in-process
        # fleet with capture armed — the recorded outcome digests are
        # the bit-parity reference the federated serve must reproduce
        reqs = []
        for i in range(n_req):
            x = r.random((12, 12)).astype(np.float32)
            m = (r.random((12, 12)) < 0.5).astype(np.float32)
            reqs.append((x * m, m, x))
        cap_dir = os.path.join(root, "capture")
        fleet = ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            FleetConfig(
                replicas=1, metrics_dir=os.path.join(root, "m-oracle"),
                capture_dir=cap_dir, min_queue_depth=64,
                verbose="none",
            ),
        )
        futs = [
            fleet.submit(b, mask=m, x_orig=x, key=f"k{i}")
            for i, (b, m, x) in enumerate(reqs)
        ]
        for f in futs:
            f.result(timeout=180)
        fleet.close()
        oracle = {
            rec["key"]: rec["outcome"]["digest"]
            for rec in cap.read_workload(cap_dir)
            if rec.get("outcome")
        }
        # 2) federated serve of the SAME bytes: host0 claims, gets
        # SIGKILLed while holding leases; host1 reaps and finishes
        qdir = os.path.join(root, "q")
        bank = os.path.join(root, "bank.npy")
        np.save(bank, d)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get(
            "PYTHONPATH", ""
        )

        def _spawn(i, extra_env=None):
            e = dict(env)
            e.update(extra_env or {})
            return subprocess.Popen(
                [
                    sys.executable, "-c",
                    _host_kill_child_code(
                        qdir, bank,
                        os.path.join(root, f"m-host{i}"), f"host{i}",
                    ),
                ],
                env=e, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )

        # host0 wedges (injected engine hang) on its third taken
        # request while holding leases — the deterministic "caught
        # mid-attempt" window the SIGKILL lands in
        p0 = _spawn(0, {
            "CCSC_FAULT_ENGINE_HANG_REQ": "3",
            "CCSC_FAULT_ENGINE_HANG_S": "600",
        })
        fe = FederatedFrontend(
            qdir, client="fe0",
            metrics_dir=os.path.join(root, "m-frontend"),
            verbose="none",
        )
        futs = [
            fe.submit(b, mask=m, x_orig=x, key=f"fed{i}")
            for i, (b, m, x) in enumerate(reqs)
        ]
        # wait until host0 is mid-stream: at least one delivery AND
        # leases still held — then kill the WHOLE PROCESS
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            st = fe.queue.stats()
            if st["results"] >= 1 and st["leased"] >= 1:
                break
            time.sleep(0.05)
        os.kill(p0.pid, signal.SIGKILL)  # no handler, no cleanup
        p0.wait()
        p1 = _spawn(1)
        fe.seal()
        results = [f.result(timeout=300) for f in futs]
        rc1 = p1.wait(timeout=300)
        fe.close()
        served_by = {res.host for res in results}
        parity = all(
            res.digest == oracle[f"k{i}"]
            for i, res in enumerate(results)
        )
        from ccsc_code_iccv2017_tpu.utils import obs

        events = obs.read_events(root, recursive=True)
        requeues = [
            e for e in events
            if e["type"] == "dqueue_requeue"
            and e.get("from_host") != e.get("by_host")
        ]
        ok = (
            len(results) == n_req
            and parity
            and "host1" in served_by
            and len(requeues) >= 1
            and rc1 == 0
        )
    return ok, (
        f"served={len(results)}/{n_req}, parity={parity}, "
        f"hosts={sorted(served_by)}, cross_host_requeues="
        f"{len(requeues)}, survivor_rc={rc1}"
    )


def _scale_up_child_code(qdir, bank_path, mdir, host_id, store=None,
                         staged=False):
    """Source of one federated host process for the scale_up scenario:
    two shape buckets; the joining host additionally points at the
    shared artifact store with staged warmup on, its declared-hot
    bucket first in the warm order."""
    extra = ""
    if store is not None:
        extra = (
            f"artifact_store={store!r}, staged_warmup=True,\n"
            f"                   warm_order=('2@12x12',),"
        )
    return f"""
import numpy as np
from ccsc_code_iccv2017_tpu.config import (
    FleetConfig, ProblemGeom, ServeConfig, SolveConfig)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem)
from ccsc_code_iccv2017_tpu.serve.federation import FederatedHost
d = np.load({bank_path!r})
geom = ProblemGeom((3, 3), 4)
cfg = SolveConfig(lambda_residual=5.0, lambda_prior=0.3, max_it=3,
                  tol=0.0, verbose="none", track_psnr=True,
                  track_objective=True)
scfg = ServeConfig(buckets=((2, (12, 12)), (2, (16, 16))),
                   max_wait_ms=2.0, verbose="none", {extra})
host = FederatedHost(
    {qdir!r}, d, ReconstructionProblem(geom), cfg, scfg,
    FleetConfig(replicas=1, min_queue_depth=64,
                restart_backoff_s=0.05, verbose="none"),
    host={host_id!r}, metrics_dir={mdir!r},
    heartbeat_s=0.2, ttl_s=1.5, skew_s=0.3, verbose="none",
)
print("JOINED", flush=True)
while not host.serve_until_sealed(timeout=5.0):
    pass
host.close()
"""


def scenario_scale_up():
    import threading
    import time

    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import CodecEngine
    from ccsc_code_iccv2017_tpu.serve.federation import (
        FederatedFrontend,
    )
    from ccsc_code_iccv2017_tpu.utils import obs

    r = np.random.default_rng(0)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    geom = ProblemGeom((3, 3), 4)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_psnr=True, track_objective=True,
    )
    with tempfile.TemporaryDirectory() as root:
        store = os.path.join(root, "artifacts")
        # 1) pre-warm the store with the HOT bucket only: a throwaway
        # engine warms 12x12 and publishes its AOT executable. The
        # cold 16x16 bucket is deliberately NOT published, so the
        # joining host exercises both paths — hot fetched, cold
        # live-compiled in the background — and the "first request
        # before coldest bucket ready" ordering has a real compile
        # window to land in rather than a millisecond fetch race.
        eng = CodecEngine(
            d, ReconstructionProblem(geom), cfg,
            ServeConfig(
                buckets=((2, (12, 12)),), max_wait_ms=2.0,
                artifact_store=store, verbose="none",
            ),
        )
        eng.close()
        # 2) host1 serves a sustained two-bucket stream the old way
        # (blocking warmup, no store); mid-stream host2 joins FROM
        # the warm store with staged warmup on
        qdir = os.path.join(root, "q")
        bank = os.path.join(root, "bank.npy")
        np.save(bank, d)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get(
            "PYTHONPATH", ""
        )

        def _spawn(i, **kw):
            return subprocess.Popen(
                [
                    sys.executable, "-c",
                    _scale_up_child_code(
                        qdir, bank,
                        os.path.join(root, f"m-host{i}"),
                        f"host{i}", **kw,
                    ),
                ],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )

        p1 = _spawn(1)
        fe = FederatedFrontend(
            qdir, client="fe0",
            metrics_dir=os.path.join(root, "m-frontend"),
            verbose="none",
        )
        # pre-built payload pool (the pump thread must not share the
        # parent rng); mostly hot-bucket 12x12, every 6th 16x16
        pool = []
        for shape in ((12, 12), (16, 16)):
            x = r.random(shape).astype(np.float32)
            m = (r.random(shape) < 0.5).astype(np.float32)
            pool.append((x * m, m, x))
        lat = {}
        served_host2 = threading.Event()
        stop = threading.Event()
        futs = []

        def _done(key, t0):
            def cb(f):
                lat[key] = time.monotonic() - t0
                with contextlib.suppress(Exception):
                    if f.result().host == "host2":
                        served_host2.set()
            return cb

        def _pump():
            i = 0
            while not stop.is_set() and i < 1500:
                b, m, x = pool[1 if i % 6 == 5 else 0]
                fut = fe.submit(b, mask=m, x_orig=x, key=f"s{i}")
                fut.add_done_callback(_done(f"s{i}", time.monotonic()))
                futs.append(fut)
                i += 1
                time.sleep(0.02)

        pump = threading.Thread(target=_pump, daemon=True)
        pump.start()
        # wait until the stream is live (host1 serving), then join
        # host2 from the warm store mid-stream
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and not lat:
            time.sleep(0.05)
        p2 = _spawn(2, store=store, staged=True)
        served_host2.wait(timeout=240)
        stop.set()
        pump.join(timeout=30)
        fe.seal()
        results = [f.result(timeout=300) for f in futs]
        rc1 = p1.wait(timeout=300)
        rc2 = p2.wait(timeout=300)
        fe.close()
        served_by = {res.host for res in results}
        lats = sorted(lat.values())
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
        # 3) the joining host's obs stream carries the proof: hot
        # bucket FETCHED from the store, first request served while
        # the cold bucket was still building
        ev = obs.read_events(
            os.path.join(root, "m-host2"), recursive=True
        )
        warm = {
            e["bucket"]: e["source"] for e in ev
            if e["type"] == "serve_warmup"
        }
        stages = [e for e in ev if e["type"] == "warmup_stage"]
        reqs = [e for e in ev if e["type"] == "serve_request"]
        cold_ready_t = max((e["t"] for e in stages), default=0.0)
        first_req_t = min(
            (e["t"] for e in reqs), default=float("inf")
        )
        hot_fetched = warm.get("2@12x12") == "fetched"
        early_serve = first_req_t < cold_ready_t
        ok = (
            len(results) == len(futs)
            and "host2" in served_by
            and hot_fetched
            and len(stages) == 2
            and early_serve
            and p99 < 60.0
            and rc1 == 0
            and rc2 == 0
        )
    return ok, (
        f"served={len(results)}/{len(futs)}, hosts={sorted(served_by)}, "
        f"hot_source={warm.get('2@12x12')}, stages={len(stages)}, "
        f"first_req_before_cold_ready={early_serve}, "
        f"p99={p99:.2f}s, rc1={rc1}, rc2={rc2}"
    )


def scenario_autoscale():
    """Self-driving capacity end-to-end (ISSUE 17 acceptance): replay
    the synthetic diurnal curve (serve.replay.generate_diurnal)
    against a 1-replica fleet while a live CapacityController owns
    capacity. At the peak the controller must brown out and grow to 2
    replicas — the grown replica warming FROM the compiled-artifact
    store, not compiling — and at the trough shrink back to 1, with
    zero lost requests and bounded p99. Then two injected control-
    plane faults against the same fleet: a sensor blackout while
    scale-down pressure is live (must hold — ``ctrl_holdoff`` and
    NEVER a blind scale-down, then reconcile once sensors return),
    and a wedged actuator under scale-up pressure (must open the
    circuit breaker — failed ``ctrl_scale`` then
    ``breaker_open:scale_up`` holdoffs — while the data plane keeps
    serving every queued request)."""
    import time

    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        ControllerConfig,
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import (
        CapacityController,
        Overloaded,
        ServeFleet,
    )
    from ccsc_code_iccv2017_tpu.serve.replay import (
        ReplayDriver,
        generate_diurnal,
    )
    from ccsc_code_iccv2017_tpu.utils import obs

    r = np.random.default_rng(0)
    d = r.normal(size=(4, 3, 3)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    geom = ProblemGeom((3, 3), 4)
    # heavy enough that ONE replica's throughput (~25 req/s on CPU)
    # sits below the diurnal PEAK (~38 req/s) but above its mean —
    # the peak genuinely saturates, the trough genuinely idles
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=400, tol=0.0,
        verbose="none", track_psnr=True, track_objective=True,
    )
    with tempfile.TemporaryDirectory() as root:
        cap = generate_diurnal(
            os.path.join(root, "capture"), n_requests=120,
            duration_s=6.0, spatial=(24, 24), amp=0.9, seed=0,
        )
        store = os.path.join(root, "artifacts")
        mdir = os.path.join(root, "m-serve")
        scfg = ServeConfig(
            buckets=((2, (24, 24)),), max_wait_ms=2.0,
            verbose="none", artifact_store=store,
        )
        # explicit tiny ceiling: queue pressure (frac of 4) is the
        # controller's scale signal, independent of rate measurement
        fleet = ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            FleetConfig(
                replicas=1, metrics_dir=mdir, max_queue_depth=4,
                restart_backoff_s=0.05, verbose="none",
            ),
        )
        ctrl = CapacityController(
            fleet,
            ControllerConfig(
                min_replicas=1, max_replicas=2, interval_s=0.05,
                high_frac=0.5, low_frac=0.1, sustain=2,
                cooldown_s=1.0, stale_s=10.0, act_timeout_s=180.0,
                act_retries=0, act_backoff_s=0.05, breaker_after=3,
                breaker_reset_s=30.0, brownout_frac=0.75,
                brownout_exit_frac=0.1,
            ),
        ).start()
        try:
            rep = ReplayDriver(
                cap, metrics_dir=os.path.join(root, "m-replay"),
                verbose="none",
            ).replay(fleet, speed=1.0, mode="open", timeout_s=600)
            # the trough: the controller drains back to the floor
            # (the brownout release + shrink each recycle an engine,
            # so allow real compile time)
            deadline = time.monotonic() + 120
            while (
                time.monotonic() < deadline
                and fleet.replica_target > 1
            ):
                time.sleep(0.05)
            trough_target = fleet.replica_target
        finally:
            ctrl.close()
        ev = obs.read_events(mdir, recursive=True)
        ups = [
            e for e in ev
            if e["type"] == "ctrl_scale"
            and e["direction"] == "up" and e["ok"]
        ]
        downs = [
            e for e in ev
            if e["type"] == "ctrl_scale"
            and e["direction"] == "down" and e["ok"]
        ]
        brown_on = [
            e for e in ev
            if e["type"] == "ctrl_brownout" and e["on"] and e["ok"]
        ]
        brown_off = [
            e for e in ev
            if e["type"] == "ctrl_brownout"
            and not e["on"] and e["ok"]
        ]
        fetched = [
            e for e in ev
            if e["type"] == "serve_warmup"
            and e.get("source") == "fetched"
        ]

        # -- fault leg A: sensor blackout while scale-down pressure
        # is live. Deterministic single-step drive (no loop thread).
        fleet.set_replica_count(2, reason="chaos_setup")
        ch_cfg = ControllerConfig(
            min_replicas=1, max_replicas=2, interval_s=0.05,
            high_frac=0.5, low_frac=0.2, sustain=1, cooldown_s=0.05,
            stale_s=10.0, act_timeout_s=0.5, act_retries=0,
            act_backoff_s=0.01, breaker_after=2, breaker_reset_s=60.0,
            brownout_frac=1.5, brownout_exit_frac=0.01,
        )
        ctrl2 = CapacityController(fleet, ch_cfg)
        with _fault(
            CCSC_FAULT_CTRL_SENSOR_BLACKOUT=1,
            CCSC_FAULT_CTRL_BLACKOUT_S="120",
        ):
            for _ in range(4):
                ctrl2.step()  # idle fleet: would shrink if it could see
                time.sleep(0.06)
            blind_held = fleet.replica_target == 2
        ctrl2.step()  # sensors restored: the shrink happens NOW
        recovered = fleet.replica_target == 1
        ctrl2.close()

        # -- fault leg B: wedged actuator under real scale-up
        # pressure -> circuit breaker; the queue still drains
        def _burst(lo, hi):
            out = []
            for i in range(lo, hi):
                x = r.random((24, 24)).astype(np.float32)
                m = (r.random((24, 24)) < 0.5).astype(np.float32)
                try:
                    out.append(
                        fleet.submit(x * m, mask=m, key=f"hang{i}")
                    )
                except Overloaded:
                    pass
            return out

        with _fault(
            CCSC_FAULT_CTRL_ACT_HANG=2,
            CCSC_FAULT_CTRL_ACT_HANG_S="600",
        ):
            ctrl3 = CapacityController(fleet, ch_cfg)
            futs = _burst(0, 4)
            ctrl3.step()  # attempt 1 wedges -> timeout -> failed
            futs += _burst(4, 8)  # keep the pressure on
            ctrl3.step()  # attempt 2 wedges -> breaker OPEN
            futs += _burst(8, 12)
            ctrl3.step()  # refused at the breaker -> ctrl_holdoff
            n_hang_served = len(
                [f.result(timeout=300) for f in futs]
            )
            ctrl3.close()
        held_at_1 = fleet.replica_target == 1
        st = fleet.stats()
        fleet.close()

        ev = obs.read_events(mdir, recursive=True)
        holds = {
            e["reason"] for e in ev if e["type"] == "ctrl_holdoff"
        }
        failed_scales = [
            e for e in ev
            if e["type"] == "ctrl_scale" and not e["ok"]
        ]
        ok = (
            rep["n_replayed"] == 120
            and rep["n_lost"] == 0
            and rep["replayed_p99_ms"] is not None
            and rep["replayed_p99_ms"] < 120_000
            and len(ups) >= 1
            and len(downs) >= 1
            and trough_target == 1
            and len(brown_on) >= 1
            and len(brown_off) >= 1
            and len(fetched) >= 1
            and blind_held
            and recovered
            and "sensor_stale" in holds
            and len(failed_scales) >= 2
            and "breaker_open:scale_up" in holds
            and held_at_1
            and len(futs) == n_hang_served
            and st["n_failed"] == 0
        )
    return ok, (
        f"replayed={rep['n_replayed']}, lost={rep['n_lost']}, "
        f"p99={rep['replayed_p99_ms']}ms, ups={len(ups)}, "
        f"downs={len(downs)}, brownout={len(brown_on)}on/"
        f"{len(brown_off)}off, store_fetches={len(fetched)}, "
        f"blackout_held={blind_held}, reconciled={recovered}, "
        f"breaker_failed_scales={len(failed_scales)}, "
        f"holdoffs={sorted(holds)}, "
        f"hang_served={n_hang_served}/{len(futs)}"
    )


def scenario_supervise_restart():
    import json

    from ccsc_code_iccv2017_tpu.utils import checkpoint as ckpt

    with tempfile.TemporaryDirectory() as d:
        ck = os.path.join(d, "ck")
        mdir = os.path.join(d, "metrics")
        code = f"""
import jax, jax.numpy as jnp, numpy as np
from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
b = jnp.asarray(np.asarray(
    jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32))
cfg = LearnConfig(max_it=3, max_it_d=2, max_it_z=2, num_blocks=2,
                  rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none",
                  metrics_dir={mdir!r})
learn(b, ProblemGeom((3, 3), 4), cfg, key=jax.random.PRNGKey(0),
      checkpoint_dir={ck!r}, checkpoint_every=1)
"""
        env = dict(
            os.environ, CCSC_FAULT_SIGTERM_IT="1", JAX_PLATFORMS="cpu"
        )
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "scripts", "supervise.py"),
                "--checkpoint-dir", ck,
                "--metrics-dir", mdir,
                "--max-restarts", "3",
                "--backoff", "0",
                "--",
                sys.executable, "-c", code,
            ],
            capture_output=True, text=True, env=env, timeout=480,
        )
        trace = {}
        tp = os.path.join(mdir, "supervisor_trace.json")
        if os.path.exists(tp):
            with open(tp) as f:
                trace = json.load(f)
        reasons = [a.get("reason") for a in trace.get("attempts", [])]
        snap = ckpt.load(ck) if p.returncode == 0 else None
        ok = (
            p.returncode == 0
            and reasons == ["preempted", "completed"]
            and snap is not None
            and snap[2] == 3
        )
    return ok, f"rc={p.returncode}, reasons={reasons}"


def scenario_sigterm_subprocess():
    from ccsc_code_iccv2017_tpu.utils import checkpoint as ckpt

    with tempfile.TemporaryDirectory() as d:
        code = f"""
import jax, jax.numpy as jnp, numpy as np
from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
from ccsc_code_iccv2017_tpu.models.learn import learn
b = jnp.asarray(np.asarray(
    jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12)), np.float32))
cfg = LearnConfig(max_it=3, max_it_d=2, max_it_z=2, num_blocks=2,
                  rho_d=50.0, rho_z=2.0, tol=0.0, verbose="none")
learn(b, ProblemGeom((3, 3), 4), cfg, key=jax.random.PRNGKey(0),
      checkpoint_dir={d!r}, checkpoint_every=1)
"""
        env = dict(os.environ, CCSC_FAULT_SIGTERM_IT="1",
                   JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env=env, timeout=240,
        )
        snap = ckpt.load(d) if p.returncode == 0 else None
        ok = p.returncode == 0 and snap is not None and snap[2] == 1
    return ok, f"rc={p.returncode}"


def run(subprocess_scenarios: bool = True, only=None) -> dict:
    """``only``: iterable of scenario names to restrict to (the pytest
    wrapper runs one representative per fault point — the dedicated
    tests in tests/test_resilience.py already cover every variant, so
    re-paying each tiny-learn jit compile twice buys nothing)."""
    scenarios = {
        "nan_recovery": scenario_nan_recovery,
        "nan_recovery_chunk": scenario_nan_recovery_chunk,
        "nan_stop_default": scenario_nan_stop_default,
        "ckpt_save_crash": scenario_ckpt_save_crash,
        "corrupt_fallback": scenario_corrupt_fallback,
        "sigterm_checkpoint": scenario_sigterm_checkpoint,
        "hang_watchdog": scenario_hang_watchdog,
        "fleet_kill": scenario_fleet_kill,
        "replay_parity": scenario_replay_parity,
        "bank_swap": scenario_bank_swap,
    }
    if subprocess_scenarios:
        # in-process but latency-calibrated (three fleet runs, one
        # deliberately ~10x slow): script mode only, run by its own
        # ci.sh stage ('--only gray_replica', exit 28)
        scenarios["gray_replica"] = scenario_gray_replica
        scenarios["host_kill"] = scenario_host_kill
        scenarios["scale_up"] = scenario_scale_up
        # in-process but ~30s of wall clock (probe sweeps at a real
        # interval + it16 solves): script mode only, run by its own
        # ci.sh stage ('--only bank_rot', exit 27)
        scenarios["bank_rot"] = scenario_bank_rot
        # in-process but ~a minute of wall clock (a full diurnal
        # replay): script mode only, same as the subprocess scenarios
        scenarios["autoscale"] = scenario_autoscale
        scenarios["sigterm_subprocess"] = scenario_sigterm_subprocess
        scenarios["supervise_restart"] = scenario_supervise_restart
    if only is not None:
        scenarios = {k: v for k, v in scenarios.items() if k in set(only)}
    results = {}
    for name, fn in scenarios.items():
        try:
            ok, msg = fn()
        except Exception as e:  # a crashed scenario is a failed one
            ok, msg = False, f"raised {type(e).__name__}: {e}"
        results[name] = (ok, msg)
        print(f"[{'PASS' if ok else 'FAIL'}] {name}: {msg}")
    return results


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="end-to-end chaos scenarios (exit 0 iff all pass)"
    )
    ap.add_argument(
        "--only", nargs="+", metavar="SCENARIO", default=None,
        help="restrict to the named scenario(s) — e.g. the ci.sh "
        "autoscale stage runs '--only autoscale'",
    )
    args = ap.parse_args(argv)
    results = run(subprocess_scenarios=True, only=args.only)
    if args.only and len(results) < len(set(args.only)):
        missing = set(args.only) - set(results)
        print(f"unknown scenario(s): {sorted(missing)}")
        return 2
    failed = [k for k, (ok, _) in results.items() if not ok]
    print(
        f"{len(results) - len(failed)}/{len(results)} chaos scenarios passed"
    )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
