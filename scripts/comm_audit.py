#!/usr/bin/env python
"""Collective-budget audit (analysis.comms), end to end, on forced
host devices — the CI proof that the mesh serving programs actually
lower to their declared communication budgets:

  batch-only mesh   every AOT bucket program contains ZERO collective
                    HLO ops (each device solves its slot shard start
                    to finish — any collective is a lowering bug)
  (batch, freq)     every bucket program stays within its declared
                    budget (CCSC_COMM_BUDGET_FREQ, default 1: the
                    single tiled all-gather at the z-solve tail) and
                    the one allowed op IS an all-gather, not a
                    smuggled reduce/permute
  enforcement       an injected over-budget count raises
                    CommBudgetError (the gate refuses, not records)

The verdicts are read from the engines' ``comm_counts`` (the warmup
audit) AND re-derived from the ``comm_audit`` obs events, so the
stream contract is exercised too.

Usage:
    JAX_PLATFORMS=cpu python scripts/comm_audit.py

Exit 0 iff every assertion holds. scripts/ci.sh runs this as its
collective-audit leg (exit code 29 on failure).
"""
from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# 8 forced host devices BEFORE jax imports (the same virtual pod the
# mesh parity tests run on); idempotent when ci.sh already set it
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _bank(k=6, s=5, seed=0):
    import numpy as np

    r = np.random.default_rng(seed)
    d = r.normal(size=(k, s, s)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    return d


def _engine(mesh_shape, slots, spatial, mdir):
    from ccsc_code_iccv2017_tpu.config import (
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import CodecEngine

    d = _bank()
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=2, tol=0.0,
        verbose="none",
    )
    return CodecEngine(
        d,
        ReconstructionProblem(ProblemGeom(d.shape[1:], d.shape[0])),
        cfg,
        ServeConfig(
            buckets=((slots, spatial),),
            mesh_shape=mesh_shape,
            metrics_dir=mdir,
            verbose="none",
        ),
    )


def main() -> int:
    import jax

    from ccsc_code_iccv2017_tpu.analysis import comms
    from ccsc_code_iccv2017_tpu.utils import obs

    if jax.device_count() < 8:
        print(
            f"FATAL: need 8 forced host devices, got "
            f"{jax.device_count()} — run under XLA_FLAGS="
            "--xla_force_host_platform_device_count=8"
        )
        return 1

    checks = []

    def check(name, ok, detail=""):
        checks.append(ok)
        print(f"[{'PASS' if ok else 'FAIL'}] {name}"
              + (f": {detail}" if detail else ""))

    def audit_events(mdir):
        return [
            e for e in obs.read_events(mdir)
            if e.get("type") == "comm_audit"
        ]

    with tempfile.TemporaryDirectory() as root:
        # ---- batch-only mesh: ZERO collectives -------------------
        m1 = os.path.join(root, "m-batch")
        eng = _engine((8,), 16, (12, 12), m1)
        try:
            counts = eng.comm_counts
        finally:
            eng.close()
        check(
            "batch-mesh engine audited its bucket program",
            len(counts) == 1, f"audited={len(counts)}",
        )
        totals = [c["total"] for c in counts.values()]
        check(
            "batch-mesh program lowers to ZERO collective HLO ops",
            totals == [0],
            ", ".join(
                comms.format_counts(c) for c in counts.values()
            ) or "none",
        )
        ev = audit_events(m1)
        check(
            "comm_audit event records the zero verdict (ok=True, "
            "budget=0)",
            len(ev) == 1 and ev[0]["ok"] and ev[0]["budget"] == 0
            and ev[0]["total"] == 0,
            f"events={[(e.get('budget'), e.get('total'), e.get('ok')) for e in ev]}",
        )

        # ---- (batch, freq) mesh: within the declared budget ------
        m2 = os.path.join(root, "m-freq")
        eng = _engine((4, 2), 8, (24, 24), m2)
        try:
            counts = eng.comm_counts
        finally:
            eng.close()
        budget = comms.declared_budget((4, 2))
        c = next(iter(counts.values()), {"total": -1})
        check(
            "freq-mesh program meets its declared budget "
            f"(CCSC_COMM_BUDGET_FREQ={budget})",
            len(counts) == 1 and 0 <= c["total"] <= budget,
            comms.format_counts(c) if "all_gather" in c else str(c),
        )
        check(
            "freq-mesh program's one exchange is the z-solve tail "
            "all-gather (no smuggled reduce/permute)",
            c.get("all_gather") == c.get("total") != 0,
            comms.format_counts(c) if "all_gather" in c else str(c),
        )
        ev = audit_events(m2)
        check(
            "comm_audit event records the freq verdict (ok=True)",
            len(ev) == 1 and ev[0]["ok"]
            and ev[0]["budget"] == budget,
            f"events={[(e.get('budget'), e.get('total'), e.get('ok')) for e in ev]}",
        )

        # ---- enforcement: an over-budget count REFUSES -----------
        injected = comms.collective_counts(
            "ROOT r = f32[8]{0} all-reduce(f32[8]{0} %x), "
            "to_apply=%add"
        )
        try:
            comms.check(injected, (8,), bucket="injected")
            refused = False
        except comms.CommBudgetError:
            refused = True
        check(
            "an injected collective over budget raises "
            "CommBudgetError",
            refused and injected["total"] == 1,
            comms.format_counts(injected),
        )

    n_fail = sum(1 for ok in checks if not ok)
    print(f"{len(checks) - n_fail}/{len(checks)} collective-audit "
          "checks passed")
    return 1 if n_fail else 0


if __name__ == "__main__":
    sys.exit(main())
