#!/usr/bin/env python
"""Replay a captured serving workload against a fresh fleet
(serve.capture -> serve.replay) and verify the answers.

    python scripts/replay.py CAPTURE_DIR [--replicas N]
        [--speed X | --max-speed] [--mode open|closed]
        [--filters BANK.mat] [--metrics-dir DIR] [--json]

    python scripts/replay.py --generate-diurnal OUT_DIR
        [--requests N --duration S --side PX --seed K]

    python scripts/replay.py --demo [--demo-dir DIR]

Default mode rebuilds a serving fleet pinned to the capture's own
geometry/solve parameters (recorded in the capture's meta.json; the
bank comes from --filters or, for synthetic-bank captures, the
deterministic --bank-seed bank) and re-submits the recorded stream:
open-loop on the recorded arrival clock x --speed (--max-speed =
back-to-back saturation, admission refusals honored + retried so
nothing is shed) or closed-loop. Every replayed result is paired with
its recorded outcome — same-bucket replays must be BIT-IDENTICAL
(sha256 of the reconstruction bytes), cross-bucket replays are held
to --psnr-tol dB. Exit 0 iff zero lost and zero mismatched.

The replay session appends a kind=replay record to the durable perf
ledger when CCSC_PERF_LEDGER is armed, so `scripts/perf_gate.py`
(try `--list --kind replay`) gates replay throughput against its own
history, and the replay metrics stream renders in obs_report's
REPLAY section.

--generate-diurnal writes a deterministic synthetic diurnal-curve
capture (sinusoidal arrival intensity) for load-shape experiments.

--demo is the self-contained end-to-end proof: a 3-replica fleet
serves a stream UNDER INJECTED KILL/HANG FAULTS with capture on, the
captured stream is replayed at 1x and at max speed against fresh
fleets, and both replays must complete with zero lost requests and
full bit-parity.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _synth_bank(k: int, support, seed: int):
    """The deterministic synthetic bank (serve.bench's construction):
    seeded normal filters, unit-normalized — the same (k, support,
    seed) always yields the same bytes, which is what lets a replay
    rebuild the exact capture-side operator without shipping it."""
    import numpy as np

    r = np.random.default_rng(seed)
    d = r.normal(size=(k, *support)).astype(np.float32)
    axes = tuple(range(1, d.ndim))
    d /= np.sqrt((d**2).sum(axis=axes, keepdims=True))
    return d


def _build_fleet(meta, args, metrics_dir, capture_requests):
    """A fresh fleet pinned to the capture's recorded configuration."""
    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet

    geom_meta = meta.get("geom") or {}
    if args.filters:
        from ccsc_code_iccv2017_tpu.utils.io_mat import load_filters_2d

        d = load_filters_2d(args.filters)
        geom = ProblemGeom(d.shape[1:], d.shape[0])
    else:
        support = tuple(
            geom_meta.get("spatial_support") or (args.support,) * 2
        )
        k = int(geom_meta.get("num_filters") or args.k)
        d = _synth_bank(k, support, args.bank_seed)
        geom = ProblemGeom(support, k)
    solve = meta.get("solve") or {}
    cfg = SolveConfig(
        lambda_residual=float(solve.get("lambda_residual", 5.0)),
        lambda_prior=float(solve.get("lambda_prior", 0.3)),
        max_it=int(solve.get("max_it", 20)),
        tol=float(solve.get("tol", 0.0)),
        verbose="none",
        track_psnr=True,
        track_objective=True,
    )
    buckets = meta.get("buckets")
    if buckets:
        btab = tuple(
            (int(b["slots"]), tuple(int(s) for s in b["spatial"]))
            for b in buckets
        )
    else:
        # no recorded table (synthetic capture): one bucket over the
        # largest recorded request shape
        hi = max(
            (tuple(r.get("spatial") or ()) for r in capture_requests),
            default=(args.side,) * 2,
        )
        btab = ((args.slots, tuple(int(s) for s in hi)),)
    # re-resolve tuning under the capture's recorded mode: on the
    # same chip + tuned store this reproduces the arm the capture
    # was served under, which same-bucket bit parity depends on
    tune = str(meta.get("tune") or "off")
    if tune != "off":
        # stderr: --json consumers own stdout
        print(
            f"replay: capture was served with tune={tune!r} — "
            "re-resolving on this chip (bit parity holds only when "
            "the same arm is picked)",
            file=sys.stderr,
        )
    scfg = ServeConfig(
        buckets=btab, max_wait_ms=args.max_wait_ms, verbose="none",
        tune=tune,
    )
    fcfg = FleetConfig(
        replicas=args.replicas,
        metrics_dir=metrics_dir,
        # "" = capture explicitly OFF: a replay run in a shell that
        # still has CCSC_CAPTURE_DIR armed must never re-capture
        # itself into the capture it is replaying
        capture_dir="",
        min_queue_depth=max(64, 2 * len(capture_requests)),
        restart_backoff_s=0.05,
        verbose="none",
    )
    return ServeFleet(d, ReconstructionProblem(geom), cfg, scfg, fcfg)


def _print_report(rep, as_json=False):
    if as_json:
        print(json.dumps(rep, indent=1))
        return
    f = lambda v: "—" if v is None else f"{v:.1f}"
    speed = "max" if rep["speed"] <= 0 else f"{rep['speed']:g}x"
    print(
        f"replay[{rep['mode']}/{speed}]: {rep['n_replayed']}/"
        f"{rep['n_recorded']} replayed, {rep['n_exact']} bit-exact, "
        f"{rep['n_psnr']} psnr-matched, {rep['n_unverified']} "
        f"unverified, {rep['n_mismatched']} MISMATCHED, "
        f"{rep['n_lost']} LOST"
    )
    print(
        f"  latency p50 {f(rep['recorded_p50_ms'])} -> "
        f"{f(rep['replayed_p50_ms'])} ms, p99 "
        f"{f(rep['recorded_p99_ms'])} -> {f(rep['replayed_p99_ms'])} "
        f"ms (recorded -> replayed), "
        f"{rep['requests_per_sec']:.2f} req/s over "
        f"{rep['elapsed_s']:.2f}s"
    )
    if rep.get("replay_overload_backoffs"):
        print(
            f"  admission: {rep['replay_overload_backoffs']} overload "
            f"backoff(s) during replay vs {rep['recorded_rejected']} "
            "recorded rejection(s)"
        )


def _run_replay(args) -> int:
    from ccsc_code_iccv2017_tpu.serve.replay import ReplayDriver
    from ccsc_code_iccv2017_tpu.utils import env as _env

    # the driver parses meta + every segment once; reuse its state
    # for the emptiness check and the fleet reconstruction instead of
    # re-reading a potentially large capture
    driver = ReplayDriver(
        args.capture_dir,
        metrics_dir=args.metrics_dir,
        psnr_tol=args.psnr_tol,
        # --json promises a machine-readable stdout: the driver's
        # console line must not precede the JSON document
        verbose="none" if args.json else "brief",
    )
    if not driver.requests:
        print(
            f"replay: no captured requests under {args.capture_dir}",
            file=sys.stderr,
        )
        return 2
    speed = args.speed
    if speed is None:
        speed = (
            0.0 if args.max_speed
            else float(_env.env_float("CCSC_REPLAY_SPEED"))
        )
    fleet = _build_fleet(
        driver.meta, args, args.metrics_dir, driver.requests
    )
    try:
        rep = driver.replay(fleet, speed=speed, mode=args.mode)
    finally:
        fleet.close()
    _print_report(rep, as_json=args.json)
    return 0 if rep["ok"] else 1


def _run_generate(args) -> int:
    from ccsc_code_iccv2017_tpu.serve.replay import generate_diurnal

    generate_diurnal(
        args.generate_diurnal,
        n_requests=args.requests,
        duration_s=args.duration,
        spatial=(args.side, args.side),
        seed=args.seed,
    )
    print(
        f"generated {args.requests} diurnal request(s) over "
        f"{args.duration:g}s -> {args.generate_diurnal}"
    )
    return 0


def _run_demo(args) -> int:
    """The end-to-end acceptance story, self-contained on CPU."""
    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        FleetConfig,
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
    )
    from ccsc_code_iccv2017_tpu.serve import ServeFleet
    from ccsc_code_iccv2017_tpu.serve.replay import ReplayDriver

    # chaos_smoke owns the fault-env save/arm/reset discipline — one
    # implementation, shared
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from chaos_smoke import _fault
    finally:
        sys.path.pop(0)

    root = args.demo_dir or tempfile.mkdtemp(prefix="ccsc_replay_demo_")
    os.makedirs(root, exist_ok=True)
    cap_dir = os.path.join(root, "capture")
    k, support, seed = 4, (3, 3), 0
    d = _synth_bank(k, support, seed)
    geom = ProblemGeom(support, k)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=3, tol=0.0,
        verbose="none", track_psnr=True, track_objective=True,
    )
    scfg = ServeConfig(
        buckets=((2, (12, 12)),), max_wait_ms=2.0, verbose="none"
    )
    r = np.random.default_rng(0)

    print("demo 1/3: 3-replica fleet, kill+hang faults, capture on")
    with _fault(
        CCSC_FAULT_ENGINE_KILL_REQ=2,
        CCSC_FAULT_ENGINE_KILL_REPLICA="0",
        CCSC_FAULT_ENGINE_HANG_REQ=2,
        CCSC_FAULT_ENGINE_HANG_REPLICA="1",
        CCSC_FAULT_ENGINE_HANG_S="3.0",
        CCSC_WATCHDOG_MIN_S="0.5",
    ):
        fleet = ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            FleetConfig(
                replicas=3,
                metrics_dir=os.path.join(root, "serve-metrics"),
                capture_dir=cap_dir,
                min_queue_depth=64,
                restart_backoff_s=0.05,
                verbose="none",
            ),
        )
        futs = []
        for i in range(args.requests):
            x = r.random((12, 12)).astype(np.float32)
            m = (r.random((12, 12)) < 0.5).astype(np.float32)
            futs.append(
                fleet.submit(x * m, mask=m, x_orig=x, key=f"d{i}")
            )
        n_served = sum(1 for f in futs if f.result(timeout=300))
        fleet.close()
    print(f"  served {n_served}/{args.requests} under faults")

    rc = 0
    for label, speed in (("1x", 1.0), ("max-speed", 0.0)):
        print(f"demo {2 if speed else 3}/3: replay at {label}")
        fresh = ServeFleet(
            d, ReconstructionProblem(geom), cfg, scfg,
            FleetConfig(
                replicas=3,
                metrics_dir=os.path.join(root, f"replay-{label}"),
                capture_dir="",  # replay fleets never re-capture
                min_queue_depth=64,
                restart_backoff_s=0.05,
                verbose="none",
            ),
        )
        try:
            rep = ReplayDriver(
                cap_dir,
                metrics_dir=os.path.join(root, f"replay-{label}"),
            ).replay(fresh, speed=speed, mode="open")
        finally:
            fresh.close()
        _print_report(rep)
        if not rep["ok"] or rep["n_exact"] != rep["n_replayed"]:
            rc = 1
    print(
        ("demo PASSED" if rc == 0 else "demo FAILED")
        + f" — artifacts under {root} (obs_report the replay-* dirs "
        "for the REPLAY section)"
    )
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "capture_dir", nargs="?", default=None,
        help="capture directory to replay (serve.capture layout)",
    )
    ap.add_argument(
        "--filters", default=None,
        help=".mat/.npz bank the capture was served with (default: "
        "the deterministic synthetic bank from the capture's "
        "recorded geometry + --bank-seed)",
    )
    ap.add_argument("--bank-seed", type=int, default=0)
    ap.add_argument("--k", type=int, default=4,
                    help="synthetic-bank filters when meta lacks geom")
    ap.add_argument("--support", type=int, default=3)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument(
        "--speed", type=float, default=None,
        help="arrival-clock speed factor (default CCSC_REPLAY_SPEED; "
        "0 = max-speed)",
    )
    ap.add_argument(
        "--max-speed", action="store_true",
        help="saturation mode: submit back-to-back, honoring "
        "admission backpressure",
    )
    ap.add_argument("--mode", choices=("open", "closed"),
                    default="open")
    ap.add_argument("--psnr-tol", type=float, default=None)
    ap.add_argument(
        "--metrics-dir", default=None,
        help="obs stream dir of the replay session (REPLAY section "
        "of scripts/obs_report.py)",
    )
    ap.add_argument("--json", action="store_true")
    ap.add_argument(
        "--generate-diurnal", default=None, metavar="OUT_DIR",
        help="write a deterministic synthetic diurnal-curve capture "
        "and exit",
    )
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--duration", type=float, default=8.0)
    ap.add_argument("--side", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--demo", action="store_true",
        help="run the self-contained capture-under-faults -> "
        "replay-verify acceptance story",
    )
    ap.add_argument("--demo-dir", default=None)
    args = ap.parse_args(argv)

    if args.generate_diurnal:
        return _run_generate(args)
    if args.demo:
        return _run_demo(args)
    if not args.capture_dir:
        ap.error(
            "a CAPTURE_DIR (or --generate-diurnal / --demo) is "
            "required"
        )
    return _run_replay(args)


if __name__ == "__main__":
    sys.exit(main())
