#!/usr/bin/env python
"""Differential profile of the HS masked learner (VERDICT r4 weak #5).

The masked learner is the slowest family on chip relative to CPU
(6.9x vs 31.5x for the consensus learner, onchip_r4.jsonl). This
script attributes one outer step's wall-clock WITHOUT trusting stage
isolation (fusion makes separately-jitted stages add up to more than
the real step): it times the full jitted outer step at
(max_it_d, max_it_z) in {(10,10), (1,10), (10,1), (1,1)} and solves

    t(d,z) = fixed + d*per_d + z*per_z

for the per-inner-iteration costs of the d-ADMM and z-ADMM scans and
the fixed overhead (top-of-step FFT, Gram/Cholesky precompute, the
two objective evaluations). Runs at the family_bench operating point
(k=100 11x11x31, n=2 cubes 96^2) so the numbers tie to the 6.9x row.

Honors CCSC_FAMILY_FFTIMPL / CCSC_FAMILY_STORAGE / CCSC_FAMILY_CARRY
so the attribution can be repeated per execution strategy. Prints one
JSON line.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils import env as cenv
from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax
import jax.numpy as jnp


def time_step(b, geom, mk_cfg, d_it, z_it, reps=3):
    """Seconds per outer step at (max_it_d, max_it_z) = (d_it, z_it).

    Uses max_it=1 learn_masked calls: the first call compiles, later
    calls reuse the jit cache (the step is jitted on static cfg)."""
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked

    cfg = mk_cfg(d_it, z_it)
    learn_masked(b, geom, cfg)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        learn_masked(b, geom, cfg)  # obj floats fence each call
    return (time.perf_counter() - t0) / reps


def main():
    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom

    n = int(os.environ.get("HSP_N", 2))
    side = int(os.environ.get("HSP_SIDE", 96))
    bands = int(os.environ.get("HSP_BANDS", 31))
    k = int(os.environ.get("HSP_K", 100))
    fft_impl = cenv.env_str("CCSC_FAMILY_FFTIMPL")
    storage = cenv.env_str("CCSC_FAMILY_STORAGE")
    carry = cenv.env_flag("CCSC_FAMILY_CARRY")
    b = jax.random.uniform(
        jax.random.PRNGKey(0), (n, bands, side, side), jnp.float32
    )
    geom = ProblemGeom((11, 11), k, (bands,))

    def mk_cfg(d_it, z_it):
        return LearnConfig(
            max_it=1, max_it_d=d_it, max_it_z=z_it, tol=0.0,
            verbose="none", fft_impl=fft_impl, storage_dtype=storage,
            carry_freq=carry,
        )

    t = {}
    for d_it, z_it in ((10, 10), (1, 10), (10, 1), (1, 1)):
        t[(d_it, z_it)] = time_step(b, geom, mk_cfg, d_it, z_it)
    per_d = (t[(10, 10)] - t[(1, 10)]) / 9.0
    per_z = (t[(10, 10)] - t[(10, 1)]) / 9.0
    fixed = t[(1, 1)] - per_d - per_z
    full = t[(10, 10)]

    # Direct timing of the per-frequency Gram inverses hiding in the
    # fixed cost, per method, at the step's real shapes: the z-kernel
    # [F, W, W] (W=31 — above the schur window, whose m=31 recursion
    # tree is compile-pathological on axon and is not timed) and the
    # d-pass [F, n, n]. Answers whether the serialized batched
    # Cholesky custom-call is what the 308 ms fixed cost is made of,
    # and whether the Newton-Schulz matmul iteration buys it back.
    import numpy as np

    from ccsc_code_iccv2017_tpu.ops.freq_solvers import hermitian_inverse

    rng = np.random.default_rng(0)
    Sy, Sx = side + 10, side + 10  # support 11 -> radius 5
    F = Sy * (Sx // 2 + 1)
    inv_ms = {}
    for label, m, methods in (
        ("zkern_w31", bands, ("cholesky", "newton")),
        ("dgram_n2", n, ("cholesky", "schur", "newton")),
    ):
        A = rng.normal(size=(F, m, 2 * m)) + 1j * rng.normal(
            size=(F, m, 2 * m)
        )
        M = (A @ np.conj(np.swapaxes(A, -1, -2)) / (2 * m)
             + np.eye(m)).astype(np.complex64)
        # axon protocol (bench.py / streaming.py): upload as stacked
        # re/im REAL planes (eager complex transfers raise
        # UNIMPLEMENTED), form the complex batch inside jit, and fence
        # via a real-scalar readback (block_until_ready is a no-op on
        # the tunnel)
        g_ri = jax.device_put(
            np.stack([M.real, M.imag]).astype(np.float32)
        )
        for method in methods:

            @jax.jit
            def f(gri, _m=method):
                g = jax.lax.complex(gri[0], gri[1])
                return jnp.sum(jnp.abs(hermitian_inverse(g, _m)))

            float(f(g_ri))  # compile + warm + fence
            t0 = time.perf_counter()
            for _ in range(3):
                float(f(g_ri))
            inv_ms[f"{label}_{method}"] = round(
                (time.perf_counter() - t0) / 3 * 1e3, 2
            )
    print(json.dumps({
        "hs_profile": {
            "platform": jax.devices()[0].platform,
            "fft_impl": fft_impl,
            "storage_dtype": storage,
            "carry_freq": carry,
            "step_s_10_10": round(full, 4),
            "per_d_iter_ms": round(per_d * 1e3, 2),
            "per_z_iter_ms": round(per_z * 1e3, 2),
            "fixed_ms": round(fixed * 1e3, 2),
            "d_scan_pct": round(100 * 10 * per_d / full, 1),
            "z_scan_pct": round(100 * 10 * per_z / full, 1),
            "fixed_pct": round(100 * fixed / full, 1),
            "inverse_ms": inv_ms,
        }
    }))


if __name__ == "__main__":
    main()
