#!/usr/bin/env python
"""Render a run's telemetry stream (utils.obs JSONL) as a text dashboard.

Usage:
    python scripts/obs_report.py METRICS_DIR_OR_FILE [--json]

Sections: run header (identity/provenance), phase breakdown
(SectionTimers drains), step trajectory, roofline trajectory (per-chunk
it/s, MFU, HBM fraction), compile/recompile table, per-host heartbeat
timeline, fleet liveness, serving latency, SLO histograms/breaches,
QUALITY (served dB vs tenant floors, golden-probe timeline, drift
verdicts, demotion advisories, the shadow-score ledger table),
TRACES (the N slowest request timelines reassembled from span events),
checkpoint/recovery/preemption events, final summary. This is the
dashboard PERF.md sections are written from — and what bench.py points
at via its ``event_stream`` provenance field.

Works on a live (still-growing) stream: the reader drops a torn
trailing line, so the report is always renderable mid-run.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.serve import slo as _slo  # noqa: E402
from ccsc_code_iccv2017_tpu.utils import obs  # noqa: E402
from ccsc_code_iccv2017_tpu.utils import trace as _trace  # noqa: E402


def _fmt_ts(t):
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))


def _by_type(events):
    out = {}
    for e in events:
        out.setdefault(e.get("type", "?"), []).append(e)
    return out


def _section(title):
    return f"\n== {title} " + "=" * max(1, 64 - len(title))


def render(events, stale_after=None, n_traces=3, ledger_path=None,
           snapshot=None):
    """-> the dashboard string (pure function of the parsed records
    plus, optionally, the durable perf ledger).
    ``stale_after``: per-host liveness threshold in seconds (default:
    the watchdog's peer-staleness default, CCSC_WATCHDOG_PEER_STALE_S).
    ``n_traces``: how many slowest request timelines the TRACES
    section renders (0 keeps the section to counts only).
    ``ledger_path``: perf-ledger JSONL to render the LEDGER section
    from (per-key trend vs the robust history band); None skips it
    unless the stream itself carries ledger_append records.
    ``snapshot``: parsed metrics.prom freshness stamp
    (serve.metricsd.parse_snapshot_stamp + an ``age_wall_s`` the
    caller computes against its clock) — flagged STALE past
    ``stale_after`` so a metrics file left by a dead fleet is loud.
    """
    if stale_after is None:
        from ccsc_code_iccv2017_tpu.utils import env as _env

        stale_after = _env.env_float("CCSC_WATCHDOG_PEER_STALE_S")
    by = _by_type(events)
    lines = []

    metas = by.get("run_meta", [])
    lines.append("CCSC run telemetry report")
    if not events:
        lines.append("  (no records)")
        return "\n".join(lines)
    lines.append(
        f"  {len(events)} records, {_fmt_ts(events[0]['t'])} .. "
        f"{_fmt_ts(events[-1]['t'])}"
    )

    lines.append(_section("RUN"))
    fleet_meta = next(
        (m for m in reversed(metas)
         if m.get("algorithm") == "serve_fleet"),
        None,
    )
    if metas:
        # newest attempt; earlier metas = resumes. A merged fleet dir
        # is different: the replicas' own run_metas are SIBLING
        # streams, not resumes — the fleet's meta is the run identity.
        m = fleet_meta or metas[-1]
        cfgknobs = m.get("config") or {}
        lines.append(f"  algorithm     {m.get('algorithm')}")
        lines.append(f"  git sha       {m.get('git_sha')}")
        lines.append(
            f"  platform      {m.get('platform')} ({m.get('chip')}), "
            f"{m.get('device_count')} device(s), "
            f"{m.get('process_count', 1)} process(es)"
        )
        if m.get("mesh_shape"):
            lines.append(f"  mesh          {m['mesh_shape']}")
        if m.get("geom"):
            lines.append(f"  geom          {m['geom']}")
        if m.get("data_shape"):
            lines.append(f"  data          {m['data_shape']}")
        fp = m.get("fingerprint")
        lines.append(f"  fingerprint   {fp[:16] + '…' if fp else None}")
        if fleet_meta is not None:
            if len(metas) > 1:
                lines.append(
                    f"  streams       {len(metas)} (fleet + replica "
                    "engine streams, merged)"
                )
        elif len(metas) > 1:
            lines.append(f"  attempts      {len(metas)} (resumed run)")
        knob_keys = (
            "outer_chunk", "donate_state", "fft_impl", "fft_pad",
            "fused_z", "storage_dtype", "d_storage_dtype", "num_blocks",
            "carry_freq", "herm_inv", "tune", "max_it", "max_it_d",
            "max_it_z",
        )
        knobs = {k: cfgknobs[k] for k in knob_keys if k in cfgknobs}
        if knobs:
            lines.append(f"  knobs         {json.dumps(knobs)}")

    phases = by.get("phase", [])
    lines.append(_section("PHASES"))
    if phases:
        totals = {}
        for p in phases:
            for name, v in (p.get("sections") or {}).items():
                agg = totals.setdefault(name, {"s": 0.0, "n": 0})
                agg["s"] += v.get("s", 0.0)
                agg["n"] += v.get("n", 0)
        width = max(len(n) for n in totals)
        for name, agg in sorted(
            totals.items(), key=lambda kv: -kv[1]["s"]
        ):
            lines.append(
                f"  {name:<{width}}  {agg['s']:9.2f}s  x{agg['n']}"
            )
    else:
        lines.append("  (no phase records)")

    steps = by.get("step", [])
    lines.append(_section("STEPS"))
    if steps:
        first, last = steps[0], steps[-1]
        lines.append(f"  recorded      {len(steps)} step records")
        for label, s in (("first", first), ("last", last)):
            fields = ", ".join(
                f"{k}={s[k]:.4g}" if isinstance(s[k], float) else
                f"{k}={s[k]}"
                for k in ("it", "obj_d", "obj_z", "d_diff", "z_diff",
                          "obj", "diff", "consensus_dis", "nonfinite_z")
                if k in s
            )
            lines.append(f"  {label:<6} {fields}")
        bad = [s for s in steps if s.get("nonfinite_z")]
        if bad:
            lines.append(
                f"  NON-FINITE    {len(bad)} step(s) with nonfinite_z > 0, "
                f"first at it={bad[0]['it']}"
            )
    else:
        lines.append("  (no step records)")

    roofs = by.get("roofline", [])
    lines.append(_section("ROOFLINE"))
    if roofs:
        lines.append(
            "  iters      it/s        MFU    HBM frac   dt"
        )
        for r in roofs:
            span = (
                f"{r.get('start_it', 0) + 1}"
                f"..{r.get('start_it', 0) + r.get('n_adopted', 0)}"
            )
            mfu = r.get("mfu")
            hbm = r.get("hbm_frac")
            lines.append(
                f"  {span:<9}  {r.get('it_per_sec', 0.0):8.3f}  "
                + (f"{100 * mfu:7.2f}%" if mfu is not None else "      —")
                + "  "
                + (f"{100 * hbm:8.2f}%" if hbm is not None else "       —")
                + f"  {r.get('dt_s', 0.0):6.2f}s"
            )
        chip = next((r["chip"] for r in roofs if r.get("chip")), None)
        if chip:
            lines.append(f"  (scored against the {chip} roofline, "
                         "utils.perfmodel)")
    else:
        lines.append("  (no roofline records)")

    compiles = [
        c for c in by.get("compile", []) if c.get("kind") == "compile"
    ]
    lines.append(_section("COMPILES"))
    summary = next(
        (s.get("compile") for s in reversed(by.get("summary", []))
         if s.get("compile")),
        None,
    )
    if compiles or summary:
        by_fun = {}
        for c in compiles:
            key = c.get("fun_name") or "<unknown>"
            agg = by_fun.setdefault(key, {"n": 0, "s": 0.0, "shapes": None})
            agg["n"] += 1
            agg["s"] += c.get("duration_s", 0.0)
            agg["shapes"] = agg["shapes"] or c.get("shapes")
        if not by_fun and summary:
            by_fun = {
                f: {"n": n, "s": 0.0, "shapes": None}
                for f, n in summary.get("compiles_by_fun", {}).items()
            }
        width = min(44, max((len(f) for f in by_fun), default=8))
        for fun, agg in sorted(by_fun.items(), key=lambda kv: -kv[1]["n"]):
            flag = "  <-- RECOMPILED" if agg["n"] > 1 else ""
            lines.append(
                f"  {fun[:width]:<{width}}  x{agg['n']:<3} "
                f"{agg['s']:8.3f}s{flag}"
            )
        if summary:
            lines.append(
                f"  total: {summary.get('n_compiles')} backend compiles, "
                f"{summary.get('compile_time_s')}s compiling, "
                f"{summary.get('trace_time_s')}s tracing"
            )
            if summary.get("recompiled_funs"):
                lines.append(
                    "  recompiled: "
                    + ", ".join(summary["recompiled_funs"])
                    + "  (expected only for partial chunks / "
                    "post-recovery rho rebuilds)"
                )
    else:
        lines.append("  (no compile records)")

    hbs = by.get("heartbeat", [])
    lines.append(_section("HOSTS"))
    if hbs:
        # liveness is judged against the run's own clock line (the
        # newest record anywhere in the stream): a host is STALE
        # because the OTHERS kept going after it went quiet — the same
        # staleness rule the watchdog applies live
        # (utils.watchdog.check_peers)
        now = max(e.get("t", 0.0) for e in events)
        hosts = {}
        for h in hbs:
            hosts.setdefault(h.get("host", 0), []).append(h)
        for host in sorted(hosts):
            hs = hosts[host]
            gaps = [
                b["t"] - a["t"] for a, b in zip(hs, hs[1:])
            ]
            lat = max(h.get("fence_latency_s", 0.0) for h in hs)
            behind = now - hs[-1]["t"]
            # staleness is a RELATIVE signal — one host quiet while
            # others kept going. With a single host there are no
            # others: post-loop finalization (final eval, summary)
            # legitimately outlasts the threshold, and the live
            # watchdog skips the check below 2 processes too.
            live = (
                f"STALE ({behind:.0f}s behind — the watchdog would "
                "declare this host dead)"
                if behind > stale_after and len(hosts) > 1
                else "live"
            )
            lines.append(
                f"  host {host}: {live:<7} {len(hs)} heartbeats, steps "
                f"{hs[0].get('step')}..{hs[-1].get('step')}, last "
                f"{_fmt_ts(hs[-1]['t'])}, max gap "
                f"{max(gaps):.1f}s, max fence {lat:.3f}s"
                if gaps else
                f"  host {host}: {live:<7} {len(hs)} heartbeat, step "
                f"{hs[0].get('step')}, at {_fmt_ts(hs[0]['t'])}, "
                f"fence {lat:.3f}s"
            )
        lines.append(
            f"  (stale threshold {stale_after:g}s; --stale-after)"
        )
    else:
        lines.append("  (no heartbeat records)")

    tpicks = by.get("tune_pick", [])
    tguards = by.get("tune_guard", [])
    tarms = by.get("tune_arm", [])
    if tpicks or tguards or tarms:
        lines.append(_section("TUNING"))
        if tarms:
            lines.append(f"  sweep         {len(tarms)} arm(s) timed")
            ok_arms = [a for a in tarms if "value" in a]
            for a in sorted(
                ok_arms, key=lambda a: -a.get("value", 0.0)
            )[:8]:
                lines.append(
                    f"    {a.get('value', 0.0):>10.4g} "
                    f"{a.get('unit', '')}  {json.dumps(a.get('arm'))}"
                )
            failed = [a for a in tarms if "error" in a]
            if failed:
                lines.append(
                    f"    ({len(failed)} arm(s) failed to run)"
                )
        for g in tguards:
            verdict = "pass" if g.get("ok") else "FAIL -> demoted"
            lines.append(
                f"  guard         {verdict}  dev={g.get('dev')} "
                f"tol={g.get('tol')}  {json.dumps(g.get('arm'))}"
            )
        for p in tpicks:
            if p.get("arm") is not None:
                lines.append(
                    f"  applied       {json.dumps(p.get('arm'))} "
                    f"({p.get('value')} {p.get('unit')}, "
                    f"{p.get('source')}) on {p.get('chip')} "
                    f"{p.get('shape_key')}"
                )
                if p.get("dropped"):
                    lines.append(
                        f"    dropped for this workload: "
                        f"{json.dumps(p['dropped'])}"
                    )
            else:
                lines.append(
                    f"  not applied   {p.get('reason')} "
                    f"({p.get('chip')} {p.get('shape_key')})"
                )

    fhbs = by.get("fleet_heartbeat", [])
    fstart = by.get("fleet_start", [])
    if fhbs or fstart:
        from ccsc_code_iccv2017_tpu.utils import watchdog as _wd

        lines.append(_section("FLEET"))
        if fstart:
            s = fstart[-1]
            lines.append(
                f"  fleet         {s.get('replicas')} replica(s), "
                f"queue ceiling {s.get('queue_ceiling')} "
                f"({s.get('ceiling_source')})"
            )
        ceils = by.get("fleet_ceiling", [])
        if ceils:
            c = ceils[-1]
            lines.append(
                f"  ceiling       {c.get('ceiling')} "
                f"(serving_bound {c.get('bound_requests_per_sec')} "
                f"req/s x {c.get('live_replicas')} live replica(s))"
            )
        # per-replica liveness: the SAME staleness rule as the HOSTS
        # column and the live watchdog (--stale-after)
        for r in _wd.check_replicas(
            events=events, stale_s=stale_after
        ):
            live = (
                f"STALE ({r['behind_s']:.0f}s behind)"
                if r["stale"] and r["state"] == "live"
                else r["state"]
            )
            lines.append(
                f"  replica {r['replica']}: {live:<9} "
                f"served {r.get('served')}, "
                f"restarts {r.get('restarts')}, last heartbeat "
                f"{_fmt_ts(r['last_t'])}"
            )
        if fhbs:
            lines.append(
                f"  (stale threshold {stale_after:g}s; --stale-after)"
            )
        reqs = by.get("fleet_requeue", [])
        n_requeued = sum(r.get("n", 0) for r in reqs)
        n_req_failed = sum(r.get("n_failed", 0) for r in reqs)
        if reqs:
            lines.append(
                f"  requeues      {n_requeued} request(s) handed off "
                f"over {len(reqs)} drain(s)"
                + (f", {n_req_failed} failed out" if n_req_failed else "")
            )
        dups = by.get("fleet_duplicate_suppressed", [])
        if dups:
            lines.append(
                f"  duplicates    {len(dups)} late straggler "
                "result(s) suppressed (at-most-once delivery)"
            )
        rejects = by.get("fleet_admission_reject", [])
        if rejects:
            lines.append(
                f"  admission     {len(rejects)} rejection(s), max "
                "queue depth at rejection "
                f"{max(r.get('queue_depth', 0) for r in rejects)}"
            )
        n_served = len(by.get("fleet_request", []))
        if n_served:
            lines.append(f"  delivered     {n_served} request(s)")

    # -- CONTROLLER: the capacity control plane (serve.controller).
    # Every ctrl_decision carries the sensor snapshot that justified
    # it, so this section can replay WHY capacity moved: the decision
    # timeline, the replica-count sparkline over fleet_scale events,
    # actuation outcomes, holdoffs by reason, and breaker state.
    decisions = by.get("ctrl_decision", [])
    scales = by.get("ctrl_scale", [])
    brownouts = by.get("ctrl_brownout", [])
    holdoffs = by.get("ctrl_holdoff", [])
    if decisions or scales or brownouts or holdoffs:
        lines.append(_section("CONTROLLER"))
        # replica-count sparkline: the fleet's target over time
        # (fleet_start anchor + every fleet_scale transition)
        counts = []
        if fstart:
            counts.append(fstart[-1].get("replicas") or 0)
        for e in by.get("fleet_scale", []):
            if e.get("to_n") is not None:
                counts.append(e["to_n"])
        if counts:
            blocks = "▁▂▃▄▅▆▇█"
            lo, hi = min(counts), max(counts)
            span = max(1, hi - lo)
            spark = "".join(
                blocks[
                    min(
                        len(blocks) - 1,
                        (c - lo) * (len(blocks) - 1) // span,
                    )
                ]
                for c in counts
            )
            lines.append(
                f"  replicas      {spark}  ({lo}..{hi}, now "
                f"{counts[-1]})"
            )
        ok_scales = [s for s in scales if s.get("ok")]
        failed_scales = [s for s in scales if not s.get("ok")]
        if scales:
            ups = sum(
                1 for s in ok_scales if s.get("direction") == "up"
            )
            downs = sum(
                1 for s in ok_scales if s.get("direction") == "down"
            )
            lines.append(
                f"  scaling       {ups} up, {downs} down"
                + (
                    f", {len(failed_scales)} FAILED actuation(s)"
                    if failed_scales
                    else ""
                )
            )
        if brownouts:
            n_on = sum(1 for b in brownouts if b.get("on"))
            last = brownouts[-1]
            lines.append(
                f"  brownout      {n_on} engagement(s), now "
                + ("ON" if last.get("on") else "off")
                + f" ({last.get('reason')})"
            )
        if holdoffs:
            by_reason = {}
            for h in holdoffs:
                r = str(h.get("reason"))
                by_reason[r] = by_reason.get(r, 0) + 1
            parts = ", ".join(
                f"{r} x{n}"
                for r, n in sorted(
                    by_reason.items(), key=lambda kv: -kv[1]
                )
            )
            lines.append(f"  holdoffs      {len(holdoffs)} ({parts})")
            n_breaker = sum(
                n for r, n in by_reason.items()
                if r.startswith("breaker_open")
            )
            if n_breaker:
                lines.append(
                    f"  breaker       OPENED (suppressed {n_breaker} "
                    "invocation(s)) — see fault_fired/ctrl timeline"
                )
        # decision timeline: the newest few, each with the sensor
        # snapshot that justified it
        for d in decisions[-8:]:
            snap = d.get("snapshot") or {}
            depth = snap.get("queue_depth")
            ceil = snap.get("ceiling")
            p99 = snap.get("p99_ms")
            lines.append(
                f"  {_fmt_ts(d.get('t', 0.0))}  {d.get('action'):<13}"
                f" {d.get('reason'):<18} depth {depth}/{ceil}"
                + (f", p99 {p99}ms" if p99 is not None else "")
                + (
                    f", {snap.get('live_replicas')} live"
                    f"/{snap.get('replica_target')} target"
                    if snap.get("replica_target") is not None
                    else ""
                )
            )
        if len(decisions) > 8:
            lines.append(
                f"  ({len(decisions) - 8} earlier decision(s) not "
                "shown)"
            )

    # -- FEDERATION: the cross-host pool over the durable file-lease
    # queue (serve.dqueue / serve.federation). Per-host liveness uses
    # the SAME staleness rule as HOSTS/FLEET (--stale-after): a host
    # whose newest fed_heartbeat lags the stream's newest record by
    # more than the threshold is flagged — a SIGKILLed host shows up
    # here before its leases even expire.
    fed_hbs = by.get("fed_heartbeat", [])
    fed_joins = by.get("fed_join", [])
    dq_subs = by.get("dqueue_submit", [])
    if fed_hbs or fed_joins or dq_subs:
        lines.append(_section("FEDERATION"))
        stream_now = max((e.get("t", 0.0) for e in events), default=0.0)
        newest = {}
        for e in fed_hbs + fed_joins:
            h = e.get("host")
            if h is None:
                continue
            if h not in newest or e.get("t", 0.0) > newest[h].get(
                "t", 0.0
            ):
                newest[h] = e
        # newest fed_leave per host: 'left' only when no NEWER
        # join/heartbeat follows it (a supervised host that left and
        # was restarted into a fresh epoch is live again, not left)
        left_t = {}
        for e in by.get("fed_leave", []):
            h = e.get("host")
            if h is not None:
                left_t[h] = max(left_t.get(h, 0.0), e.get("t", 0.0))
        for h in sorted(newest):
            e = newest[h]
            behind = stream_now - e.get("t", 0.0)
            if left_t.get(h, -1.0) >= e.get("t", 0.0):
                state = "left"
            elif behind > stale_after:
                state = f"STALE ({behind:.0f}s behind)"
            else:
                state = "live"
            lines.append(
                f"  host {h}: {state}, epoch {e.get('epoch')}, "
                f"served {e.get('served', 0)}, leased "
                f"{e.get('leased', 0)}, last heartbeat "
                f"{_fmt_ts(e.get('t', 0.0))}"
            )
        if newest:
            lines.append(
                f"  (stale threshold {stale_after:g}s; --stale-after)"
            )
        dq_req = by.get("dqueue_requeue", [])
        n_cross = sum(
            1 for e in dq_req
            if e.get("from_host") != e.get("by_host")
        )
        lines.append(
            f"  queue         {len(dq_subs)} submitted, "
            f"{len(by.get('dqueue_claim', []))} claimed, "
            f"{len(by.get('dqueue_complete', []))} completed, "
            f"{len(by.get('dqueue_failed', []))} failed, "
            f"{len(by.get('dqueue_suppressed', []))} suppressed"
        )
        if dq_req:
            lines.append(
                f"  requeues      {len(dq_req)} lease hand-off(s), "
                f"{n_cross} across hosts (dead-owner leases reaped "
                "by survivors)"
            )

    sreqs = by.get("serve_request", [])
    sdisp = by.get("serve_dispatch", [])
    if sreqs or sdisp:
        lines.append(_section("SERVING"))
        # one percentile implementation across engine/fleet stats(),
        # the serve bench record, and this report: the log-bucketed
        # serving histogram (serve.slo.Histogram)
        lat_h = _slo.Histogram.of(
            r.get("latency_ms", 0.0) for r in sreqs
        )
        pct = lambda q: (
            lat_h.percentile(q)
            if lat_h.percentile(q) is not None
            else float("nan")
        )

        if sreqs:
            wait_h = _slo.Histogram.of(
                r.get("wait_ms", 0.0) for r in sreqs
            )
            wait_p50 = wait_h.percentile(0.5)
            wait_p50 = float("nan") if wait_p50 is None else wait_p50
            lines.append(
                f"  requests      {len(sreqs)} served, latency p50 "
                f"{pct(0.5):.1f} ms / p99 {pct(0.99):.1f} ms, queue "
                f"wait p50 {wait_p50:.1f} ms"
            )
        if sdisp:
            occ = sum(d.get("occupancy", 0.0) for d in sdisp) / len(sdisp)
            depth = max(d.get("queue_depth", 0) for d in sdisp)
            lines.append(
                f"  dispatches    {len(sdisp)}, mean bucket occupancy "
                f"{100 * occ:.0f}%, max queue depth {depth}"
            )
            per = {}
            for d_ in sdisp:
                agg = per.setdefault(
                    d_.get("bucket", "?"), {"n": 0, "req": 0, "occ": 0.0}
                )
                agg["n"] += 1
                agg["req"] += d_.get("n", 0)
                agg["occ"] += d_.get("occupancy", 0.0)
            for bname in sorted(per):
                agg = per[bname]
                lines.append(
                    f"    {bname:<14} {agg['n']:4d} dispatch(es), "
                    f"{agg['req']:4d} request(s), occupancy "
                    f"{100 * agg['occ'] / agg['n']:.0f}%"
                )
        # request-lifecycle plane (serve.fleet deadlines/hedging):
        # how many requests the fleet REFUSED to waste work on
        # (deadline expiries by lifecycle point, cooperative
        # cancellations) and what hedging did about gray replicas
        hsp = by.get("hedge_spawn", [])
        hwin = by.get("hedge_win", [])
        hlost = by.get("hedge_lost", [])
        gray = by.get("fleet_gray_replica", [])
        if hsp or hwin or hlost or gray:
            lines.append(
                f"  hedging       {len(hsp)} hedge(s) spawned, "
                f"{len(hwin)} won, {len(hlost)} lost "
                "(duplicates suppressed)"
            )
            for g in gray:
                lines.append(
                    f"    gray replica {g.get('replica_id')}: p50 "
                    f"{g.get('p50_ms')} ms vs fleet p50 "
                    f"{g.get('fleet_p50_ms')} ms "
                    f"({g.get('factor')}x outlier)"
                )
        dle = by.get("deadline_exceeded", [])
        canc = by.get("request_cancelled", [])
        if dle or canc:
            where = {}
            for e in dle:
                w = str(e.get("where", "?"))
                where[w] = where.get(w, 0) + 1
            by_where = ", ".join(
                f"{k} {v}" for k, v in sorted(where.items())
            )
            lines.append(
                f"  deadlines     {len(dle)} exceeded"
                + (f" ({by_where})" if by_where else "")
                + f", {len(canc)} cancelled"
            )
        warm = by.get("serve_ready", [])
        if warm:
            w = warm[-1]
            lines.append(
                f"  warmup        {w.get('n_buckets')} bucket(s) in "
                f"{w.get('warmup_s')}s, persistent cache hits "
                f"{w.get('persistent_cache_hits')}"
            )
            if w.get("knobs"):
                # the resolved arm every request was served under
                # (serve_warmup/serve_ready knob dict)
                lines.append(
                    f"  served under  {json.dumps(w['knobs'])}"
                )
            # per-replica device topology (newest serve_ready per
            # replica): a mesh replica serves its buckets from
            # prod(mesh_shape) devices via shard_map
            topo = {}
            for e in warm:
                topo[e.get("replica_id", 0)] = e
            if len(topo) > 1 or any(
                (t.get("devices") or 1) > 1 for t in topo.values()
            ):
                for rid in sorted(topo, key=lambda r: (r is None, r)):
                    t = topo[rid]
                    mesh = t.get("mesh")
                    lines.append(
                        f"  replica {rid}: "
                        f"{t.get('devices') or 1} device(s)"
                        + (
                            "  mesh "
                            + "x".join(str(a) for a in mesh)
                            if mesh
                            else "  single-device"
                        )
                    )
            # mixed-fleet ceiling sanity: with mesh and single-device
            # replicas in one fleet, the derived admission bound must
            # credit each replica's device count
            # (perfmodel.fleet_serving_bound) — live throughput
            # EXCEEDING the derived bound by >20% means the ceiling
            # math under-counted somebody's devices and is rejecting
            # load the fleet demonstrably carries
            dev_set = {t.get("devices") or 1 for t in topo.values()}
            ceils = by.get("fleet_ceiling", [])
            freq_evs = by.get("fleet_request", [])
            if len(dev_set) > 1 and ceils and len(freq_evs) >= 2:
                bound = ceils[-1].get("bound_requests_per_sec") or 0.0
                ts = [e.get("t", 0.0) for e in freq_evs]
                span = max(ts) - min(ts)
                achieved = (
                    (len(freq_evs) - 1) / span if span > 0 else 0.0
                )
                if bound > 0 and achieved > 1.2 * bound:
                    lines.append(
                        f"  CEILING MISMATCH  live throughput "
                        f"{achieved:.2f} req/s exceeds the derived "
                        f"bound {bound:.2f} req/s by >20% on a mixed "
                        "mesh/single-device fleet — the admission "
                        "ceiling is under-crediting device counts "
                        "(utils.perfmodel.fleet_serving_bound)"
                    )
        if summary and summary.get("persistent_cache_hits") is not None:
            lines.append(
                f"  compile cache {summary['persistent_cache_hits']} "
                f"hit(s), {summary.get('persistent_cache_misses')} "
                "miss(es) over the run"
            )

    stages = by.get("warmup_stage", [])
    afetch = by.get("artifact_fetch", [])
    apub = by.get("artifact_publish", [])
    bcold = by.get("bucket_cold", [])
    if stages or afetch or apub:
        lines.append(_section("WARMUP"))
        # per-bucket ready timeline (hot-to-cold staged order):
        # when each program became serveable, and from where
        for st in stages:
            lines.append(
                f"  stage {st.get('stage')}/{st.get('n_stages')}  "
                f"{st.get('bucket', '?'):<14} ready at "
                f"{st.get('ready_s', 0.0):7.3f}s  "
                f"[{st.get('source', '?')}]"
            )
        n_src = {}
        for st in stages:
            n_src[st.get("source", "?")] = (
                n_src.get(st.get("source", "?"), 0) + 1
            )
        if n_src:
            lines.append(
                "  sources       "
                + ", ".join(
                    f"{n_src[s]} {s}" for s in sorted(n_src)
                )
            )
        ready = by.get("serve_ready", [])
        newest_ready = ready[-1] if ready else None
        if newest_ready and newest_ready.get(
            "first_ready_s"
        ) is not None:
            w = newest_ready
            lines.append(
                f"  join->first-request "
                f"{w['first_ready_s']}s (all "
                f"{w.get('n_buckets')} bucket(s) in "
                f"{w.get('warmup_s')}s"
                + (", staged" if w.get("staged") else ", blocking")
                + ")"
            )
        if afetch:
            n_st = {}
            for e in afetch:
                n_st[e.get("status", "?")] = (
                    n_st.get(e.get("status", "?"), 0) + 1
                )
            lines.append(
                "  store fetches "
                + ", ".join(
                    f"{n_st[s]} {s}" for s in sorted(n_st)
                )
            )
        if apub:
            n_st = {}
            for e in apub:
                n_st[e.get("status", "?")] = (
                    n_st.get(e.get("status", "?"), 0) + 1
                )
            lines.append(
                "  store publishes "
                + ", ".join(
                    f"{n_st[s]} {s}" for s in sorted(n_st)
                )
            )
        if bcold:
            lines.append(
                f"  cold refusals {len(bcold)} (bucket_cold "
                "retry-after admissions while staging)"
            )

    shists = by.get("slo_histogram", [])
    sbreach = by.get("slo_breach", [])
    sprof = by.get("slo_profile", [])
    # tenant-stamped records belong to the TENANTS section below —
    # mixed into the fleet-wide keys here, a tenant's (smaller, later)
    # histogram would silently overwrite the 'total [fleet]' row
    fleet_hists = [h for h in shists if not h.get("tenant")]
    fleet_breach = [b for b in sbreach if not b.get("tenant")]
    if fleet_hists or fleet_breach:
        lines.append(_section("SLO"))
        # newest snapshot per (phase, scope): histograms are
        # cumulative, so the last record IS the run's distribution —
        # percentiles recomputed offline from the stream alone
        newest = {}
        for h in fleet_hists:
            newest[(h.get("phase"), h.get("replica_id"))] = h
        for (phase, rid), h in sorted(
            newest.items(), key=lambda kv: (str(kv[0][0]), str(kv[0][1]))
        ):
            hist = _slo.from_snapshot(h)
            scope = "fleet" if rid is None else f"replica {rid}"
            f = lambda v: "—" if v is None else f"{v:.1f}"
            lines.append(
                f"  {phase:<8} [{scope}]  n={hist.n}  p50 "
                f"{f(hist.percentile(0.50))} ms  p95 "
                f"{f(hist.percentile(0.95))} ms  p99 "
                f"{f(hist.percentile(0.99))} ms  max "
                f"{hist.max_ms:.1f} ms"
            )
        if fleet_breach:
            lines.append(f"  breaches      {len(fleet_breach)}")
            for b in fleet_breach[-5:]:
                lines.append(
                    f"    {_fmt_ts(b['t'])}  p"
                    f"{int(100 * b.get('quantile', 0))} "
                    f"{b.get('observed_ms')} ms > target "
                    f"{b.get('target_ms')} ms (n={b.get('n')})"
                )
        for p in sprof:
            lines.append(
                f"  xprof capture {p.get('trace_dir')} (armed by an "
                "SLO breach; scripts/xprof_report.py attributes it)"
            )

    # -- TENANTS: per-tenant latency vs declared targets, quota
    # rejections, and bank swap history (serve.tenancy /
    # serve.registry) ------------------------------------------------
    t_hists = [h for h in shists if h.get("tenant")]
    t_rejects = by.get("tenant_reject", [])
    swaps = by.get("bank_swap", [])
    pubs = by.get("bank_publish", [])
    if t_hists or t_rejects or swaps or pubs:
        lines.append(_section("TENANTS"))
        newest_t = {}
        for h in t_hists:
            newest_t[h["tenant"]] = h  # cumulative: last wins
        t_breached = {
            b.get("tenant")
            for b in sbreach
            if b.get("tenant")
        }
        for tenant in sorted(newest_t):
            h = newest_t[tenant]
            hist = _slo.from_snapshot(h)

            def _vs(q, target):
                v = hist.percentile(q)
                if v is None:
                    return "—"
                s = f"{v:.1f} ms"
                if target:
                    s += (
                        f" > target {target:g}"
                        if v > target
                        else f" (target {target:g})"
                    )
                return s

            flag = "  <-- SLO BREACHED" if tenant in t_breached else ""
            lines.append(
                f"  {tenant:<12} n={hist.n}  p50 "
                f"{_vs(0.50, h.get('target_p50_ms'))}  p99 "
                f"{_vs(0.99, h.get('target_p99_ms'))}{flag}"
            )
        if t_rejects:
            per_rej = {}
            for e in t_rejects:
                agg = per_rej.setdefault(
                    e.get("tenant", "?"), {"n": 0, "quota": None}
                )
                agg["n"] += 1
                agg["quota"] = e.get("quota")
            for tenant in sorted(per_rej):
                agg = per_rej[tenant]
                lines.append(
                    f"  rejections    {tenant}: {agg['n']} quota "
                    f"refusal(s) (quota {agg['quota']}) — explicit "
                    "Overloaded, other tenants unaffected"
                )
        for p_ in pubs:
            lines.append(
                f"  published     {_fmt_ts(p_['t'])}  "
                f"{p_.get('bank_id')} @ {p_.get('digest')}"
                + (
                    f" (tenant {p_['tenant']})"
                    if p_.get("tenant") else ""
                )
            )
        for s in swaps:
            scope = (
                "fleet" if s.get("replica_id") is None
                else f"replica {s['replica_id']}"
            )
            lines.append(
                f"  bank swap     {_fmt_ts(s['t'])}  "
                f"{s.get('bank_id') or '<default>'}: "
                f"{s.get('old_digest') or '(first publish)'} -> "
                f"{s.get('new_digest')}  [{scope}]"
                + (
                    f" (tenant {s['tenant']})"
                    if s.get("tenant") else ""
                )
            )

    # -- QUALITY: the quality observatory (serve.quality) — served
    # dB per (bank, tenant, bucket) vs declared tenant floors, solve
    # diagnostics read back at the dispatch fences, the golden-probe
    # timeline, drift verdicts vs ledger history, demotion
    # advisories, and the shadow-score table quality_gate.py judges.
    q_hists = by.get("quality_histogram", [])
    q_breach = by.get("quality_breach", [])
    q_diags = by.get("quality_solve_diag", [])
    q_probes = by.get("quality_probe", [])
    q_pbreach = by.get("quality_probe_breach", [])
    q_drift = by.get("quality_drift", [])
    q_advice = by.get("quality_demote_advice", [])
    if q_hists or q_probes or q_drift or q_advice or q_breach:
        lines.append(_section("QUALITY"))
        # newest snapshot per (bank, tenant, bucket): cumulative, so
        # the last record IS the served-dB distribution. dB is
        # better-is-higher, so the bad tail is the LOW percentiles —
        # p10 is rendered where a latency section would render p99.
        newest_q = {}
        for h in q_hists:
            key = (
                h.get("bank_id"), h.get("tenant"), h.get("bucket"),
                h.get("replica_id"),
            )
            newest_q[key] = h
        breached_tenants = {
            b.get("tenant"): b for b in q_breach if b.get("tenant")
        }
        for key in sorted(
            newest_q, key=lambda k: tuple(str(x) for x in k)
        ):
            bank_id, tenant, bucket, rid = key
            if rid is not None and (
                (bank_id, tenant, bucket, None) in newest_q
            ):
                continue  # fleet-scope row supersedes replica rows
            hist = _slo.from_snapshot(newest_q[key])
            f = lambda v: "—" if v is None else f"{v:.2f}"
            br = breached_tenants.get(tenant)
            flag = (
                f"  <-- BELOW FLOOR {br['min_psnr_db']:g} dB"
                if br is not None else ""
            )
            lines.append(
                f"  {(bank_id or '<default>'):<12} "
                f"tenant={tenant or '—':<8} {bucket or '—':<12} "
                f"n={hist.n}  p50 {f(hist.percentile(0.50))} dB  "
                f"p10 {f(hist.percentile(0.10))} dB{flag}"
            )
        # solve diagnostics: newest per bucket (on-device objective
        # split + stop reasons, read back at the existing fences)
        newest_d = {}
        for d_ in q_diags:
            newest_d[d_.get("bucket")] = d_
        for bname in sorted(newest_d, key=str):
            d_ = newest_d[bname]
            extra = (
                f", obj fid/l1 {d_['obj_fid_mean']:.4g}"
                f"/{d_['obj_l1_mean']:.4g}"
                if d_.get("obj_fid_mean") is not None else ""
            )
            lines.append(
                f"  solve {bname:<12} n={d_.get('n')}  iters "
                f"{d_.get('iters_mean')}  tol-stop "
                f"{100 * (d_.get('tol_stop_frac') or 0):.0f}%  "
                f"maxit-stop "
                f"{100 * (d_.get('maxit_stop_frac') or 0):.0f}%  "
                f"nonfinite {d_.get('nonfinite')}{extra}"
            )
        if q_probes:
            n_st = {}
            for p_ in q_probes:
                n_st[p_.get("status", "?")] = (
                    n_st.get(p_.get("status", "?"), 0) + 1
                )
            lines.append(
                f"  probes        {len(q_probes)} sweep result(s): "
                + ", ".join(
                    f"{n_st[s]} {s}" for s in sorted(n_st)
                )
            )
            for p_ in q_pbreach[-5:]:
                lines.append(
                    f"    {_fmt_ts(p_['t'])}  BREACH {p_.get('probe')}"
                    f"  bank {p_.get('bank_id') or '<default>'} @ "
                    f"{(p_.get('digest') or '?')[:12]}: "
                    f"{p_.get('db')} dB < ref {p_.get('ref_db')} dB"
                )
        for d_ in q_drift[-5:]:
            lines.append(
                f"  drift         {_fmt_ts(d_['t'])}  bank "
                f"{d_.get('bank_id') or '<default>'} @ "
                f"{(d_.get('digest') or '?')[:12]}: rolling "
                f"{d_.get('rolling_db')} dB < band lo "
                f"{d_.get('band_lo')} dB (history median "
                f"{d_.get('median')} over {d_.get('n_history')})"
            )
        for a in q_advice:
            lines.append(
                f"  DEMOTE ADVICE {_fmt_ts(a['t'])}  bank "
                f"{a.get('bank_id') or '<default>'}: "
                f"{(a.get('from_digest') or '?')[:12]} -> "
                f"{(a.get('to_digest') or '(no prior digest)')[:12]}"
                f"  [{a.get('reason')}] — advisory only; the "
                "operator decides the rollback"
            )
        # shadow-score table: the kind=quality ledger history the
        # publish-time gate judges (scripts/quality_gate.py)
        if ledger_path and os.path.exists(ledger_path):
            from ccsc_code_iccv2017_tpu.analysis import (  # noqa: E402
                ledger as _ledger,
            )
            from ccsc_code_iccv2017_tpu.serve import (  # noqa: E402
                quality as _quality,
            )

            qled = _ledger.Ledger(ledger_path)
            for key, recs in sorted(qled.by_key().items()):
                recs = [
                    r for r in recs if r.get("kind") == "quality"
                ]
                if not recs:
                    continue
                band = _quality.quality_band(
                    [r["value"] for r in recs]
                )
                digests = {}
                for r in recs:
                    dg = (r.get("digest") or "?")[:12]
                    digests[dg] = digests.get(dg, 0) + 1
                lines.append(
                    f"  shadow scores {key}\n"
                    f"    n={len(recs)}  newest "
                    f"{recs[-1]['value']:.2f} dB  median "
                    f"{(band['median'] if band else 0.0):.2f} dB  "
                    f"band lo {(band['lo'] if band else 0.0):.2f} dB"
                    f"  [" + ", ".join(
                        f"{dg}x{n}"
                        for dg, n in sorted(digests.items())
                    ) + "]"
                )

    # -- SNAPSHOT: metrics.prom freshness (serve.metricsd stamp) -----
    if snapshot:
        lines.append(_section("SNAPSHOT"))
        age = snapshot.get("age_wall_s")
        stale = age is not None and age > stale_after
        lines.append(
            f"  metrics.prom  run {snapshot.get('run_id')}, written "
            f"{_fmt_ts(snapshot.get('timestamp', 0.0))}"
            + (
                f", {age:.0f}s ago"
                + (
                    "  <-- STALE (the fleet that wrote this is gone "
                    "or wedged)" if stale else ""
                )
                if age is not None else ""
            )
        )
        if snapshot.get("age_s"):
            lines.append(
                f"  data age      {snapshot['age_s']:.0f}s at write "
                "time (the source had stopped changing)"
            )

    # -- REPLAY: recorded vs replayed traffic (serve.replay) ---------
    rsums = by.get("replay_summary", [])
    rreqs = by.get("replay_request", [])
    caps = by.get("capture_summary", [])
    if rsums or rreqs or caps:
        lines.append(_section("REPLAY"))
        for c in caps:
            lines.append(
                f"  capture       {c.get('n_requests')} request(s), "
                f"{c.get('n_payloads')} payload(s) "
                f"({c.get('n_dedup_hits')} dedup hit(s), "
                f"{(c.get('payload_bytes') or 0) / 1e6:.2f} MB), "
                f"overhead {c.get('overhead_s')}s "
                f"({c.get('overhead_ms_per_request')} ms/req) -> "
                f"{c.get('path')}"
            )
        fmt = lambda v: "—" if v is None else f"{v:.1f}"
        for s in rsums:
            speed = (
                "max" if (s.get("speed") or 0) <= 0
                else f"{s['speed']:g}x"
            )
            lines.append(
                f"  session       {s.get('mode')}/{speed}: "
                f"{s.get('n_replayed')}/{s.get('n_recorded')} "
                f"replayed, {s.get('n_exact')} bit-exact, "
                f"{s.get('n_psnr')} psnr-matched, "
                f"{s.get('n_unverified')} unverified, "
                f"{s.get('n_mismatched')} MISMATCHED, "
                f"{s.get('n_lost')} LOST"
            )
            lines.append(
                "                latency p50 "
                f"{fmt(s.get('recorded_p50_ms'))} -> "
                f"{fmt(s.get('replayed_p50_ms'))} ms, p99 "
                f"{fmt(s.get('recorded_p99_ms'))} -> "
                f"{fmt(s.get('replayed_p99_ms'))} ms "
                "(recorded -> replayed), "
                f"{s.get('requests_per_sec')} req/s"
            )
            rej = s.get("recorded_rejected")
            backs = s.get("replay_overload_backoffs") or 0
            if rej is not None or backs:
                lines.append(
                    f"                admission: {backs} replay "
                    f"backoff(s) vs {rej} recorded rejection(s)"
                )
        if rreqs and not rsums:
            # a replay killed before its summary: reconstruct counts
            per = {}
            for r in rreqs:
                per[r.get("status", "?")] = (
                    per.get(r.get("status", "?"), 0) + 1
                )
            lines.append(
                f"  (no summary — live/killed replay; statuses so "
                f"far: {json.dumps(per)})"
            )

    # -- MEMORY: measured vs modeled HBM watermark (utils.memwatch) --
    wms = by.get("mem_watermark", [])
    ooms = by.get("mem_oom_dump", [])
    if wms or ooms:
        lines.append(_section("MEMORY"))
        gb = lambda b: "—" if b is None else f"{b / 1e9:.3f} GB"
        w = wms[-1] if wms else None
        if w is not None:
            src = w.get("source") or "unmeasured"
            lines.append(
                f"  measured peak  {gb(w.get('peak_hbm_bytes'))}  "
                f"({src}, {w.get('n_samples', 0)} sample(s))"
            )
            lines.append(
                f"  modeled peak   {gb(w.get('modeled_hbm_bytes'))}  "
                "(perfmodel.inmem_learn_estimate — the preflight the "
                "degrade ladder trusts)"
            )
            if w.get("delta_frac") is not None:
                flag = (
                    "  <-- DRIFT past CCSC_MEM_DELTA_FRAC"
                    if w.get("flagged") else ""
                )
                lines.append(
                    f"  delta          "
                    f"{100 * w['delta_frac']:+.1f}% measured vs "
                    f"modeled{flag}"
                )
        for o in ooms:
            lines.append(
                f"  OOM dump       {_fmt_ts(o['t'])}  "
                f"{o.get('path')}"
            )

    # -- LEDGER: this run's appends + per-key trend vs history band --
    led_appends = by.get("ledger_append", [])
    anomalies = by.get("perf_anomaly", [])
    if led_appends or anomalies or ledger_path:
        lines.append(_section("LEDGER"))
        for a in led_appends:
            lines.append(
                f"  appended      {a.get('value'):.6g} "
                f"{a.get('unit') or ''}  -> {a.get('key')}"
            )
        if anomalies:
            lines.append(
                f"  anomalies     {len(anomalies)} perf_anomaly "
                "event(s) — rolling roofline fraction fell below "
                "the historical band"
            )
            for a in anomalies[-3:]:
                lines.append(
                    f"    {_fmt_ts(a['t'])}  rolling "
                    f"{a.get('rolling_frac')} < band lo "
                    f"{a.get('band_lo')} (median {a.get('median')} "
                    f"over {a.get('n_history')} run(s))"
                )
        if ledger_path and os.path.exists(ledger_path):
            from ccsc_code_iccv2017_tpu.analysis import (  # noqa: E402
                ledger as _ledger,
            )

            led = _ledger.Ledger(ledger_path)
            groups = led.by_key()
            verdicts = {
                v["key"]: v for v in _ledger.gate(led)
            }
            lines.append(
                f"  history       {sum(len(v) for v in groups.values())}"
                f" record(s) over {len(groups)} key(s) "
                f"({ledger_path})"
            )
            newest_first = sorted(
                groups.items(),
                key=lambda kv: -(kv[1][-1].get("t") or 0.0),
            )
            for key, recs in newest_first[:12]:
                v = verdicts.get(key, {})
                newest = recs[-1]
                if v.get("skipped") or "median" not in v:
                    judged = "(young history)"
                else:
                    rel = v.get("ratio_vs_median")
                    judged = (
                        ("OK" if v["ok"] else "REGRESSED")
                        + (
                            f" {100 * (rel - 1):+.1f}% vs median "
                            f"{v['median']:.6g}, band lo "
                            f"{v['lo']:.6g}"
                            if rel else ""
                        )
                    )
                lines.append(
                    f"    {key}\n"
                    f"      n={len(recs)}  newest "
                    f"{newest['value']:.6g} "
                    f"{newest.get('unit') or ''}  {judged}"
                )
            if len(newest_first) > 12:
                lines.append(
                    f"    … {len(newest_first) - 12} more key(s) "
                    "(scripts/perf_gate.py --list)"
                )

    spans = [
        e for e in events
        if e.get("type") in ("span_start", "span_end")
    ]
    if spans:
        lines.append(_section("TRACES"))
        traces = _trace.assemble(events)
        complete = [t for t in traces.values() if t.complete]
        orphan_spans = sum(
            len(t.orphans) + len(t.unparented)
            for t in traces.values()
        )
        lines.append(
            f"  {len(traces)} trace(s), {len(complete)} complete, "
            f"{orphan_spans} orphan/dangling span(s)"
        )
        bad = [t for t in traces.values() if not t.complete]
        if bad:
            lines.append(
                "  INCOMPLETE: "
                + ", ".join(t.trace_id for t in bad[:8])
                + (" …" if len(bad) > 8 else "")
            )
        if n_traces:
            lines.append(
                f"  {min(n_traces, len(complete))} slowest request "
                "timeline(s):"
            )
            for t in _trace.slowest(traces, n_traces):
                for ln in _trace.render_timeline(t).splitlines():
                    lines.append("  " + ln)

    lines.append(_section("EVENTS"))
    n_ev = 0
    for kind in ("checkpoint_save", "checkpoint_load", "recovery",
                 "preemption", "stall", "peer_stale", "degrade",
                 "fault_fired", "slo_breach", "slo_profile",
                 "perf_anomaly", "mem_oom_dump",
                 "fleet_replica_dead",
                 "fleet_replica_restart", "fleet_replica_ready",
                 "fleet_replica_abandoned", "fleet_requeue",
                 "fleet_overload", "bank_swap", "tenant_reject",
                 "quality_breach", "quality_probe_breach",
                 "quality_drift", "quality_demote_advice",
                 "fed_join", "fed_leave",
                 "dqueue_requeue", "dqueue_failed",
                 "artifact_fetch", "artifact_publish",
                 "warmup_stage", "bucket_cold"):
        for e in by.get(kind, []):
            n_ev += 1
            detail = {
                k: v for k, v in e.items()
                if k not in ("t", "type", "host")
            }
            lines.append(
                f"  {_fmt_ts(e['t'])}  {kind:<16} {json.dumps(detail)}"
            )
    if not n_ev:
        lines.append("  (no checkpoint/recovery/preemption events)")

    lines.append(_section("SUMMARY"))
    summaries = by.get("summary", [])
    if summaries:
        s = summaries[-1]
        detail = {
            k: v for k, v in s.items()
            if k not in ("t", "type", "host", "compile")
        }
        lines.append(f"  {json.dumps(detail)}")
        if s.get("status") != "ok":
            lines.append("  NOTE: run did not close cleanly")
    else:
        lines.append(
            "  (no summary record — run still live or killed hard; "
            "everything above survived)"
        )
    return "\n".join(lines)


def _snapshot_stamp(path):
    """Parsed freshness stamp of ``path``/metrics.prom (None when the
    target is not a dir or carries no stamped snapshot), with
    ``age_wall_s`` computed against THIS process's clock — the
    reader-side half of the staleness contract."""
    if not os.path.isdir(path):
        return None
    from ccsc_code_iccv2017_tpu.serve import metricsd as _metricsd

    stamp = _metricsd.parse_snapshot_stamp(
        os.path.join(path, "metrics.prom")
    )
    if stamp is not None and stamp.get("timestamp"):
        stamp["age_wall_s"] = max(
            0.0, time.time() - stamp["timestamp"]
        )
    return stamp


def follow(path, recursive=False, interval_s=2.0, stale_after=None,
           n_traces=3, ledger_path=None, max_polls=None, out=None):
    """Live dashboard: tail the stream incrementally
    (``obs.EventTail`` — each poll costs O(new records), never a
    re-parse of the whole stream) and re-render whenever records
    arrive — or when the metrics.prom snapshot's staleness verdict
    FLIPS (a dead fleet emits no new records, which is exactly when
    the STALE flag must appear). Runs until interrupted (or
    ``max_polls`` polls, for tests/one-shots). Returns the
    accumulated event list."""
    import builtins

    if stale_after is None:
        from ccsc_code_iccv2017_tpu.utils import env as _env

        stale_after = _env.env_float("CCSC_WATCHDOG_PEER_STALE_S")
    emit = out if out is not None else builtins.print
    tail = obs.EventTail(path, recursive=recursive)
    events = []
    polls = 0
    last_stale = False
    try:
        while max_polls is None or polls < max_polls:
            polls += 1
            fresh = tail.poll()
            snapshot = _snapshot_stamp(path)
            stale = bool(
                snapshot is not None
                and snapshot.get("age_wall_s") is not None
                and snapshot["age_wall_s"] > stale_after
            )
            if fresh or stale != last_stale:
                events.extend(fresh)
                emit(
                    "\n" + "#" * 72 + f"\n# follow: +{len(fresh)} "
                    f"record(s), {len(events)} total, "
                    f"{_fmt_ts(time.time())}\n" + "#" * 72
                )
                emit(
                    render(
                        events, stale_after=stale_after,
                        n_traces=n_traces, ledger_path=ledger_path,
                        snapshot=snapshot,
                    )
                )
            last_stale = stale
            if max_polls is None or polls < max_polls:
                time.sleep(interval_s)
    except KeyboardInterrupt:
        emit(f"\nfollow: stopped ({len(events)} record(s) seen)")
    return events


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="metrics dir or one events-*.jsonl")
    ap.add_argument(
        "--json", action="store_true",
        help="emit the parsed record list as JSON instead of the "
        "text dashboard",
    )
    ap.add_argument(
        "--stale-after", type=float, default=None,
        help="per-host liveness threshold in seconds for the HOSTS "
        "column: a host whose newest heartbeat lags the stream by "
        "more than this is flagged STALE (default: the watchdog's "
        "CCSC_WATCHDOG_PEER_STALE_S, 120)",
    )
    ap.add_argument(
        "--traces", type=int, default=3,
        help="render the N slowest request timelines in the TRACES "
        "section (reassembled from span events; 0 = counts only)",
    )
    ap.add_argument(
        "--recursive", action="store_true",
        help="merge event streams from subdirectories too (a fleet "
        "metrics dir holds each replica engine's stream in a "
        "replica-NN/ subdir; auto-enabled when such subdirs exist)",
    )
    ap.add_argument(
        "--ledger", default=None,
        help="perf-ledger JSONL for the LEDGER section (default: "
        "the standard resolution — CCSC_PERF_LEDGER, else "
        "$CCSC_COMPILE_CACHE/ccsc_perf_ledger.jsonl, else repo "
        "perf_ledger.jsonl — when that file exists)",
    )
    ap.add_argument(
        "--follow", action="store_true",
        help="live mode: tail the stream incrementally "
        "(obs.EventTail, per-file offsets — each poll parses only "
        "appended records) and re-render on growth until "
        "interrupted",
    )
    ap.add_argument(
        "--interval", type=float, default=2.0,
        help="--follow poll cadence in seconds",
    )
    args = ap.parse_args(argv)
    recursive = args.recursive
    if not recursive and os.path.isdir(args.path):
        # a fleet dir wants the whole-fleet union by default
        recursive = any(
            n.startswith("replica-")
            and os.path.isdir(os.path.join(args.path, n))
            for n in os.listdir(args.path)
        )
    ledger_path = args.ledger
    if ledger_path is None:
        from ccsc_code_iccv2017_tpu.analysis import ledger as _ledger

        candidate = _ledger.default_ledger_path()
        if os.path.exists(candidate):
            ledger_path = candidate
    if args.follow and args.json:
        ap.error(
            "--follow renders the live text dashboard; it cannot "
            "honor --json (use --json on a one-shot run, or tail "
            "the events-*.jsonl files directly for machine "
            "consumption)"
        )
    if args.follow:
        return follow(
            args.path, recursive=recursive,
            interval_s=args.interval, stale_after=args.stale_after,
            n_traces=args.traces, ledger_path=ledger_path,
        )
    events = obs.read_events(args.path, recursive=recursive)
    if args.json:
        print(json.dumps(events))
        return events
    print(
        render(
            events, stale_after=args.stale_after,
            n_traces=args.traces, ledger_path=ledger_path,
            snapshot=_snapshot_stamp(args.path),
        )
    )
    return events


if __name__ == "__main__":
    main()
