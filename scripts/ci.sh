#!/usr/bin/env bash
# The one pre-merge entrypoint: static analysis -> tier-1 tests ->
# perf regression gate, in that order (cheapest signal first).
#
#   bash scripts/ci.sh
#
# Exit-code contract (stable — wire CI stages to these):
#   0   everything passed
#   10  scripts/lint.py found NEW findings (not baselined/suppressed)
#   20  tier-1 pytest has NEW failures (the ROADMAP.md tier-1
#       invocation: -m 'not slow' on CPU). Failures listed in
#       scripts/ci_known_failures.txt — the documented environment-
#       dependent set (absent /root/reference mount, golden drift,
#       the pallas-mesh replication gap) — are tolerated, mirroring
#       the driver's "no worse than the seed" rule; anything NOT on
#       that list fails the stage.
#   25  scripts/warmup_smoke.py failed: the compiled-artifact-store
#       warm startup recompiled a bucket program (or failed to
#       publish/fetch/append its kind=warmup ledger record) — the
#       pre-warmed-elasticity contract (serve.artifacts) is broken
#   26  the autoscale chaos leg failed (scripts/chaos_smoke.py
#       --only autoscale): the capacity controller did not grow at
#       the diurnal peak / shrink at the trough / brown out, lost a
#       request, or an injected sensor blackout or wedged actuator
#       broke the fail-safe contract (serve.controller)
#   27  the bank-rot chaos leg failed (scripts/chaos_smoke.py
#       --only bank_rot): a degraded-bank hot-swap was not flagged
#       by the golden probes within ~one probe interval, the drift
#       watch missed the served-dB excursion, the demotion advisory
#       named the wrong rollback digest, a request was lost, served
#       bytes lost bit-parity, or the episode triggered a new XLA
#       compile (serve.quality — the quality observatory)
#   28  the gray-replica chaos leg failed (scripts/chaos_smoke.py
#       --only gray_replica): with one replica injected ~10x slow
#       (slow, not hung), hedged attempts did not hold fleet p99
#       within 3x the healthy baseline, a request was lost or
#       double-delivered, a hedge pair left an incomplete trace,
#       hedging exceeded its hedge_max_frac cap, served bytes lost
#       bit-parity, or the watchdog fired on a non-stall
#       (serve.fleet — the request-lifecycle plane)
#   29  the collective-audit leg failed (scripts/comm_audit.py on 8
#       forced host devices): a batch-only mesh bucket program
#       lowered with a collective HLO op in it, the (batch, freq)
#       program exceeded its declared budget (CCSC_COMM_BUDGET_FREQ)
#       or swapped its z-solve-tail all-gather for another op class,
#       or the gate failed to refuse an injected over-budget count
#       (analysis.comms — the comm-aware serving contract)
#   30  scripts/perf_gate.py judged a regression against the durable
#       perf ledger (skipped silently when no ledger file exists yet
#       — a young repo must not fail CI on an empty history)
#
# Each stage runs only if the previous passed: a lint finding or test
# failure makes the perf verdict moot, and fail-fast keeps the signal
# attributable.
set -u -o pipefail

cd "$(dirname "$0")/.."

echo "== ci: 1/3 static analysis (scripts/lint.py)"
python scripts/lint.py || exit 10

echo "== ci: 2/3 tier-1 tests (pytest -m 'not slow', CPU)"
T1_LOG=$(mktemp)
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$T1_LOG"
T1_RC=${PIPESTATUS[0]}
if [ "$T1_RC" -ne 0 ]; then
    OBSERVED=$(grep -aE '^(FAILED|ERROR) ' "$T1_LOG" \
        | awk '{print $2}' | sort -u)
    if [ -z "$OBSERVED" ]; then
        # nonzero exit with no per-test verdicts = a harness-level
        # failure (timeout, internal error) — never tolerated
        echo "== ci: tier-1 exited $T1_RC with no test verdicts"
        exit 20
    fi
    NEW=$(echo "$OBSERVED" \
        | grep -vxF -f scripts/ci_known_failures.txt || true)
    if [ -n "$NEW" ]; then
        echo "== ci: NEW tier-1 failures (not in scripts/ci_known_failures.txt):"
        echo "$NEW"
        exit 20
    fi
    echo "== ci: tier-1 failures are all on the documented known list — tolerated"
fi

# Opt-in mesh-serving parity leg (CCSC_CI_DEVICES=8): re-runs the
# mesh parity suite under an EXPLICITLY forced host-device count —
# tier-1 above already fakes 8 devices via tests/conftest.py, but
# this leg proves the suite under the production-documented flag
# (XLA_FLAGS=--xla_force_host_platform_device_count=N) in a clean
# pytest process. If the container cannot fake that many devices the
# suite's own device-count skips apply — a skip is not a failure
# (the ci_known_failures.txt stance: environment-dependent absence
# is tolerated, a real assertion failure is not).
if [ -n "${CCSC_CI_DEVICES:-}" ]; then
    echo "== ci: 2b/3 mesh-serving parity suite (CCSC_CI_DEVICES=$CCSC_CI_DEVICES forced host devices)"
    XLA_FLAGS="--xla_force_host_platform_device_count=$CCSC_CI_DEVICES" \
        JAX_PLATFORMS=cpu python -m pytest tests/test_serve_mesh.py -q \
        -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly \
        || exit 20
fi

echo "== ci: 2c/3 warmup leg (scripts/warmup_smoke.py: cold-vs-warm artifact-store startup)"
JAX_PLATFORMS=cpu python scripts/warmup_smoke.py || exit 25

echo "== ci: 2d/3 autoscale leg (scripts/chaos_smoke.py --only autoscale: diurnal replay under the capacity controller)"
JAX_PLATFORMS=cpu python scripts/chaos_smoke.py --only autoscale || exit 26

echo "== ci: 2e/3 bank-rot leg (scripts/chaos_smoke.py --only bank_rot: degraded-bank hot-swap vs the quality observatory)"
JAX_PLATFORMS=cpu python scripts/chaos_smoke.py --only bank_rot || exit 27

echo "== ci: 2f/3 gray-replica leg (scripts/chaos_smoke.py --only gray_replica: hedged attempts vs a slow-but-alive replica)"
JAX_PLATFORMS=cpu python scripts/chaos_smoke.py --only gray_replica || exit 28

echo "== ci: 2g/3 collective-audit leg (scripts/comm_audit.py: HLO collective budgets of the mesh bucket programs)"
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    JAX_PLATFORMS=cpu python scripts/comm_audit.py || exit 29

echo "== ci: 3/3 perf regression gate (scripts/perf_gate.py)"
# resolve the same ledger path perf_gate would; gate only when a
# ledger actually exists (exit 0 on an empty observatory)
LEDGER_PATH=$(python - <<'EOF'
import os, sys
sys.path.insert(0, os.getcwd())
from ccsc_code_iccv2017_tpu.analysis import ledger
print(ledger.default_ledger_path())
EOF
)
if [ -f "$LEDGER_PATH" ]; then
    python scripts/perf_gate.py || exit 30
else
    echo "== ci: no perf ledger at $LEDGER_PATH — gate skipped"
fi

echo "== ci: all stages passed"
