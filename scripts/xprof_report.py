#!/usr/bin/env python
"""Op-by-op attribution of an xprof trace (VERDICT r4 weak #1).

Usage: python scripts/xprof_report.py <trace_dir>

Reads the .xplane.pb files jax.profiler.trace wrote under
``trace_dir`` (any nesting), picks the device plane (TPU if present,
else the busiest plane), aggregates event durations by op name on
each plane line, and prints a JSON line with the top ops of the
busiest line — the "where do the 0.55 s go" answer the analytic cost
model cannot give. Parsing uses the XPlane proto bundled with the
baked-in tensorflow; no network, no TensorBoard UI.
"""
import glob
import json
import os
import sys
from collections import defaultdict


def load_spaces(trace_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    spaces = []
    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    ):
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append((path, xs))
    return spaces


def summarize(trace_dir, top=25):
    spaces = load_spaces(trace_dir)
    if not spaces:
        return {"xprof": "no .xplane.pb found", "dir": trace_dir}
    # prefer a TPU device plane; otherwise the plane with the most
    # total event time (host planes include idle python time)
    best = None  # (is_tpu, total_ps, plane, path)
    for path, xs in spaces:
        for plane in xs.planes:
            total = sum(
                ev.duration_ps
                for line in plane.lines
                for ev in line.events
            )
            is_tpu = "TPU" in plane.name or "/device:" in plane.name
            key = (is_tpu, total)
            if best is None or key > (best[0], best[1]):
                best = (is_tpu, total, plane, path)
    _, _, plane, path = best
    names = {m.id: m.name for m in plane.event_metadata.values()}
    # aggregate per line, then choose which line to report from below
    line_tot = defaultdict(int)
    line_ops = {}
    for line in plane.lines:
        ops = defaultdict(int)
        for ev in line.events:
            ops[names.get(ev.metadata_id, "?")] += ev.duration_ps
        line_tot[line.name] = sum(ops.values())
        line_ops[line.name] = ops
    # prefer the op-level timeline by name: on TPU the plane carries
    # both "XLA Modules" (one whole-program event — always the
    # "busiest" line) and "XLA Ops" (per-HLO events, what we want)
    op_lines = [
        n for n, tot in line_tot.items()
        if "xla ops" in n.lower() and tot > 0
    ]
    if op_lines:
        busiest = max(op_lines, key=line_tot.get)
    else:
        busiest = max(line_tot, key=line_tot.get) if line_tot else None
    ops = line_ops.get(busiest, {})
    total_ps = sum(ops.values())
    rows = sorted(ops.items(), key=lambda kv: -kv[1])[:top]
    return {
        "xprof": "ok",
        "plane": plane.name,
        "line": busiest,
        "file": os.path.relpath(path, trace_dir),
        "total_ms": round(total_ps / 1e9, 3),
        "top_ops": [
            {
                "op": name[:120],
                "ms": round(ps / 1e9, 3),
                "pct": round(100.0 * ps / total_ps, 1) if total_ps else 0,
            }
            for name, ps in rows
        ],
    }


if __name__ == "__main__":
    d = sys.argv[1] if len(sys.argv) > 1 else "artifacts_prof/tuned"
    print(json.dumps(summarize(d)))
