#!/usr/bin/env python
"""Op-by-op attribution of an xprof trace (VERDICT r4 weak #1).

Usage: python scripts/xprof_report.py <trace_dir> [--top N]

Reads the .xplane.pb files jax.profiler.trace wrote under
``trace_dir`` (any nesting), picks the device plane (TPU if present,
else the busiest plane), aggregates event durations by op name on
each plane line, and prints a JSON line with the top ops of the
busiest line — the "where do the 0.55 s go" answer the analytic cost
model cannot give. Parsing uses the XPlane proto bundled with the
baked-in tensorflow; no network, no TensorBoard UI.

Degrades honestly: a container without tensorflow's XPlane proto (or
a corrupt trace file) yields a one-line JSON error record
(``{"xprof": "unavailable", ...}``) on stdout and exit code 0 —
callers that pipe this into bench records or the serving SLO breach
flow (serve.slo arms a capture; this script attributes it) get a
parseable answer either way, never a raw traceback. Output rides the
utils.obs console tiers so a capturing run's stream records it too.
"""
import argparse
import glob
import json
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def load_spaces(trace_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    spaces = []
    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    ):
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append((path, xs))
    return spaces


def summarize(trace_dir, top=25):
    try:
        spaces = load_spaces(trace_dir)
    except Exception as e:
        # no tensorflow in this container, or an unparseable trace:
        # a clear JSON error line, not a traceback (the xprof answer
        # is optional; crashing the caller is not)
        return {
            "xprof": "unavailable",
            "error": f"{type(e).__name__}: {e}",
            "dir": trace_dir,
        }
    if not spaces:
        return {"xprof": "no .xplane.pb found", "dir": trace_dir}
    # prefer a TPU device plane; otherwise the plane with the most
    # total event time (host planes include idle python time)
    best = None  # (is_tpu, total_ps, plane, path)
    for path, xs in spaces:
        for plane in xs.planes:
            total = sum(
                ev.duration_ps
                for line in plane.lines
                for ev in line.events
            )
            is_tpu = "TPU" in plane.name or "/device:" in plane.name
            key = (is_tpu, total)
            if best is None or key > (best[0], best[1]):
                best = (is_tpu, total, plane, path)
    _, _, plane, path = best
    names = {m.id: m.name for m in plane.event_metadata.values()}
    # aggregate per line, then choose which line to report from below
    line_tot = defaultdict(int)
    line_ops = {}
    for line in plane.lines:
        ops = defaultdict(int)
        for ev in line.events:
            ops[names.get(ev.metadata_id, "?")] += ev.duration_ps
        line_tot[line.name] = sum(ops.values())
        line_ops[line.name] = ops
    # prefer the op-level timeline by name: on TPU the plane carries
    # both "XLA Modules" (one whole-program event — always the
    # "busiest" line) and "XLA Ops" (per-HLO events, what we want)
    op_lines = [
        n for n, tot in line_tot.items()
        if "xla ops" in n.lower() and tot > 0
    ]
    if op_lines:
        busiest = max(op_lines, key=line_tot.get)
    else:
        busiest = max(line_tot, key=line_tot.get) if line_tot else None
    ops = line_ops.get(busiest, {})
    total_ps = sum(ops.values())
    rows = sorted(ops.items(), key=lambda kv: -kv[1])[:top]
    return {
        "xprof": "ok",
        "plane": plane.name,
        "line": busiest,
        "file": os.path.relpath(path, trace_dir),
        "total_ms": round(total_ps / 1e9, 3),
        "top_ops": [
            {
                "op": name[:120],
                "ms": round(ps / 1e9, 3),
                "pct": round(100.0 * ps / total_ps, 1) if total_ps else 0,
            }
            for name, ps in rows
        ],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "trace_dir", nargs="?", default="artifacts_prof/tuned",
        help="directory jax.profiler.trace / serve.slo wrote",
    )
    ap.add_argument(
        "--top", type=int, default=25,
        help="ops to keep from the busiest line",
    )
    args = ap.parse_args(argv)
    out = summarize(args.trace_dir, top=args.top)
    # the obs console tier: with an active run the line lands in the
    # event stream too; standalone it is a plain print
    from ccsc_code_iccv2017_tpu.utils import obs

    obs.console(json.dumps(out), tier="always")
    return out


if __name__ == "__main__":
    main()
