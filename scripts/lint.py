#!/usr/bin/env python
"""Run the repo's static analysis suite (ccsc_code_iccv2017_tpu/analysis).

    python scripts/lint.py                      # all checks, exit != 0
                                                # on NEW findings
    python scripts/lint.py --checks jit-purity,thread-safety
    python scripts/lint.py --json               # machine-readable
    python scripts/lint.py --update-baseline    # re-review: absorb
                                                # current findings
    python scripts/lint.py --write-env-docs     # regenerate
                                                # docs/ENV_KNOBS.md
    python scripts/lint.py --list               # available checks

Findings already absorbed by analysis/baseline.json, or suppressed
inline with `# ccsc: allow[check-id]`, do not fail the run. Stale
baseline entries (matching nothing anymore) are reported so the
baseline shrinks as debt is paid — tests/test_analysis.py fails on
them, keeping the reviewed file honest.

The same suite runs as a tier-1 test (tests/test_analysis.py), so CI
enforces every check on every PR.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.analysis import core  # noqa: E402
from ccsc_code_iccv2017_tpu.analysis import envreg  # noqa: E402

ENV_DOCS_PATH = os.path.join(REPO, "docs", "ENV_KNOBS.md")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="roots to analyze (default: the package + scripts/)",
    )
    ap.add_argument(
        "--checks", default=None,
        help="comma list of check ids (default: all)",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit findings as JSON",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite analysis/baseline.json from the current "
        "findings (a reviewed act — the diff is the review)",
    )
    ap.add_argument(
        "--baseline", default=core.BASELINE_PATH,
        help="baseline file (default analysis/baseline.json)",
    )
    ap.add_argument(
        "--write-env-docs", action="store_true",
        help="regenerate docs/ENV_KNOBS.md from utils.env.REGISTRY "
        "and exit",
    )
    ap.add_argument(
        "--list", action="store_true", dest="list_checks",
        help="list available checks and exit",
    )
    args = ap.parse_args(argv)

    if args.list_checks:
        for name in core.all_check_names():
            print(name)
        return 0
    if args.write_env_docs:
        os.makedirs(os.path.dirname(ENV_DOCS_PATH), exist_ok=True)
        with open(ENV_DOCS_PATH, "w", encoding="utf-8") as f:
            f.write(envreg.render_env_docs())
        print(f"wrote {os.path.relpath(ENV_DOCS_PATH, REPO)}")
        return 0

    t0 = time.perf_counter()
    roots = args.paths or core.DEFAULT_ROOTS
    checks = (
        [c.strip() for c in args.checks.split(",") if c.strip()]
        if args.checks
        else None
    )
    project = core.Project(roots)
    findings = core.run_checks(project, checks)
    baseline = core.load_baseline(args.baseline)
    new, baselined, stale = core.split_baseline(findings, baseline)

    if args.update_baseline:
        core.save_baseline(findings, args.baseline)
        print(
            f"baseline updated: {len(findings)} finding(s) absorbed "
            f"({os.path.relpath(args.baseline, REPO)})"
        )
        return 0

    dt = time.perf_counter() - t0
    if args.as_json:
        print(
            json.dumps(
                {
                    "new": [vars(f) for f in new],
                    "baselined": [vars(f) for f in baselined],
                    "stale_baseline": stale,
                    "elapsed_s": round(dt, 3),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.render())
        if stale:
            print(
                f"-- {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (fixed or "
                "moved — prune with --update-baseline):"
            )
            for e in stale:
                print(
                    f"   {e.get('path')}: [{e.get('check')}] "
                    f"{e.get('message')}"
                )
        print(
            f"-- lint: {len(new)} new, {len(baselined)} baselined, "
            f"{len(stale)} stale baseline, "
            f"{len(project.sources)} files in {dt:.2f}s"
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
