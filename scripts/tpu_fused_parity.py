#!/usr/bin/env python
"""Real-TPU parity check for the pallas fused_z + shard_map branch.

ADVICE r4: off-TPU, the mesh test routes to the jnp reference (pallas
interpret mode cannot run under shard_map's vma checks), so the
pvary/vma-lift lowering in ops/pallas_fused_z.py only ever executes on
real hardware. This probe runs it there: a small consensus learn with
fused_z under a 1-device 'block' shard_map mesh (shard_map marks the
axis varying-manual even at size 1, so the lift branch and the mosaic
lowering both engage) against the unsharded fused and unfused runs.
Prints one JSON line; queued in scripts/onchip_queue.sh phase
'accuracy'.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models.learn import learn
    from ccsc_code_iccv2017_tpu.parallel.mesh import block_mesh

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(6)
    b = jnp.asarray(rng.standard_normal((4, 20, 20)).astype(np.float32))
    geom = ProblemGeom((5, 5), 6)
    kw = dict(
        max_it=2, max_it_d=2, max_it_z=3, num_blocks=1,
        verbose="none", track_objective=True,
    )
    key = jax.random.PRNGKey(0)
    r_ref = learn(b, geom, LearnConfig(**kw), key=key)
    r_fus = learn(b, geom, LearnConfig(**kw, fused_z=True), key=key)
    r_msh = learn(
        b, geom, LearnConfig(**kw, fused_z=True), key=key,
        mesh=block_mesh(1),
    )
    d_ref = np.asarray(r_ref.d)
    err_fused = float(
        np.max(np.abs(np.asarray(r_fus.d) - d_ref))
        / max(np.max(np.abs(d_ref)), 1e-12)
    )
    err_mesh = float(
        np.max(np.abs(np.asarray(r_msh.d) - np.asarray(r_fus.d)))
        / max(np.max(np.abs(np.asarray(r_fus.d))), 1e-12)
    )
    ok = err_fused < 1e-3 and err_mesh < 1e-3
    print(json.dumps({
        "tpu_fused_parity": "ok" if ok else "MISMATCH",
        "platform": platform,
        "fused_vs_einsum_rel": err_fused,
        "mesh_vs_fused_rel": err_mesh,
        "obj_z_ref": r_ref.trace["obj_vals_z"],
        "obj_z_fused_mesh": r_msh.trace["obj_vals_z"],
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
