#!/usr/bin/env python
"""Adversarial validation of the "~100 GB/s platform ceiling" theory.

r4's microbenchmark saw soft_threshold move ~1.2 GB in 14.9 ms
(~83 GB/s) at ONE size and PERF.md took that as the platform's
effective bandwidth — but a single fixed-size timing cannot separate
per-dispatch overhead (tunnel round-trip + launch) from true streaming
bandwidth. This probe measures time-vs-bytes across ~3 decades
(8 MB -> 4 GB moved) for two op classes and fits

    time(bytes) = overhead + bytes / BW

by least squares; the slope is the real bandwidth, the intercept the
fixed cost. Two op classes:

  copy  - donated-buffer increment y = x + 1 (donate_argnums=0): the
          purest stream XLA can run — read N, write N, no reduction,
          the output is materialized by construction (it feeds the
          next chained call). This is the "donated-buffer copy probe"
          VERDICT r4 asked for.
  sthr  - soft_threshold + full reduction (the r4 microbench op), for
          continuity with the r4 data point.

Fencing: the axon platform's block_until_ready is a no-op (PERF.md
tunnel protocol), so each measurement chains R calls y=f(y) and fences
once with a 1-element readback that depends on the whole chain; the
per-call time is the chained total / R. Chaining also means dispatch
overhead is counted once per call, exactly like production steps.

Prints one JSON line per (op, size) plus one fit line per op. On a
healthy v5e the copy slope should approach several hundred GB/s; if
instead the slope itself is ~100 GB/s at 4 GB moved, the ceiling
theory stands and the step is genuinely near the platform's memory
roofline.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax
import jax.numpy as jnp
import numpy as np


def _fit(rows):
    """Least-squares time = a + bytes/BW over rows [(bytes, sec)]."""
    if len(rows) < 2:
        return None
    x = np.array([r[0] for r in rows], np.float64)
    y = np.array([r[1] for r in rows], np.float64)
    slope, intercept = np.polyfit(x, y, 1)
    if slope <= 0:
        return {"overhead_ms": float(intercept * 1e3), "fit_gbps": None}
    return {
        "overhead_ms": float(intercept * 1e3),
        "fit_gbps": float(1.0 / slope / 1e9),
    }


def main():
    # bytes MOVED per call (read + write); buffer is half this
    sizes_mb = [8, 32, 128, 512, 1536, 4096]
    max_mb = float(os.environ.get("BW_MAX_MB", 4096))
    sizes_mb = [s for s in sizes_mb if s <= max_mb]
    platform = jax.devices()[0].platform

    def copy_op(a):
        return a + 1.0

    def sthr_op(a):
        return jnp.sign(a) * jnp.maximum(jnp.abs(a) - 0.1, 0.0)

    f_copy = jax.jit(copy_op, donate_argnums=0)
    f_sthr = jax.jit(sthr_op, donate_argnums=0)

    fits = {}
    for name, fn in (("copy", f_copy), ("sthr", f_sthr)):
        rows = []
        for mb in sizes_mb:
            n = int(mb * 1e6 / 2 / 4)  # moved = 2 buffers of n f32
            reps = 8 if mb <= 128 else (5 if mb <= 512 else 3)
            y = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
            y = fn(y)  # compile (consumes y, returns fresh buffer)
            float(y[0])  # fence compile
            t0 = time.perf_counter()
            for _ in range(reps):
                y = fn(y)
            float(y[0])  # 1-element readback fences the whole chain
            dt = (time.perf_counter() - t0) / reps
            moved = 2 * n * 4
            rows.append((moved, dt))
            print(json.dumps({
                "bwprobe": name,
                "moved_mb": round(moved / 1e6, 1),
                "ms": round(dt * 1e3, 3),
                "gbps": round(moved / dt / 1e9, 2),
                "platform": platform,
            }), flush=True)
            del y
        # fit on the upper half only: small sizes are pure overhead
        fits[name] = _fit(rows[len(rows) // 2:])
        print(json.dumps({
            "bwprobe_fit": name,
            "platform": platform,
            **(fits[name] or {}),
        }), flush=True)

    copy_bw = (fits.get("copy") or {}).get("fit_gbps")
    verdict = None
    if copy_bw is not None:
        # the r4 theory said ~100 GB/s effective; >2x that at large
        # sizes falsifies it (the step then has real headroom)
        verdict = (
            "ceiling-theory-falsified" if copy_bw > 200.0
            else "ceiling-theory-stands"
        )
    print(json.dumps({
        "bwprobe_verdict": verdict,
        "copy_fit_gbps": copy_bw,
        "platform": platform,
    }), flush=True)


if __name__ == "__main__":
    main()
