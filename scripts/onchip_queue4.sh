#!/bin/bash
# Round-4 on-chip queue, phase 4: trajectory-accuracy probe for the
# execution-strategy knobs (scripts/accuracy_probe.py) — the evidence
# CPU tests cannot produce (bf16 MXU truncation, real mosaic fused_z).
# Waits for earlier phases (single-client tunnel), then runs once.
set -u
cd "$(dirname "$0")/.."
OUT=onchip_r4.jsonl
LOG=/tmp/onchip_queue4.log

probe() {
  timeout 60 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
x = jnp.ones((128, 128)); float((x @ x).sum())
" > /dev/null 2>&1
}

note() { echo "{\"note\": \"$1\", \"at\": \"$(date +%H:%M:%S)\"}" >> "$OUT"; }

while pgrep -f "scripts/onchip_queue.sh|scripts/onchip_queue2.sh|scripts/onchip_queue3.sh" \
    | grep -qv $$ 2>/dev/null; do
  echo "$(date +%H:%M:%S) earlier phase still running" >> "$LOG"
  sleep 180
done

while true; do
  if probe; then
    note "phase 4 start (accuracy probe)"
    timeout 2400 python scripts/accuracy_probe.py >> "$OUT" 2>> "$LOG" \
      || note "accuracy_probe FAILED"
    note "phase 4 complete"
    break
  fi
  echo "$(date +%H:%M:%S) tunnel down" >> "$LOG"
  sleep 240
done
