#!/usr/bin/env python
"""On-chip FFT strategy microbenchmark (round-4 utilization work).

The north-star bench's hot loop moves the [8, 16, 100, 110, 110] code
tensor through rfft2/irfft2 every inner z-iteration. 110 = 2*5*11 is
not a friendly FFT size on TPU; this script times, at bench shapes:

  a) rfft2/irfft2 at the reference padding (110^2),
  b) the same at the next power of two (128^2),
  c) a DFT-as-matmul pair (two complex matmuls per axis) at 110^2 —
     the MXU route that avoids FFT codegen entirely,
  d) the elementwise soft-threshold pass for a bandwidth roofline
     reference point.

Each timed op is jitted with a scalar readback fence (axon
block_until_ready is a no-op). Prints one JSON dict per variant.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax
import jax.numpy as jnp
import numpy as np


def timed(name, fn, *args, reps=5):
    gj = jax.jit(fn)
    out = gj(*args)
    float(out[1] if isinstance(out, tuple) else out)  # compile+fence
    t0 = time.perf_counter()
    for _ in range(reps):
        out = gj(*args)
    float(out[1] if isinstance(out, tuple) else out)
    dt = (time.perf_counter() - t0) / reps
    print(json.dumps({"op": name, "ms": round(dt * 1e3, 3)}), flush=True)
    return dt


def dft_mats(n):
    # NUMPY constants (a jnp array closed over by a jitted fn must be
    # read back to host to embed as an MLIR constant, and the axon
    # platform cannot); reuse the production matrices.
    from ccsc_code_iccv2017_tpu.ops.fourier import _dft_mat

    return _dft_mat(n, inverse=False), _dft_mat(n, inverse=True)


def main():
    L = int(os.environ.get("MB_BLOCKS", 8))
    NI = int(os.environ.get("MB_NI", 16))
    K = int(os.environ.get("MB_K", 100))
    S = int(os.environ.get("MB_SIZE", 110))
    S2 = int(os.environ.get("MB_SIZE_FAST", 128))
    reps = int(os.environ.get("MB_REPS", 5))
    print(
        json.dumps(
            {
                "shape": [L, NI, K, S, S],
                "fast": S2,
                "platform": jax.devices()[0].platform,
            }
        ),
        flush=True,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (L, NI, K, S, S), jnp.float32)
    x2 = jax.random.normal(
        jax.random.PRNGKey(0), (L, NI, K, S2, S2), jnp.float32
    )

    # a) rfft2 + irfft2 roundtrip at 110
    def rt(a):
        h = jnp.fft.rfftn(a, axes=(-2, -1))
        b = jnp.fft.irfftn(h, s=a.shape[-2:], axes=(-2, -1))
        return b, b.ravel()[0]

    timed(f"rfft2+irfft2 {S}", rt, x, reps=reps)
    # b) same at 128
    timed(f"rfft2+irfft2 {S2}", rt, x2, reps=reps)

    # forward only
    def fwd(a):
        h = jnp.fft.rfftn(a, axes=(-2, -1))
        return h, jnp.real(h).ravel()[0]

    timed(f"rfft2 {S}", fwd, x, reps=reps)
    timed(f"rfft2 {S2}", fwd, x2, reps=reps)

    # c) DFT-as-matmul roundtrip at 110 (full complex, both axes)
    W, Winv = dft_mats(S)

    def mm_rt(a):
        ac = a.astype(jnp.complex64)
        h = jnp.einsum("...xy,xu,yv->...uv", ac, W, W)
        b = jnp.real(jnp.einsum("...uv,ux,vy->...xy", h, Winv, Winv))
        return b, b.ravel()[0]

    timed(f"dft-matmul fwd+inv {S}", mm_rt, x, reps=reps)

    # c2) the production matmul-DFT path (ops.fourier, half-spectrum
    # rfft matrices, HIGHEST-precision real matmuls — fft_impl='matmul')
    from ccsc_code_iccv2017_tpu.ops import fourier

    def prod_rt(a):
        h = fourier.rfftn_spatial(a, 2, impl="matmul")
        b = fourier.irfftn_spatial(h, a.shape[-2:], impl="matmul")
        return b, b.ravel()[0]

    timed(f"fourier-matmul fwd+inv {S}", prod_rt, x, reps=reps)

    def prod_fwd(a):
        h = fourier.rfftn_spatial(a, 2, impl="matmul")
        return h, jnp.real(h).ravel()[0]

    timed(f"fourier-matmul fwd {S}", prod_fwd, x, reps=reps)

    # d) bandwidth reference: soft threshold (2 reads + 1 write-ish)
    def st(a):
        o = jnp.sign(a) * jnp.maximum(jnp.abs(a) - 0.1, 0.0)
        return o, o.ravel()[0]

    timed("soft_threshold", st, x, reps=reps)

    # batched einsum reference at bench shape: the z-solve's k-reduction
    dh = jax.random.normal(
        jax.random.PRNGKey(1), (K, S * (S // 2 + 1)), jnp.complex64
    )
    zh = jax.random.normal(
        jax.random.PRNGKey(2), (L, NI, K, S * (S // 2 + 1)), jnp.complex64
    )

    def ks(d, z):
        o = jnp.einsum("kf,lnkf->lnf", d, z)
        return o, jnp.real(o).ravel()[0]

    timed("z-solve k-einsum", ks, dh, zh, reps=reps)


if __name__ == "__main__":
    main()
