#!/usr/bin/env python
"""Serving benchmark: CodecEngine vs the per-request driver loop.

Measures steady-state engine throughput (per-bank plans + shape
buckets + AOT warmup + micro-batching, serve.CodecEngine) against the
reference-shaped one-``reconstruct()``-call-per-request loop
(reconstruct_2D_subsampling.m:35-60) on a stream of small inpainting
requests, and records the request-latency histogram.

Prints one JSON record (the serve.bench record format; bench.py emits
the same workload as the CCSC_BENCH_SERVE on-chip arm) followed by a
text latency histogram unless --json.

Knobs are env vars shared with the bench arm: CCSC_SERVE_REQUESTS,
CCSC_SERVE_SIZE_MIN/MAX, CCSC_SERVE_K, CCSC_SERVE_SUPPORT,
CCSC_SERVE_SLOTS, CCSC_SERVE_MAXIT, CCSC_SERVE_WAIT_MS,
CCSC_SERVE_HOMOG, CCSC_COMPILE_CACHE.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()


def _histogram(lat_ms, width=50):
    """Text latency histogram (10 bins over the observed range)."""
    if not lat_ms:
        return "  (no latency records)"
    lo, hi = min(lat_ms), max(lat_ms)
    span = max(hi - lo, 1e-9)
    bins = [0] * 10
    for v in lat_ms:
        bins[min(9, int((v - lo) / span * 10))] += 1
    peak = max(bins)
    lines = []
    for i, n in enumerate(bins):
        a = lo + span * i / 10
        b = lo + span * (i + 1) / 10
        bar = "#" * int(width * n / peak) if peak else ""
        lines.append(f"  {a:9.1f}-{b:9.1f} ms  {n:4d}  {bar}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--json", action="store_true",
        help="emit only the JSON record (no histogram)",
    )
    ap.add_argument(
        "--requests", type=int, default=None,
        help="stream length (overrides CCSC_SERVE_REQUESTS)",
    )
    ap.add_argument(
        "--homog", action="store_true",
        help="homogeneous stream at the bucket shape "
        "(CCSC_SERVE_HOMOG=1): isolates micro-batching from "
        "shape bucketing; outputs bit-identical to the loop",
    )
    ap.add_argument(
        "--tune", default=None, choices=["off", "auto", "sweep"],
        help="also run a TUNED engine on the same stream "
        "(CCSC_SERVE_TUNE; ServeConfig.tune — 'sweep' measures the "
        "solve arms on this chip first, 'auto' applies the tuned "
        "store entry) and record the default-vs-tuned gap",
    )
    ap.add_argument(
        "--mesh", default=None, metavar="BATCH[xFREQ]",
        help="also run a MESH engine on the same stream "
        "(CCSC_SERVE_MESH; ServeConfig.mesh_shape — the bucket's "
        "slots sharded over a device mesh via shard_map, e.g. '4' "
        "or '4x2') and record the default-vs-mesh gap; on CPU run "
        "under XLA_FLAGS=--xla_force_host_platform_device_count=N",
    )
    ap.add_argument(
        "--pipeline", type=int, default=None, metavar="DEPTH",
        help="also run a PIPELINED engine on the same stream "
        "(CCSC_SERVE_PIPELINE; ServeConfig.pipeline_depth — the "
        "worker holds DEPTH launched batches in flight, overlapping "
        "batch N+1's upload with batch N's solve) and record the "
        "default-vs-pipelined gap plus a bitwise parity verdict",
    )
    args = ap.parse_args(argv)
    if args.requests is not None:
        os.environ["CCSC_SERVE_REQUESTS"] = str(args.requests)
    if args.homog:
        os.environ["CCSC_SERVE_HOMOG"] = "1"
    if args.tune is not None:
        os.environ["CCSC_SERVE_TUNE"] = args.tune
    if args.mesh is not None:
        os.environ["CCSC_SERVE_MESH"] = args.mesh
    if args.pipeline is not None:
        os.environ["CCSC_SERVE_PIPELINE"] = str(args.pipeline)

    from ccsc_code_iccv2017_tpu.serve.bench import run_serve_workload
    from ccsc_code_iccv2017_tpu.utils import obs

    rec = run_serve_workload()
    # durable perf ledger (analysis.ledger; no-op unless
    # CCSC_PERF_LEDGER is set): this session's serving record accrues
    # history next to the bench arms' — the same shared mapping
    # bench.py's CCSC_BENCH_SERVE arm appends through
    from ccsc_code_iccv2017_tpu.analysis import ledger as _ledger

    _ledger.append_serve_record(
        rec, git_sha=obs.git_sha(), source="scripts/serve_bench.py"
    )
    print(json.dumps(rec))
    if args.json:
        return rec
    lat = sorted(
        e["latency_ms"]
        for e in obs.read_events(rec["event_stream"])
        if e.get("type") == "serve_request"
    )
    print("\nrequest latency histogram (queue wait + solve):")
    print(_histogram(lat))
    print(
        f"\nengine {rec['engine_requests_per_sec']} req/s vs loop "
        f"{rec['loop_requests_per_sec']} req/s "
        f"({rec['speedup_vs_loop']}x; warm loop "
        f"{rec['loop_warm_requests_per_sec']} req/s), p50 "
        f"{rec['p50_ms']} ms, p99 {rec['p99_ms']} ms, "
        f"recompiles after warmup: {rec['recompiles_after_warmup']}"
    )
    if "tuned_requests_per_sec" in rec:
        print(
            f"tuned engine {rec['tuned_requests_per_sec']} req/s "
            f"({rec['speedup_tuned_vs_default']}x the default engine; "
            f"max rel err vs loop {rec['tuned_max_rel_err_vs_loop']}) "
            f"under {rec['tuned_knobs']}"
        )
    if "mesh_requests_per_sec" in rec:
        print(
            f"mesh engine ({rec['mesh']}, {rec['mesh_devices']} "
            f"devices) {rec['mesh_requests_per_sec']} req/s "
            f"({rec['speedup_mesh_vs_default']}x the default engine; "
            f"max rel err vs loop {rec['mesh_max_rel_err_vs_loop']})"
        )
    elif rec.get("mesh_skipped"):
        print(f"mesh arm skipped: {rec['mesh_skipped']}")
    if "pipeline_requests_per_sec" in rec:
        print(
            f"pipelined engine (depth {rec['pipeline_depth']}) "
            f"{rec['pipeline_requests_per_sec']} req/s "
            f"({rec['speedup_pipeline_vs_default']}x the default "
            "engine; bit-identical: "
            f"{rec['pipeline_bit_identical']})"
        )
    return rec


if __name__ == "__main__":
    main()
