#!/usr/bin/env python
"""Continue training the 3D bank from a saved checkpoint bank.

The full-protocol (n=64, max_it=20 — learn_kernels_3D.m:15-16,71-82)
3D train landed 0.13 dB behind the shipped reference bank with the
objective still falling steadily at the protocol's iteration cap
(Diff_z 0.33 vs tol 1e-2): the bank is undertrained at 20 iterations,
not underpowered. This script warm-starts the consensus learner from
the saved bank (LearnConfig init_d — the warm start the reference
declares but ignores, dParallel.m:4) on the SAME synthesized clips
(same seed) and runs additional outer iterations, then re-runs the
identical held-out evaluation as scripts/family_banks.py.

Duals restart at zero, so this is a fresh consensus solve initialized
at the learned dictionary — standard ADMM practice; the trace confirms
the objective continues DOWN from the warm start.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

from family_banks import (  # noqa: E402
    SHIPPED, central_slice, heldout_psnr_3d, inmem_learn_estimate,
    synth_video,
)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--bank", required=True,
                    help="bank_3d.mat to continue from")
    ap.add_argument("--more", type=int, default=20,
                    help="additional outer iterations")
    ap.add_argument("--n", type=int, default=64)
    ap.add_argument("--side", type=int, default=50)
    ap.add_argument("--out", default="artifacts_family_cpu64")
    args = ap.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models.learn import learn
    from ccsc_code_iccv2017_tpu.utils import display, io_mat

    os.makedirs(args.out, exist_ok=True)
    plat = jax.devices()[0].platform
    init = io_mat.load_filters_3d(args.bank)
    k, support = init.shape[0], init.shape[1]
    print(f"continuing from {args.bank} {init.shape} on {plat}",
          flush=True)

    b = synth_video(args.n, args.side, args.side)
    geom = ProblemGeom((support,) * 3, k)
    knobs = (
        dict(fft_impl="matmul", storage_dtype="bfloat16",
             d_storage_dtype="bfloat16")
        if plat in ("tpu", "axon") else {}
    )
    cfg = LearnConfig(
        max_it=args.more, tol=1e-2, rho_d=5000.0, rho_z=1.0,
        num_blocks=8, verbose="brief", track_objective=True, **knobs,
    )
    # pre-flight: the in-memory n=64 learn materializes full-batch
    # code spectra; on a chip whose HBM the estimate exceeds, the
    # compile-then-OOM attempt costs ~5 min before failing (the r5
    # full-scale 3D train did exactly that). Warm-start requires
    # init_d, which the streaming learner does not take — so this is
    # an explicit error, not a silent fallback (ADVICE open item).
    est, budget = inmem_learn_estimate(b.shape, geom, cfg)
    if plat in ("tpu", "axon") and est > budget:
        raise SystemExit(
            f"continue_3d pre-flight: the in-memory n={args.n} learn "
            f"needs ~{est / 1e9:.1f} GB of full-batch temps, over the "
            f"~{budget / 1e9:.0f} GB device budget (CCSC_INMEM_HBM_GB) "
            "— it would compile for minutes and then OOM. Run with "
            "JAX_PLATFORMS=cpu (host RAM), shrink --n, or train from "
            "scratch with the streaming learner "
            "(scripts/family_banks.py, which falls back to it; "
            "streaming cannot warm-start from --bank)."
        )
    t0 = time.time()
    res = learn(jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0),
                init_d=jnp.asarray(init))
    t = time.time() - t0
    io_mat.save_filters(
        os.path.join(args.out, "bank_3d_cont.mat"), res.d, res.trace,
        layout="3d",
    )
    display.save_filter_mosaic(
        os.path.join(args.out, "mosaic_3d_cont.png"),
        central_slice(np.asarray(res.d), "3d"),
        title=f"3D bank, +{args.more} warm-started iterations",
    )

    # identical held-out evaluation to family_banks.py's 3D leg —
    # the SAME function (family_banks.heldout_psnr_3d), not a copy
    own = float(heldout_psnr_3d(np.asarray(res.d), args.side))
    shipped = float(
        heldout_psnr_3d(io_mat.load_filters_3d(SHIPPED["3d"]), args.side)
    )
    out = {
        "family": "3d_continued",
        "extra_it": args.more,
        "t_learn_s": round(t, 1),
        "platform": plat,
        "own_psnr": round(own, 2),
        "shipped_psnr": round(shipped, 2),
        "obj": float(res.trace["obj_vals_z"][-1]),
    }
    with open(os.path.join(args.out, "result_3d_cont.json"), "w") as f:
        json.dump(out, f)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
