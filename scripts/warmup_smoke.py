#!/usr/bin/env python
"""Warmup smoke: cold-vs-warm compiled-artifact-store startup, end to
end, in one process tree — the CI proof that pre-warmed elasticity
(serve.artifacts) actually skips XLA, not just that the store
round-trips bytes.

Two child engine startups against the SAME store directory:

  cold   fresh store: every bucket program is live-compiled and its
         AOT-serialized executable published (artifact_publish won)
  warm   a NEW process on the now-populated store: every bucket
         program is fetched + deserialized (artifact_fetch hit,
         serve_warmup source=fetched) and the obs stream carries
         ZERO backend-compile events for the bucket program
         (fun_name ccsc_bucket_program) — the assertion is read from
         the CompileMonitor events in the metrics stream, not from
         wall-clock deltas, so a fast machine cannot fake it

Both runs serve one request (the fetched executable must actually
execute, not just deserialize) and append a ``kind=warmup`` perf-
ledger record (CCSC_PERF_LEDGER armed to a scratch file); the warm
record must carry ``n_compiles=0``.

Usage:
    JAX_PLATFORMS=cpu python scripts/warmup_smoke.py

Exit 0 iff every assertion holds. scripts/ci.sh runs this as its
warmup leg (exit code 25 on failure).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _child_code(store, mdir):
    """One engine startup: two tiny buckets, artifact store armed,
    one served request, startup seconds on stdout as JSON."""
    return f"""
import json, time
t0 = time.monotonic()
import numpy as np
from ccsc_code_iccv2017_tpu.config import (
    ProblemGeom, ServeConfig, SolveConfig)
from ccsc_code_iccv2017_tpu.models.reconstruct import (
    ReconstructionProblem)
from ccsc_code_iccv2017_tpu.serve import CodecEngine
r = np.random.default_rng(0)
d = r.normal(size=(4, 3, 3)).astype(np.float32)
d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
cfg = SolveConfig(lambda_residual=5.0, lambda_prior=0.3, max_it=3,
                  tol=0.0, verbose="none", track_psnr=True,
                  track_objective=True)
eng = CodecEngine(
    d, ReconstructionProblem(ProblemGeom((3, 3), 4)), cfg,
    ServeConfig(buckets=((2, (12, 12)), (2, (16, 16))),
                max_wait_ms=2.0, artifact_store={store!r},
                metrics_dir={mdir!r}, verbose="none"),
)
startup_s = time.monotonic() - t0
x = r.random((12, 12)).astype(np.float32)
m = (r.random((12, 12)) < 0.5).astype(np.float32)
res = eng.submit(x * m, mask=m, x_orig=x).result(timeout=180)
eng.close()
print(json.dumps({{"startup_s": startup_s,
                   "psnr": float(res.psnr or 0.0)}}), flush=True)
"""


def _run_child(store, mdir, env):
    p = subprocess.run(
        [sys.executable, "-c", _child_code(store, mdir)],
        capture_output=True, text=True, env=env, timeout=480,
    )
    if p.returncode != 0:
        print(p.stdout)
        print(p.stderr, file=sys.stderr)
        raise RuntimeError(f"child engine failed (rc={p.returncode})")
    return json.loads(p.stdout.strip().splitlines()[-1])


def _bucket_compiles(events):
    """Backend-compile events attributable to the bucket program (the
    engine names it ccsc_bucket_program for exactly this filter)."""
    return [
        e for e in events
        if e["type"] == "compile" and e.get("kind") == "compile"
        and "ccsc_bucket_program" in (e.get("fun_name") or "")
    ]


def main() -> int:
    from ccsc_code_iccv2017_tpu.utils import obs

    checks = []

    def check(name, ok, detail=""):
        checks.append(ok)
        print(f"[{'PASS' if ok else 'FAIL'}] {name}"
              + (f": {detail}" if detail else ""))

    with tempfile.TemporaryDirectory() as root:
        store = os.path.join(root, "artifacts")
        ledger = os.path.join(root, "ledger.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   CCSC_PERF_LEDGER=ledger)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get(
            "PYTHONPATH", "")
        # any ambient persistent XLA cache would let the warm run
        # "cheat" with cache-hit compiles — the point is the store
        env.pop("CCSC_COMPILE_CACHE", None)

        cold = _run_child(store, os.path.join(root, "m-cold"), env)
        warm = _run_child(store, os.path.join(root, "m-warm"), env)

        cev = obs.read_events(os.path.join(root, "m-cold"),
                              recursive=True)
        wev = obs.read_events(os.path.join(root, "m-warm"),
                              recursive=True)

        pubs = [e for e in cev if e["type"] == "artifact_publish"
                and e.get("status") in ("won", "repair")]
        check("cold run publishes both bucket executables",
              len(pubs) == 2, f"published={len(pubs)}")
        check("cold run live-compiles the bucket program",
              len(_bucket_compiles(cev)) >= 1,
              f"bucket_compiles={len(_bucket_compiles(cev))}")

        wcomp = _bucket_compiles(wev)
        check("warm run performs ZERO bucket-program XLA compiles",
              len(wcomp) == 0, f"bucket_compiles={len(wcomp)}")
        fetches = [e for e in wev if e["type"] == "artifact_fetch"]
        check("warm run fetches every bucket from the store",
              len(fetches) == 2
              and all(e.get("status") == "hit" for e in fetches),
              f"statuses={[e.get('status') for e in fetches]}")
        sources = [e.get("source") for e in wev
                   if e["type"] == "serve_warmup"]
        check("warm run warms every bucket from fetched artifacts",
              sources and all(s == "fetched" for s in sources),
              f"sources={sources}")
        ready = [e for e in wev if e["type"] == "serve_ready"]
        check("warm serve_ready reports n_compiled=0",
              len(ready) == 1 and ready[0].get("n_compiled") == 0,
              f"serve_ready={[(e.get('n_fetched'), e.get('n_compiled')) for e in ready]}")
        check("warm run serves a real request off the fetched "
              "executable", warm.get("psnr", 0.0) > 0.0,
              f"psnr={warm.get('psnr'):.2f}")

        recs = []
        if os.path.exists(ledger):
            with open(ledger) as f:
                recs = [json.loads(ln) for ln in f
                        if ln.strip()]
        wrecs = [r for r in recs if r.get("kind") == "warmup"]
        check("both startups append kind=warmup ledger records",
              len(wrecs) == 2, f"warmup_records={len(wrecs)}")
        check("warm ledger record carries n_compiles=0",
              bool(wrecs) and wrecs[-1].get("n_compiles") == 0,
              f"n_compiles={[r.get('n_compiles') for r in wrecs]}")

        print(f"cold startup {cold['startup_s']:.2f}s -> warm startup "
              f"{warm['startup_s']:.2f}s "
              f"({cold['startup_s'] / max(warm['startup_s'], 1e-9):.1f}x)")
    n_fail = sum(1 for ok in checks if not ok)
    print(f"{len(checks) - n_fail}/{len(checks)} warmup checks passed")
    return 1 if n_fail else 0


if __name__ == "__main__":
    sys.exit(main())
