#!/usr/bin/env python
"""Full-operating-point 2D filter bank: learn THIS framework's own
k=100 11x11 bank at the reference protocol and prove it reconstructs
at least as well as the shipped reference bank (VERDICT r1 missing #5).

Protocol (2D/learn_kernels_2D_large.m:8-45): gray + local_cn + zero
mean images -> consensus learner, kernel [11,11,100],
lambda_res=lambda=1.0, max_it=20, tol=1e-3, 8 blocks, rho 5000/1
(dzParallel.m:99,112,154) -> save bank + mosaic + trace. Training data:
overlapping 100x100 tiles of the 10 shipped Test jpgs (the only images
the reference repo ships; its own Large_Datset folder is absent).

Evaluation (reconstruct_2D_subsampling.m protocol): 50% random mask
inpainting on the 10 Test images at native 256^2, lambda_res=5.0,
lambda=2.0, max_it=100, same masks for both banks; per-image PSNR of
the learned bank vs the shipped Filters_ours_2D_large.mat.

Writes: <out>/learned_bank.mat, filters_mosaic.png, trace + PSNR table
in ARTIFACTS_2D.md.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

TEST_DIR = "/root/reference/2D/Inpainting/Test"
SHIPPED = "/root/reference/2D/Filters/Filters_ours_2D_large.mat"


def tile_crops(imgs, side, n_target):
    """Overlapping side x side tiles, evenly strided to reach
    ~n_target crops over the stack."""
    import numpy as np

    n_img, H, W = imgs.shape
    per = max(1, round(n_target / n_img))
    g = max(1, int(np.ceil(np.sqrt(per))))
    ys = np.linspace(0, H - side, g).astype(int)
    xs = np.linspace(0, W - side, g).astype(int)
    out = [
        im[y : y + side, x : x + side]
        for im in imgs
        for y in ys
        for x in xs
    ]
    return np.stack(out)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=320, help="training crops")
    ap.add_argument("--crop", type=int, default=100)
    ap.add_argument("--blocks", type=int, default=8)
    ap.add_argument("--max-it", type=int, default=20)
    ap.add_argument("--eval-max-it", type=int, default=100)
    ap.add_argument("--streaming", action="store_true")
    ap.add_argument("--out", default="artifacts_2d")
    args = ap.parse_args()

    import numpy as np

    from ccsc_code_iccv2017_tpu.config import (
        LearnConfig,
        ProblemGeom,
        SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.data.images import load_images
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem,
        reconstruct,
    )
    from ccsc_code_iccv2017_tpu.utils import display
    from ccsc_code_iccv2017_tpu.utils.io_mat import (
        load_filters_2d,
        save_filters,
    )

    os.makedirs(args.out, exist_ok=True)
    import jax
    import jax.numpy as jnp

    print("platform:", jax.devices()[0].platform, flush=True)

    # ---- training data: local_cn tiles (learn_kernels_2D_large.m:8-11)
    imgs = load_images(
        TEST_DIR, contrast_normalize="local_cn", zero_mean=True
    )
    b = tile_crops(imgs, args.crop, args.n)
    n = (b.shape[0] // args.blocks) * args.blocks
    b = b[:n]
    print(f"training tiles: {b.shape}", flush=True)

    geom = ProblemGeom((11, 11), 100)
    cfg = LearnConfig(
        lambda_residual=1.0,
        lambda_prior=1.0,
        max_it=args.max_it,
        max_it_d=5,
        max_it_z=10,
        tol=1e-3,
        rho_d=5000.0,
        rho_z=1.0,
        num_blocks=args.blocks,
        verbose="brief",
        track_objective=True,
    )
    t0 = time.time()
    if args.streaming:
        from ccsc_code_iccv2017_tpu.parallel.streaming import (
            learn_streaming,
        )

        res = learn_streaming(b, geom, cfg, key=jax.random.PRNGKey(0))
    else:
        from ccsc_code_iccv2017_tpu.models.learn import learn

        res = learn(jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0))
    t_learn = time.time() - t0
    print(f"learned in {t_learn:.1f}s", flush=True)

    bank = os.path.join(args.out, "learned_bank.mat")
    # keep a handful of Dz examples like the shipped artifact (its Dz
    # holds 5 reconstructions, SURVEY.md section 6)
    save_filters(bank, res.d, res.trace, layout="2d", Dz=res.Dz[:8])
    display.save_filter_mosaic(
        os.path.join(args.out, "filters_mosaic.png"),
        np.asarray(res.d),
        title=f"learned k=100 11x11 ({args.max_it} it)",
    )

    # ---- evaluation: inpainting PSNR, learned vs shipped ------------
    from ccsc_code_iccv2017_tpu.apps.inpaint_2d import smooth_fill

    test = load_images(TEST_DIR)  # 'none' mode (reconstruct_2D:13)
    rng = np.random.default_rng(7)
    masks = (rng.uniform(size=test.shape) > 0.5).astype(np.float32)
    sm = smooth_fill(test * masks, masks)
    prob = ReconstructionProblem(ProblemGeom((11, 11), 100))
    scfg = SolveConfig(
        lambda_residual=5.0,
        lambda_prior=2.0,
        max_it=args.eval_max_it,
        tol=1e-3,
        verbose="none",
    )

    def psnrs(d):
        r = reconstruct(
            jnp.asarray(test * masks),
            jnp.asarray(np.asarray(d, np.float32)),
            prob,
            scfg,
            mask=jnp.asarray(masks),
            smooth_init=jnp.asarray(sm),
            x_orig=jnp.asarray(test),
        )
        rec = np.clip(np.asarray(r.recon), 0, 1)
        mse = np.mean((rec - test) ** 2, axis=(1, 2))
        return 10 * np.log10(1.0 / np.maximum(mse, 1e-12))
    sm_mse = np.mean((np.clip(sm, 0, 1) - test) ** 2, axis=(1, 2))
    p_fill = 10 * np.log10(1.0 / np.maximum(sm_mse, 1e-12))

    p_learned = psnrs(np.asarray(res.d))
    p_shipped = psnrs(load_filters_2d(SHIPPED))

    lines = [
        "# ARTIFACTS — full-operating-point 2D bank",
        "",
        f"Learned k=100 11x11, max_it={args.max_it}, n={n} local_cn "
        f"{args.crop}^2 tiles of the 10 shipped Test jpgs, 8 blocks, "
        f"rho 5000/1 (learn_kernels_2D_large.m protocol) in "
        f"{t_learn:.1f}s on {jax.devices()[0].platform}.",
        "",
        "Inpainting, 50% random mask, 10 Test images at 256^2, "
        f"lambda_res=5 lambda=2 max_it={args.eval_max_it} "
        "(reconstruct_2D_subsampling.m protocol), same masks for both "
        "banks:",
        "",
        "| image | learned bank PSNR | shipped bank PSNR | "
        "smooth-fill baseline |",
        "|---|---|---|---|",
    ]
    for i, (pl, ps, pf) in enumerate(zip(p_learned, p_shipped, p_fill)):
        lines.append(f"| {i}.jpg | {pl:.2f} | {ps:.2f} | {pf:.2f} |")
    lines += [
        f"| **mean** | **{p_learned.mean():.2f}** | "
        f"**{p_shipped.mean():.2f}** | **{p_fill.mean():.2f}** |",
        "",
        f"Final objective: {res.trace['obj_vals_z'][-1]:.6g}; "
        f"trace in {bank}.",
    ]
    md = "\n".join(lines)
    with open(os.path.join(args.out, "ARTIFACTS_2D.md"), "w") as f:
        f.write(md + "\n")
    print(md)
    print(
        json.dumps(
            {
                "learned_mean_psnr": round(float(p_learned.mean()), 3),
                "shipped_mean_psnr": round(float(p_shipped.mean()), 3),
                "t_learn_s": round(t_learn, 1),
                "n": int(n),
            }
        )
    )


if __name__ == "__main__":
    main()
