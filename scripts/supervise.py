#!/usr/bin/env python
"""Run supervisor: launch a learner CLI, watch it, restart from its
checkpoint until the run actually completes.

The in-process resilience layer (utils.resilience) survives what a
process can survive: divergence, preemption signals, torn snapshots.
It cannot survive the process itself dying — a segfaulting runtime, an
OOM kill, a watchdog stall abort (utils.watchdog), a wedged dispatch.
Multi-block consensus ADMM tolerates restart from any block boundary
(PAPERS.md arXiv:1312.3040), and every learner here checkpoints at
those boundaries — so the missing piece is purely supervisory, and ad
hoc ``while true; do python learn_2d.py; done`` loops get none of the
judgment below. This script is that piece:

- launches the given command as a child process (everything after
  ``--``), teeing its output to a per-attempt log file;
- tails the run's telemetry (``--metrics-dir``, utils.obs) and the
  checkpoint dir for PROGRESS — a child that is alive but has written
  nothing for ``--stall-timeout`` seconds is declared hung, killed
  (SIGTERM, then SIGKILL) and restarted; the in-process watchdog's
  stall abort (exit code 87) is recognized the same way;
- on any crash, restarts from ``--checkpoint-dir`` with exponential
  backoff (``--backoff`` * 2^k, capped) up to ``--max-restarts``;
- on a CLEAN exit, decides completed-vs-preempted from the event
  stream: an attempt whose records include a ``preemption`` was asked
  to stop early and is resumed; one that ran to its summary without
  preemption is done;
- poison-run detection: two consecutive deaths before the FIRST
  checkpoint ever lands mean restarts cannot help (the run dies
  deterministically in setup/compile) — abort with a diagnosis and
  the tail of the last attempt's log instead of burning the restart
  budget;
- writes a parity-checkable trace of every attempt (reason, exit
  code, timestamps, checkpoint presence) to ``--trace`` (default
  ``<metrics-dir>/supervisor_trace.json``), re-written after every
  attempt so the trace survives the supervisor itself being killed.

The supervisor also exports ``CCSC_FAULT_STATE_DIR`` to the child (set
to the metrics dir) so injected chaos faults (utils.faults) stay
fire-once ACROSS restarts — the property tests/test_supervised.py
leans on.

Usage:
    python scripts/supervise.py --checkpoint-dir CK --metrics-dir M \\
        [--max-restarts 5] [--backoff 5] [--stall-timeout 0] \\
        -- python -m ccsc_code_iccv2017_tpu.apps.learn_2d --data ... \\
           --checkpoint-dir CK --metrics-dir M

Exit codes: 0 completed; 2 poison run; 3 restart budget exhausted;
4 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils import obs  # noqa: E402
from ccsc_code_iccv2017_tpu.utils.watchdog import EXIT_STALL  # noqa: E402

EXIT_OK = 0
EXIT_POISON = 2
EXIT_EXHAUSTED = 3
EXIT_USAGE = 4

_CKPT_FILES = ("ccsc_state.npz", "ccsc_state.prev.npz")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "--checkpoint-dir", default=None,
        help="the child's checkpoint dir — the restart point, and the "
        "poison-run detector's evidence of first progress",
    )
    p.add_argument(
        "--metrics-dir", default=None,
        help="the child's utils.obs metrics dir: progress signal for "
        "hang detection, preempted-vs-completed on clean exits, and "
        "the fault-marker state dir (CCSC_FAULT_STATE_DIR)",
    )
    p.add_argument(
        "--max-restarts", type=int, default=5,
        help="crash-restart budget (crashes, stall aborts, hang "
        "kills). Orderly preemptions — clean exits that checkpointed "
        "and asked to be resumed — have their own budget "
        "(--max-preemptions): a healthy run on preemptible capacity "
        "must not be abandoned for being preempted often",
    )
    p.add_argument("--max-preemptions", type=int, default=100)
    p.add_argument(
        "--backoff", type=float, default=5.0,
        help="base restart delay; attempt k sleeps backoff * 2^(k-1), "
        "capped at --backoff-cap",
    )
    p.add_argument("--backoff-cap", type=float, default=300.0)
    p.add_argument(
        "--stall-timeout", type=float, default=0.0,
        help="kill the child when its metrics/checkpoint dirs show no "
        "progress for this many seconds (0 = rely on the in-process "
        "watchdog's stall abort only)",
    )
    p.add_argument(
        "--trace", default=None,
        help="where to write the supervisor trace JSON (default "
        "<metrics-dir>/supervisor_trace.json)",
    )
    p.add_argument(
        "--log-dir", default=None,
        help="per-attempt child logs (default <metrics-dir>, else cwd)",
    )
    p.add_argument(
        "cmd", nargs=argparse.REMAINDER,
        help="the learner command, after a literal --",
    )
    return p


def _progress_stamp(paths):
    """A monotone token of on-disk progress: newest (mtime, size) over
    every file under the watched dirs. Changes whenever the child
    writes an event, a heartbeat, or a checkpoint."""
    stamp = (0.0, 0)
    for root in paths:
        if not root or not os.path.isdir(root):
            continue
        try:
            names = os.listdir(root)
        except OSError:
            continue
        for name in names:
            fp = os.path.join(root, name)
            try:
                st = os.stat(fp)
            except OSError:
                continue
            stamp = max(stamp, (st.st_mtime, st.st_size))
    return stamp


def _checkpoint_exists(checkpoint_dir) -> bool:
    if not checkpoint_dir:
        return False
    return any(
        os.path.exists(os.path.join(checkpoint_dir, f))
        for f in _CKPT_FILES
    )


def _attempt_preempted(metrics_dir) -> bool:
    """Whether the NEWEST attempt in the event stream was preempted
    (asked to checkpoint-and-exit early) — a clean exit that still
    wants a resume. Records after the last run_meta are that attempt's."""
    if not metrics_dir:
        return False
    events = obs.read_events(metrics_dir)
    last_meta = max(
        (i for i, e in enumerate(events) if e.get("type") == "run_meta"),
        default=-1,
    )
    return any(
        e.get("type") == "preemption" for e in events[last_meta + 1 :]
    )


def _tail(path, nbytes=2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - nbytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return "(no log)"


class Supervisor:
    def __init__(self, args):
        self.args = args
        self.attempts = []
        self.restarts = 0  # crash restarts (charged to --max-restarts)
        self.resumes = 0  # preemption resumes (--max-preemptions)
        self.outcome = None
        base = args.metrics_dir or "."
        self.trace_path = args.trace or os.path.join(
            base, "supervisor_trace.json"
        )
        self.log_dir = args.log_dir or base
        os.makedirs(self.log_dir, exist_ok=True)
        if args.metrics_dir:
            os.makedirs(args.metrics_dir, exist_ok=True)

    # -- trace ---------------------------------------------------------
    def _write_trace(self):
        os.makedirs(
            os.path.dirname(os.path.abspath(self.trace_path)),
            exist_ok=True,
        )
        tmp = self.trace_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "cmd": self.args.cmd,
                    "checkpoint_dir": self.args.checkpoint_dir,
                    "metrics_dir": self.args.metrics_dir,
                    "max_restarts": self.args.max_restarts,
                    "restarts": self.restarts,
                    "resumes": self.resumes,
                    "outcome": self.outcome,
                    "attempts": self.attempts,
                },
                f,
                indent=2,
            )
        os.replace(tmp, self.trace_path)

    # -- one attempt ---------------------------------------------------
    def _run_attempt(self, n: int):
        a = self.args
        log_path = os.path.join(self.log_dir, f"supervise-attempt-{n}.log")
        env = dict(os.environ)
        if a.metrics_dir:
            # fault fire-once markers survive restarts (utils.faults)
            env.setdefault("CCSC_FAULT_STATE_DIR", a.metrics_dir)
        watched = (a.metrics_dir, a.checkpoint_dir)
        rec = {
            "attempt": n,
            "start_t": time.time(),
            "log": log_path,
            "checkpoint_at_start": _checkpoint_exists(a.checkpoint_dir),
        }
        with open(log_path, "wb") as logf:
            proc = subprocess.Popen(
                a.cmd, stdout=logf, stderr=subprocess.STDOUT, env=env
            )
            stamp = _progress_stamp(watched)
            quiet_since = time.monotonic()
            killed_for_hang = False
            while True:
                try:
                    proc.wait(timeout=1.0)
                    break
                except subprocess.TimeoutExpired:
                    pass
                if a.stall_timeout <= 0:
                    continue
                new_stamp = _progress_stamp(watched)
                now = time.monotonic()
                if new_stamp != stamp:
                    stamp = new_stamp
                    quiet_since = now
                elif now - quiet_since > a.stall_timeout:
                    print(
                        f"supervise: no progress for {a.stall_timeout:g}s"
                        " — declaring the child hung, killing it",
                        flush=True,
                    )
                    killed_for_hang = True
                    proc.send_signal(signal.SIGTERM)
                    try:
                        proc.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    break
        rc = proc.returncode
        rec.update(
            end_t=time.time(),
            rc=rc,
            checkpoint_present=_checkpoint_exists(a.checkpoint_dir),
        )
        if killed_for_hang:
            rec["reason"] = "hang"
        elif rc == EXIT_STALL:
            rec["reason"] = "stall_abort"
        elif rc != 0:
            rec["reason"] = "crash"
        elif _attempt_preempted(a.metrics_dir):
            rec["reason"] = "preempted"
        else:
            rec["reason"] = "completed"
        return rec

    # -- the loop ------------------------------------------------------
    def run(self) -> int:
        a = self.args
        pre_ckpt_deaths = 0
        attempt = 0
        while True:
            attempt += 1
            rec = self._run_attempt(attempt)
            self.attempts.append(rec)
            self._write_trace()
            reason = rec["reason"]
            print(
                f"supervise: attempt {attempt} -> {reason} "
                f"(rc={rec['rc']})",
                flush=True,
            )
            if reason == "completed":
                self.outcome = "completed"
                self._write_trace()
                return EXIT_OK
            # every other reason wants a relaunch — judge it first
            died = reason in ("crash", "stall_abort", "hang")
            if died and not rec["checkpoint_present"]:
                pre_ckpt_deaths += 1
                if pre_ckpt_deaths >= 2:
                    self.outcome = "poison"
                    self._write_trace()
                    print(
                        "supervise: POISON RUN — two consecutive deaths "
                        "before the first checkpoint ever landed; a "
                        "restart cannot help (the run dies "
                        "deterministically in setup/compile). Last "
                        "output:\n" + _tail(rec["log"]),
                        flush=True,
                    )
                    return EXIT_POISON
            else:
                pre_ckpt_deaths = 0
            if not died:
                # an orderly preemption checkpointed and asked to be
                # resumed: it consumes its OWN (generous) budget, not
                # the crash-restart budget — a healthy run on
                # preemptible capacity is resumed, not abandoned. No
                # backoff either: nothing is broken.
                if self.resumes >= a.max_preemptions:
                    self.outcome = "exhausted"
                    self._write_trace()
                    print(
                        "supervise: preemption-resume budget "
                        f"({a.max_preemptions}) exhausted.",
                        flush=True,
                    )
                    return EXIT_EXHAUSTED
                self.resumes += 1
                print(
                    f"supervise: resuming preempted run (resume "
                    f"{self.resumes}/{a.max_preemptions})",
                    flush=True,
                )
                continue
            if self.restarts >= a.max_restarts:
                self.outcome = "exhausted"
                self._write_trace()
                print(
                    f"supervise: restart budget ({a.max_restarts}) "
                    "exhausted. Last output:\n" + _tail(rec["log"]),
                    flush=True,
                )
                return EXIT_EXHAUSTED
            self.restarts += 1
            delay = min(
                a.backoff * (2 ** (self.restarts - 1)), a.backoff_cap
            )
            if delay > 0:
                print(
                    f"supervise: restart {self.restarts}/"
                    f"{a.max_restarts} in {delay:g}s",
                    flush=True,
                )
                time.sleep(delay)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print(
            "supervise: no command given — pass the learner CLI after "
            "`--`",
            file=sys.stderr,
        )
        return EXIT_USAGE
    args.cmd = cmd
    return Supervisor(args).run()


if __name__ == "__main__":
    sys.exit(main())
