#!/usr/bin/env python
"""Run supervisor: launch a learner CLI, watch it, restart from its
checkpoint until the run actually completes.

The in-process resilience layer (utils.resilience) survives what a
process can survive: divergence, preemption signals, torn snapshots.
It cannot survive the process itself dying — a segfaulting runtime, an
OOM kill, a watchdog stall abort (utils.watchdog), a wedged dispatch.
Multi-block consensus ADMM tolerates restart from any block boundary
(PAPERS.md arXiv:1312.3040), and every learner here checkpoints at
those boundaries — so the missing piece is purely supervisory, and ad
hoc ``while true; do python learn_2d.py; done`` loops get none of the
judgment below. This script is that piece:

- launches the given command as a child process (everything after
  ``--``), teeing its output to a per-attempt log file;
- tails the run's telemetry (``--metrics-dir``, utils.obs) and the
  checkpoint dir for PROGRESS — a child that is alive but has written
  nothing for ``--stall-timeout`` seconds is declared hung, killed
  (SIGTERM, then SIGKILL) and restarted; the in-process watchdog's
  stall abort (exit code 87) is recognized the same way;
- on any crash, restarts from ``--checkpoint-dir`` with exponential
  backoff (``--backoff`` * 2^k, capped) up to ``--max-restarts``;
- on a CLEAN exit, decides completed-vs-preempted from the event
  stream: an attempt whose records include a ``preemption`` was asked
  to stop early and is resumed; one that ran to its summary without
  preemption is done;
- poison-run detection: two consecutive deaths before the FIRST
  checkpoint ever lands mean restarts cannot help (the run dies
  deterministically in setup/compile) — abort with a diagnosis and
  the tail of the last attempt's log instead of burning the restart
  budget;
- writes a parity-checkable trace of every attempt (reason, exit
  code, timestamps, checkpoint presence) to ``--trace`` (default
  ``<metrics-dir>/supervisor_trace.json``), re-written after every
  attempt so the trace survives the supervisor itself being killed.

``--metrics-dir`` is repeatable: a serving-fleet child
(serve.ServeFleet) writes its fleet stream at the top level and one
stream per replica in ``replica-NN/`` subdirs — pass each dir and the
supervisor judges progress across all of them, and preemption PER DIR
(a preemption record in any one replica's newest attempt marks the
child preempted; merging the dirs into one stream would scope every
record to whichever dir's run_meta happens to be newest).

Multi-child mode (``--child``, repeatable): supervise N children —
e.g. one serving engine per chip behind a shared front queue — each
judged and restarted INDEPENDENTLY with its own restart/preemption
budget. Per-child dirs pair with children by index (give N
``--metrics-dir``/``--checkpoint-dir`` flags, or one parent dir from
which ``child-NN`` subdirs are derived). The run completes when every
child completes; a poison child (or an exhausted budget) stops the
whole fleet with the matching exit code.

Federated serving (``--federate DIR``): exports ``CCSC_DQUEUE_DIR`` so
each child started with ``apps/serve.py --federate`` drains the shared
file-lease work queue at DIR (serve.federation) — one supervised child
per host, each joining the pool under a fresh lease epoch on every
(re)start and leaving cleanly on completion. A child killed outright
(even SIGKILL, which no in-process layer survives) leaves only expired
leases; the surviving hosts' reapers requeue its work.

The supervisor also exports ``CCSC_FAULT_STATE_DIR`` to the child (set
to the metrics dir) so injected chaos faults (utils.faults) stay
fire-once ACROSS restarts — the property tests/test_supervised.py
leans on.

Usage:
    python scripts/supervise.py --checkpoint-dir CK --metrics-dir M \\
        [--max-restarts 5] [--backoff 5] [--stall-timeout 0] \\
        -- python -m ccsc_code_iccv2017_tpu.apps.learn_2d --data ... \\
           --checkpoint-dir CK --metrics-dir M

    python scripts/supervise.py --metrics-dir PARENT \\
        --child 'python -m ccsc_code_iccv2017_tpu.apps.serve ...' \\
        --child 'python -m ccsc_code_iccv2017_tpu.apps.serve ...'

Exit codes: 0 completed; 2 poison run; 3 restart budget exhausted;
4 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils import obs  # noqa: E402
from ccsc_code_iccv2017_tpu.utils.watchdog import EXIT_STALL  # noqa: E402

EXIT_OK = 0
EXIT_POISON = 2
EXIT_EXHAUSTED = 3
EXIT_USAGE = 4
# internal: a multi-child sibling failed terminally and this child was
# stopped mid-flight — not this child's own failure, so it never
# becomes the fleet exit code
EXIT_STOPPED = 5

_CKPT_FILES = ("ccsc_state.npz", "ccsc_state.prev.npz")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "--checkpoint-dir", action="append", default=None,
        help="the child's checkpoint dir — the restart point, and the "
        "poison-run detector's evidence of first progress. Repeatable "
        "in multi-child mode (paired with --child by index)",
    )
    p.add_argument(
        "--metrics-dir", action="append", default=None,
        help="the child's utils.obs metrics dir(s): progress signal "
        "for hang detection, preempted-vs-completed on clean exits "
        "(judged PER DIR — a fleet child has one dir per replica), "
        "and the fault-marker state dir (CCSC_FAULT_STATE_DIR). "
        "Repeatable",
    )
    p.add_argument(
        "--child", action="append", default=None, metavar="CMDLINE",
        help="multi-child mode: supervise this shell-quoted command "
        "as one independent child (repeatable; mutually exclusive "
        "with the trailing `-- CMD`). Each child gets its own "
        "restart/preemption budget and its own per-index dirs",
    )
    p.add_argument(
        "--federate", default=None, metavar="DIR",
        help="cross-host federation: export CCSC_DQUEUE_DIR=DIR to "
        "every child so a serving child started with --federate "
        "(apps/serve.py) joins the shared file-lease work queue at "
        "DIR. Each supervised child is one pool host: it joins under "
        "a fresh lease epoch on every (re)start and leaves cleanly "
        "on completion — per-host supervisors join/leave the pool "
        "dynamically, and a child SIGKILLed mid-solve just leaves "
        "expired leases the surviving hosts reap",
    )
    p.add_argument(
        "--max-restarts", type=int, default=5,
        help="crash-restart budget (crashes, stall aborts, hang "
        "kills). Orderly preemptions — clean exits that checkpointed "
        "and asked to be resumed — have their own budget "
        "(--max-preemptions): a healthy run on preemptible capacity "
        "must not be abandoned for being preempted often",
    )
    p.add_argument("--max-preemptions", type=int, default=100)
    p.add_argument(
        "--backoff", type=float, default=5.0,
        help="base restart delay; attempt k sleeps backoff * 2^(k-1), "
        "capped at --backoff-cap",
    )
    p.add_argument("--backoff-cap", type=float, default=300.0)
    p.add_argument(
        "--stall-timeout", type=float, default=0.0,
        help="kill the child when its metrics/checkpoint dirs show no "
        "progress for this many seconds (0 = rely on the in-process "
        "watchdog's stall abort only)",
    )
    p.add_argument(
        "--trace", default=None,
        help="where to write the supervisor trace JSON (default "
        "<metrics-dir>/supervisor_trace.json)",
    )
    p.add_argument(
        "--log-dir", default=None,
        help="per-attempt child logs (default <metrics-dir>, else cwd)",
    )
    p.add_argument(
        "cmd", nargs=argparse.REMAINDER,
        help="the learner command, after a literal --",
    )
    return p


def _progress_stamp(paths):
    """A monotone token of on-disk progress: newest (mtime, size) over
    every file under the watched dirs — accepts a LIST of dirs (a
    fleet child has one metrics dir per replica) and additionally
    scans one level of subdirectories, so a fleet child watched only
    by its top-level dir still shows its replicas' ``replica-NN/``
    stream writes as progress. Changes whenever the child writes an
    event, a heartbeat, or a checkpoint."""
    stamp = (0.0, 0)
    for root in paths:
        if not root or not os.path.isdir(root):
            continue
        try:
            names = os.listdir(root)
        except OSError:
            continue
        for name in names:
            fp = os.path.join(root, name)
            try:
                st = os.stat(fp)
            except OSError:
                continue
            if os.path.isdir(fp):
                try:
                    sub = os.listdir(fp)
                except OSError:
                    continue
                for s in sub:
                    try:
                        sst = os.stat(os.path.join(fp, s))
                    except OSError:
                        continue
                    stamp = max(stamp, (sst.st_mtime, sst.st_size))
                continue
            stamp = max(stamp, (st.st_mtime, st.st_size))
    return stamp


def _checkpoint_exists(checkpoint_dirs) -> bool:
    return any(
        os.path.exists(os.path.join(d, f))
        for d in checkpoint_dirs if d
        for f in _CKPT_FILES
    )


def _dir_preempted(metrics_dir) -> bool:
    events = obs.read_events(metrics_dir)
    last_meta = max(
        (i for i, e in enumerate(events) if e.get("type") == "run_meta"),
        default=-1,
    )
    return any(
        e.get("type") == "preemption" for e in events[last_meta + 1 :]
    )


def _attempt_preempted(metrics_dirs) -> bool:
    """Whether the NEWEST attempt in any of the child's event streams
    was preempted (asked to checkpoint-and-exit early) — a clean exit
    that still wants a resume. Records after the last run_meta are
    that attempt's.

    Judged PER DIR: a fleet child has one stream per replica, and one
    preempted replica marks the child preempted. Merging the dirs into
    a single stream first would scope every record to whichever dir's
    run_meta happens to be newest — a replica that was preempted
    before another replica's restart wrote its run_meta would be
    judged by the wrong attempt."""
    return any(_dir_preempted(d) for d in metrics_dirs if d)


class _PreemptionTail:
    """Incremental form of :func:`_attempt_preempted` for the
    supervisor loop: the stateless judge re-reads every stream from
    byte 0 after EVERY attempt, which over a long supervised fleet
    run (N attempts x M replica streams, each growing monotonically)
    turns the judgment quadratic in the stream size. This tail rides
    ``utils.obs.EventTail`` — one offset per file, only appended
    records are parsed — and folds the same per-dir state machine:
    a ``run_meta`` opens a fresh attempt (clearing the flag), a
    ``preemption`` after it marks the dir preempted."""

    def __init__(self, metrics_dirs):
        self._tails = {
            d: obs.EventTail(d) for d in metrics_dirs if d
        }
        self._flag = {d: False for d in self._tails}

    def preempted(self) -> bool:
        for d, tail in self._tails.items():
            for rec in tail.poll():
                kind = rec.get("type")
                if kind == "run_meta":
                    self._flag[d] = False
                elif kind == "preemption":
                    self._flag[d] = True
        return any(self._flag.values())


def _tail(path, nbytes=2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - nbytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return "(no log)"


class Supervisor:
    """The judgment loop for ONE child. Multi-child mode instantiates
    N of these (one per ``--child``), each with its own budgets, trace,
    and per-index dirs; ``stop_event`` lets a sibling's terminal
    failure stop this child's loop promptly (reason ``fleet_stop``)."""

    def __init__(
        self, args, cmd, metrics_dirs, checkpoint_dirs,
        label="", trace_path=None, stop_event=None,
    ):
        self.args = args
        self.cmd = cmd
        self.metrics_dirs = [m for m in metrics_dirs if m]
        self.checkpoint_dirs = [c for c in checkpoint_dirs if c]
        self.label = label
        self.stop_event = stop_event
        self.attempts = []
        self.restarts = 0  # crash restarts (charged to --max-restarts)
        self.resumes = 0  # preemption resumes (--max-preemptions)
        self.outcome = None
        base = self.metrics_dirs[0] if self.metrics_dirs else "."
        trace_name = (
            f"supervisor_trace-{label}.json"
            if label and not self.metrics_dirs
            else "supervisor_trace.json"
        )
        self.trace_path = trace_path or os.path.join(base, trace_name)
        self.log_dir = args.log_dir or base
        os.makedirs(self.log_dir, exist_ok=True)
        for m in self.metrics_dirs:
            os.makedirs(m, exist_ok=True)
        # incremental preemption judgment across attempts: each
        # judge costs O(records this attempt wrote), not O(stream)
        self._preempt_tail = _PreemptionTail(self.metrics_dirs)

    def _say(self, msg: str) -> None:
        tag = f" [{self.label}]" if self.label else ""
        print(f"supervise{tag}: {msg}", flush=True)

    # -- trace ---------------------------------------------------------
    def _write_trace(self):
        os.makedirs(
            os.path.dirname(os.path.abspath(self.trace_path)),
            exist_ok=True,
        )
        tmp = self.trace_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "cmd": self.cmd,
                    "label": self.label,
                    "checkpoint_dir": self.checkpoint_dirs,
                    "metrics_dir": self.metrics_dirs,
                    "max_restarts": self.args.max_restarts,
                    "restarts": self.restarts,
                    "resumes": self.resumes,
                    "outcome": self.outcome,
                    "attempts": self.attempts,
                },
                f,
                indent=2,
            )
        os.replace(tmp, self.trace_path)

    # -- one attempt ---------------------------------------------------
    def _run_attempt(self, n: int):
        a = self.args
        tag = f"-{self.label}" if self.label else ""
        log_path = os.path.join(
            self.log_dir, f"supervise{tag}-attempt-{n}.log"
        )
        env = dict(os.environ)
        if self.metrics_dirs:
            # fault fire-once markers survive restarts (utils.faults)
            env.setdefault("CCSC_FAULT_STATE_DIR", self.metrics_dirs[0])
        if a.federate:
            # the shared work-queue dir rides the env so a federated
            # serving child (apps/serve.py --federate) joins the pool
            # without per-child flag plumbing
            env["CCSC_DQUEUE_DIR"] = a.federate
        watched = self.metrics_dirs + self.checkpoint_dirs
        rec = {
            "attempt": n,
            "start_t": time.time(),
            "log": log_path,
            "checkpoint_at_start": _checkpoint_exists(
                self.checkpoint_dirs
            ),
        }
        with open(log_path, "wb") as logf:
            proc = subprocess.Popen(
                self.cmd, stdout=logf, stderr=subprocess.STDOUT, env=env
            )
            stamp = _progress_stamp(watched)
            quiet_since = time.monotonic()
            killed_for_hang = False
            killed_for_stop = False

            def _kill():
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()

            while True:
                try:
                    proc.wait(timeout=1.0)
                    break
                except subprocess.TimeoutExpired:
                    pass
                if self.stop_event is not None and self.stop_event.is_set():
                    # a sibling child failed terminally — stop this one
                    self._say("sibling child failed — stopping")
                    killed_for_stop = True
                    _kill()
                    break
                if a.stall_timeout <= 0:
                    continue
                new_stamp = _progress_stamp(watched)
                now = time.monotonic()
                if new_stamp != stamp:
                    stamp = new_stamp
                    quiet_since = now
                elif now - quiet_since > a.stall_timeout:
                    self._say(
                        f"no progress for {a.stall_timeout:g}s"
                        " — declaring the child hung, killing it"
                    )
                    killed_for_hang = True
                    _kill()
                    break
        rc = proc.returncode
        rec.update(
            end_t=time.time(),
            rc=rc,
            checkpoint_present=_checkpoint_exists(self.checkpoint_dirs),
        )
        if killed_for_stop:
            rec["reason"] = "fleet_stop"
        elif killed_for_hang:
            rec["reason"] = "hang"
        elif rc == EXIT_STALL:
            rec["reason"] = "stall_abort"
        elif rc != 0:
            rec["reason"] = "crash"
        elif self._preempt_tail.preempted():
            rec["reason"] = "preempted"
        else:
            rec["reason"] = "completed"
        return rec

    # -- the loop ------------------------------------------------------
    def run(self) -> int:
        a = self.args
        pre_ckpt_deaths = 0
        attempt = 0
        while True:
            attempt += 1
            rec = self._run_attempt(attempt)
            self.attempts.append(rec)
            self._write_trace()
            reason = rec["reason"]
            self._say(f"attempt {attempt} -> {reason} (rc={rec['rc']})")
            if reason == "completed":
                self.outcome = "completed"
                self._write_trace()
                return EXIT_OK
            if reason == "fleet_stop":
                self.outcome = "stopped"
                self._write_trace()
                return EXIT_STOPPED
            # every other reason wants a relaunch — judge it first
            died = reason in ("crash", "stall_abort", "hang")
            if died and not rec["checkpoint_present"]:
                pre_ckpt_deaths += 1
                if pre_ckpt_deaths >= 2:
                    self.outcome = "poison"
                    self._write_trace()
                    self._say(
                        "POISON RUN — two consecutive deaths "
                        "before the first checkpoint ever landed; a "
                        "restart cannot help (the run dies "
                        "deterministically in setup/compile). Last "
                        "output:\n" + _tail(rec["log"])
                    )
                    return EXIT_POISON
            else:
                pre_ckpt_deaths = 0
            if not died:
                # an orderly preemption checkpointed and asked to be
                # resumed: it consumes its OWN (generous) budget, not
                # the crash-restart budget — a healthy run on
                # preemptible capacity is resumed, not abandoned. No
                # backoff either: nothing is broken.
                if self.resumes >= a.max_preemptions:
                    self.outcome = "exhausted"
                    self._write_trace()
                    self._say(
                        "preemption-resume budget "
                        f"({a.max_preemptions}) exhausted."
                    )
                    return EXIT_EXHAUSTED
                self.resumes += 1
                self._say(
                    f"resuming preempted run (resume "
                    f"{self.resumes}/{a.max_preemptions})"
                )
                continue
            if self.restarts >= a.max_restarts:
                self.outcome = "exhausted"
                self._write_trace()
                self._say(
                    f"restart budget ({a.max_restarts}) "
                    "exhausted. Last output:\n" + _tail(rec["log"])
                )
                return EXIT_EXHAUSTED
            self.restarts += 1
            delay = min(
                a.backoff * (2 ** (self.restarts - 1)), a.backoff_cap
            )
            if delay > 0:
                self._say(
                    f"restart {self.restarts}/{a.max_restarts} "
                    f"in {delay:g}s"
                )
                if self.stop_event is not None:
                    # interruptible backoff: a sibling failure must
                    # not leave this child sleeping out its delay
                    self.stop_event.wait(delay)
                else:
                    time.sleep(delay)


def _pair_dirs(dirs, n: int, flag: str):
    """Pair repeated dir flags with N children: N flags pair by index,
    ONE flag is a parent from which child-NN subdirs are derived, none
    means no dirs. Anything else is a usage error."""
    if not dirs:
        return [[] for _ in range(n)]
    if len(dirs) == n:
        return [[d] for d in dirs]
    if len(dirs) == 1:
        return [
            [os.path.join(dirs[0], f"child-{i:02d}")] for i in range(n)
        ]
    raise ValueError(
        f"{flag}: got {len(dirs)} dirs for {n} children — give one "
        "per child (paired by index), a single parent dir (child-NN "
        "subdirs are derived), or none"
    )


def _run_fleet(args, mdirs, ckdirs) -> int:
    """Multi-child mode: one Supervisor per ``--child``, each driven on
    its own thread with independent budgets. The run completes when
    every child completes; the FIRST terminal failure (poison,
    exhausted budget) stops the siblings and becomes the exit code."""
    import threading

    cmds = [shlex.split(c) for c in args.child]
    n = len(cmds)
    try:
        m_per = _pair_dirs(mdirs, n, "--metrics-dir")
        ck_per = _pair_dirs(ckdirs, n, "--checkpoint-dir")
    except ValueError as e:
        print(f"supervise: {e}", file=sys.stderr)
        return EXIT_USAGE
    stop = threading.Event()
    sups = [
        Supervisor(
            args, cmds[i], m_per[i], ck_per[i],
            label=f"child-{i:02d}", stop_event=stop,
        )
        for i in range(n)
    ]
    codes = [None] * n

    def _drive(i):
        try:
            codes[i] = sups[i].run()
        except BaseException:  # a crashed supervisor fails the fleet
            codes[i] = EXIT_EXHAUSTED
            raise
        finally:
            if codes[i] not in (EXIT_OK, EXIT_STOPPED):
                stop.set()

    threads = [
        threading.Thread(
            target=_drive, args=(i,), name=f"supervise-child-{i:02d}"
        )
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rc = next(
        (c for c in codes if c not in (EXIT_OK, EXIT_STOPPED)), EXIT_OK
    )
    if args.trace:
        # fleet-level summary next to the per-child traces
        tmp = args.trace + ".tmp"
        os.makedirs(
            os.path.dirname(os.path.abspath(args.trace)), exist_ok=True
        )
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "children": [
                        {
                            "label": s.label,
                            "cmd": s.cmd,
                            "outcome": s.outcome,
                            "rc": codes[i],
                            "trace": s.trace_path,
                        }
                        for i, s in enumerate(sups)
                    ],
                    "rc": rc,
                },
                f,
                indent=2,
            )
        os.replace(tmp, args.trace)
    print(
        f"supervise: fleet done — "
        + ", ".join(f"{s.label}={s.outcome}" for s in sups),
        flush=True,
    )
    return rc


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    mdirs = list(args.metrics_dir or [])
    ckdirs = list(args.checkpoint_dir or [])
    if args.child:
        if cmd:
            print(
                "supervise: --child and a trailing `-- CMD` are "
                "mutually exclusive",
                file=sys.stderr,
            )
            return EXIT_USAGE
        return _run_fleet(args, mdirs, ckdirs)
    if not cmd:
        print(
            "supervise: no command given — pass the learner CLI after "
            "`--` (or use --child)",
            file=sys.stderr,
        )
        return EXIT_USAGE
    sup = Supervisor(args, cmd, mdirs, ckdirs, trace_path=args.trace)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
