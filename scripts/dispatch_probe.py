#!/usr/bin/env python
"""Per-dispatch overhead probe for the axon TPU tunnel.

The r4 microbenchmark showed even a pure elementwise op moving bytes
at ~10% of datasheet HBM bandwidth. Two hypotheses: (a) the kernels
are bandwidth-inefficient, (b) a fixed per-call overhead (tunnel
round-trip + dispatch) dominates at these sizes. This probe times one
jitted elementwise op across sizes spanning 4 decades; the y-intercept
of time-vs-bytes is the fixed overhead, the slope is the real
streaming bandwidth. Prints one JSON line per size plus a fit line.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import jax
import jax.numpy as jnp
import numpy as np


def main():
    reps = int(os.environ.get("DP_REPS", 10))
    sizes_mb = [0.004, 0.04, 0.4, 4, 40, 400]
    rows = []
    for mb in sizes_mb:
        n = max(int(mb * 1e6 / 4), 256)
        x = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)

        def st(a):
            o = jnp.sign(a) * jnp.maximum(jnp.abs(a) - 0.1, 0.0)
            # reduce over the WHOLE result: a [0]-element fence would
            # let XLA sink the slice and never stream the array
            return jnp.sum(o)

        f = jax.jit(st)
        float(f(x))  # compile + fence
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(x)
        float(out)
        dt = (time.perf_counter() - t0) / reps
        rows.append((2 * n * 4, dt))  # read + write bytes
        print(
            json.dumps(
                {"bytes": 2 * n * 4, "ms": round(dt * 1e3, 4)}
            ),
            flush=True,
        )
    b = np.array([r[0] for r in rows], float)
    t = np.array([r[1] for r in rows], float)
    slope, intercept = np.polyfit(b, t, 1)
    print(
        json.dumps(
            {
                "fit": "t = overhead + bytes/bw",
                "overhead_ms": round(intercept * 1e3, 3),
                "streaming_gbps": round(1e-9 / slope, 1)
                if slope > 0
                else None,
                "platform": jax.devices()[0].platform,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
