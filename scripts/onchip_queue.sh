#!/bin/bash
# Round-4 on-chip measurement queue (PERF.md "On-chip queue").
#
# Probes the axon TPU tunnel; the moment it answers, runs every queued
# benchmark SERIALLY (the tunnel is single-client — see PERF.md's
# tunnel-wedge protocol) and appends JSON lines to onchip_r4.jsonl.
# Each step runs under `timeout`; bench.py additionally self-watchdogs
# (CCSC_BENCH_TIMEOUT) with a CPU fallback we label and keep.
set -u
cd "$(dirname "$0")/.."
OUT=onchip_r4.jsonl
LOG=/tmp/onchip_queue.log

probe() {
  timeout 60 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
x = jnp.ones((128, 128)); float((x @ x).sum())
" > /dev/null 2>&1
}

note() { echo "{\"note\": \"$1\", \"at\": \"$(date +%H:%M:%S)\"}" >> "$OUT"; }

run_bench() { # label, env pairs...
  local label=$1; shift
  echo "=== $label $(date +%H:%M:%S)" >> "$LOG"
  local line
  line=$(env "$@" CCSC_BENCH_TIMEOUT=2400 timeout 5400 python bench.py 2>> "$LOG" | tail -1)
  if [ -n "$line" ]; then
    echo "{\"run\": \"$label\", \"result\": $line}" >> "$OUT"
  else
    note "$label FAILED/empty"
  fi
}

# pick the fastest real-TPU arm measured SO FAR and persist its knobs
# (read back from each record's own "knobs" field — single source of
# truth) as bench_tuned.json for future `python bench.py` runs; env
# still overrides. Requires a SUCCESSFUL baseline to compare against;
# otherwise (and when baseline wins) any stale tuned file is removed
# so defaults really are the defaults.
pick() {
  python scripts/pick_tuned.py >> "$LOG" 2>&1
}

while true; do
  if probe; then
    # rotate any previous generation's records: the arm picker must
    # only see THIS invocation's measurements
    [ -f "$OUT" ] && mv "$OUT" "$OUT.$(date +%s).old"
    note "tunnel UP - starting queue"
    # pin the defaults during the A/Bs so a pre-existing
    # bench_tuned.json can't contaminate the baseline arm. Arms run in
    # expected-win order and the picker runs AFTER EVERY arm, so even
    # a short tunnel window leaves a valid (partial) tuned config.
    run_bench baseline CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=none CCSC_BENCH_STORAGE=float32
    pick
    run_bench fftpad_pow2 CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=pow2 CCSC_BENCH_STORAGE=float32
    pick
    run_bench fftpad_pow2_bf16 CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=pow2 CCSC_BENCH_STORAGE=bfloat16
    pick
    run_bench bf16 CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=none CCSC_BENCH_STORAGE=bfloat16
    pick
    run_bench fftpad_fast CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=fast CCSC_BENCH_STORAGE=float32
    pick
    run_bench pallas CCSC_BENCH_PALLAS=1 CCSC_BENCH_FFTPAD=none CCSC_BENCH_STORAGE=float32
    pick
    echo "=== microbench $(date +%H:%M:%S)" >> "$LOG"
    timeout 3600 python scripts/fft_microbench.py >> "$OUT" 2>> "$LOG" \
      || note "fft_microbench FAILED"
    echo "=== families $(date +%H:%M:%S)" >> "$LOG"
    timeout 5400 python scripts/family_bench.py >> "$OUT" 2>> "$LOG" \
      || note "family_bench FAILED"
    run_bench profile CCSC_BENCH_PROFILE=1
    note "queue complete"
    break
  fi
  echo "$(date +%H:%M:%S) tunnel down" >> "$LOG"
  sleep 240
done
