#!/bin/bash
# Consolidated on-chip runner (round 5) — replaces the seven r4
# pollers (onchip_queue{,2..6}.sh + onchip_lastchance.sh) with ONE
# probe/lock/watchdog implementation and phases as data (VERDICT r4
# weak #6 / next-step #8).
#
# Usage: scripts/onchip_queue.sh [deadline_seconds_from_now]
#   default deadline 34200 s (9.5 h) — the runner exits unconditionally
#   at the deadline so it can never share the tunnel with the driver's
#   end-of-round bench (two concurrent clients wedge a live tunnel —
#   PERF.md protocol). The deadline is relative to start, so the
#   script is reusable (ADVICE r4: no absolute wall-clock bake-in).
#
# Phase protocol:
#   - single-client lock: flock on $LOCK (a persistent fd the kernel
#     releases when the holder dies — no stale state to clean up)
#   - probe() is the only tunnel-liveness test; phases run only after
#     a fresh successful probe
#   - completed phases AND arms are recorded in $STATE so a restarted
#     runner resumes where it left off (the tunnel died mid-run twice
#     in r4). $STATE is round-scoped; to re-measure from scratch after
#     a code fix, `rm $STATE` (and rotate $OUT) before relaunching.
#   - a phase that fails MAX_PHASE_FAILS times is given up (noted in
#     $OUT) rather than retried every poll cycle until the deadline
#   - every python invocation is double-watchdogged: CCSC_BENCH_TIMEOUT
#     (in-process subprocess watchdog) + an outer `timeout`
#   - bench_tuned.json is re-picked after EVERY measured arm, so even
#     a short tunnel window leaves a valid (partial) tuned config
set -u
cd "$(dirname "$0")/.."
OUT=onchip_r5.jsonl
LOG=/tmp/onchip_r5.log
STATE=/tmp/onchip_r5.phases
LOCK=/tmp/ccsc_tunnel.lockfile
DEADLINE=$(($(date +%s) + ${1:-34200}))
POLL=240

log() { echo "$(date +%H:%M:%S) $*" >> "$LOG"; }
note() { echo "{\"note\": \"$1\", \"at\": \"$(date +%H:%M:%S)\"}" >> "$OUT"; }

past_deadline() { [ "$(date +%s)" -ge "$DEADLINE" ]; }
time_left() { echo $((DEADLINE - $(date +%s))); }
capped() { # min(wanted_timeout, time to deadline) — a child started
  # just before the deadline must not hold the tunnel past it (the
  # driver's end-of-round bench may start then; two clients wedge it)
  local want=$1 l
  l=$(time_left)
  [ "$l" -lt "$want" ] && echo "$l" || echo "$want"
}
too_late() { [ "$(time_left)" -le 120 ]; }

# ---- single-client lock: flock on a persistent fd. The kernel
# releases it when the holder dies (any signal, incl. kill -9), so
# there is no stale-lock state and no steal race.
acquire_lock() {
  exec 9>"$LOCK"
  until flock -n 9; do
    log "tunnel lock held, waiting"
    past_deadline && exit 0
    sleep 60
  done
  echo $$ >&9
}

probe() {
  timeout 90 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
x = jnp.ones((128, 128)); float((x @ x).sum())
" > /dev/null 2>&1
}

phase_done() { grep -qx "$1" "$STATE" 2>/dev/null; }
mark_done() { echo "$1" >> "$STATE"; }
MAX_PHASE_FAILS=3
fail_count() { grep -cx "failed:$1" "$STATE" 2>/dev/null || true; }
mark_failed() { echo "failed:$1" >> "$STATE"; }
pick() { python scripts/pick_tuned.py >> "$LOG" 2>&1; }

run_bench() { # label, env pairs...
  local label=$1; shift
  too_late && return 1
  log "bench arm: $label"
  local line
  # inner watchdog (bench.py's subprocess.run) fires first so the
  # workload child is cleaned up; the outer timeout is the backstop
  # fallback disabled: a hung TPU attempt fails fast instead of
  # burning another timeout on a DEGRADED CPU record the picker
  # ignores (the outer timeout therefore only needs ONE attempt)
  line=$(env "$@" CCSC_BENCH_NO_FALLBACK=1 \
    CCSC_BENCH_TIMEOUT="$(capped 2000)" \
    timeout "$(capped 2400)" python bench.py 2>> "$LOG" | tail -1)
  if [ -n "$line" ] && echo "$line" | python -c \
      'import json,sys; json.load(sys.stdin)' > /dev/null 2>&1; then
    echo "{\"run\": \"$label\", \"result\": $line}" >> "$OUT"
    case "$line" in *DEGRADED*|*FAILED*) return 1 ;; esac
    return 0
  fi
  note "$label FAILED/empty"
  return 1
}

run_py() { # timeout_s, script args...
  local t=$1; shift
  too_late && return 1
  log "py: $*"
  timeout "$(capped "$t")" python "$@" >> "$OUT" 2>> "$LOG"
}

run_arms_file() { # one "label ENV=V ..." per line; re-picks per arm.
  # Per-arm resume state ("arm:<label>" in $STATE): a phase retried
  # after one failing arm must not re-burn tunnel time re-measuring
  # the arms that already succeeded.
  local file=$1 label envs rc=0
  [ -f "$file" ] || { log "no arms file $file"; return 0; }
  while read -r label envs; do
    [ -z "$label" ] && continue
    case "$label" in \#*) continue ;; esac
    phase_done "arm:$label" && continue
    past_deadline && return 1
    # shellcheck disable=SC2086
    if run_bench "$label" $envs; then
      mark_done "arm:$label"
      pick
    else
      rc=1
    fi
  done < "$file"
  return $rc
}

run_family_arms() { # drives family_bench; one JSON line per family
  local file=$1 label envs line got rc=0
  [ -f "$file" ] || return 0
  while read -r label envs; do
    [ -z "$label" ] && continue
    case "$label" in \#*) continue ;; esac
    phase_done "farm:$label" && continue
    past_deadline && return 1
    too_late && return 1
    log "family arm: $label"
    got=0
    # shellcheck disable=SC2086
    while read -r line; do
      if echo "$line" | python -c \
          'import json,sys; json.load(sys.stdin)' > /dev/null 2>&1; then
        echo "{\"family_arm\": \"$label\", \"result\": $line}" >> "$OUT"
        got=1
      fi
    done < <(env $envs timeout "$(capped 2400)" \
      python scripts/family_bench.py 2>> "$LOG")
    if [ "$got" -eq 0 ]; then
      note "family arm $label FAILED/empty"
      rc=1
    else
      mark_done "farm:$label"
    fi
  done < "$file"
  return $rc
}

# ---- phases ---------------------------------------------------------
phase_baseline() {
  # pin EVERY lever (incl. env-level ones a tuned pick could apply
  # via bench_tuned.json) — a baseline rerun after a pick must not
  # silently inherit tuned settings
  run_bench baseline CCSC_BENCH_FFTPAD=none CCSC_BENCH_STORAGE=float32 \
    CCSC_BENCH_DSTORAGE=float32 CCSC_BENCH_FFTIMPL=xla \
    CCSC_BENCH_PALLAS=0 CCSC_BENCH_FUSEDZ=0 \
    CCSC_BENCH_FUSEDZ_PREC=highest CCSC_HERM_INV=cholesky
}
phase_arms() { run_arms_file scripts/onchip_arms.txt; }
phase_bandwidth() { run_py 2400 scripts/bandwidth_probe.py; }
phase_accuracy() {
  run_py 2400 scripts/accuracy_probe.py || return 1
  run_py 1200 scripts/tpu_fused_parity.py
}
phase_hs() {
  run_family_arms scripts/hs_arms.txt || return 1
  run_py 2400 scripts/hs_profile.py
}
phase_profile() {
  rm -rf artifacts_prof/tuned
  run_bench profile_tuned CCSC_BENCH_PROFILE=1 CCSC_BENCH_PROFILE_REPS=2 \
    CCSC_BENCH_XPROF=artifacts_prof/tuned || return 1
  run_py 600 scripts/xprof_report.py artifacts_prof/tuned
}
phase_arms2() { run_arms_file scripts/onchip_arms2.txt; }
phase_accuracy2() {
  # re-probe after wave B adds configs (fused_z_high / matmul_high /
  # fused_z_default) so the picker's accuracy gate has records for them
  run_py 2400 scripts/accuracy_probe.py
}
phase_hs2() {
  # wave C: newton Gram-inverse arm + the extended profile (direct
  # per-method inverse timings) at the measured-winner family knobs
  run_family_arms scripts/hs_arms2.txt || return 1
  CCSC_FAMILY_FFTIMPL=matmul CCSC_FAMILY_STORAGE=bfloat16 \
    run_py 2400 scripts/hs_profile.py
}
phase_profile2() {
  # xprof of the CURRENT tuned config (the phase-6 capture predates
  # the wave-B pick: it profiled matmul_bf16-composition, not the
  # fused-kernel + schur step now shipped in bench_tuned.json)
  rm -rf artifacts_prof/tuned_r5
  run_bench profile2 CCSC_BENCH_PROFILE=1 CCSC_BENCH_PROFILE_REPS=2 \
    CCSC_BENCH_XPROF=artifacts_prof/tuned_r5 || return 1
  run_py 600 scripts/xprof_report.py artifacts_prof/tuned_r5
}
phase_banks() {
  # needs a real window — but family results are saved per family
  # (family_banks resume), so a late partial run still banks whatever
  # families it finishes; only refuse truly hopeless windows
  [ "$(time_left)" -le 1500 ] && return 1
  # Protocol iterations (max_it=20): the warm-started +20-iteration
  # CPU continuation measured WORSE held-out PSNR (30.66 vs 30.73 —
  # the objective plateaus then the bank overfits the synthetic
  # statistics), so extra iterations are evidence-rejected. The
  # measured lever is SAMPLE COUNT (-0.90 @ n=16, -0.52 @ n=32,
  # -0.13 @ n=64): train at n=80 (device-tier budget raised to
  # admit its ~9.6 GB state; chip minutes, not CPU hours).
  CCSC_STREAM_RESIDENT_GB=12 timeout "$(capped 10800)" \
    python scripts/family_banks.py --hs-n 12 --n 80 \
    --out artifacts_family >> "$LOG" 2>&1
}

# Ordered by value density under a short window (r4's only window was
# 31 minutes): the round's #1 question (the bandwidth-ceiling theory)
# right after the baseline, then the unmeasured second-wave arms.
PHASES="${CCSC_PHASES:-baseline bandwidth arms accuracy hs profile banks}"

acquire_lock
log "runner start, deadline in ${1:-34200}s, phases: $PHASES"

while true; do
  past_deadline && { log "deadline reached, exiting"; exit 0; }
  remaining=""
  for p in $PHASES; do phase_done "$p" || remaining="$remaining $p"; done
  if [ -z "$remaining" ]; then log "all phases complete"; exit 0; fi
  if probe; then
    for p in $remaining; do
      past_deadline && { log "deadline reached mid-run"; exit 0; }
      note "phase $p start"
      if "phase_$p"; then
        mark_done "$p"
        note "phase $p complete"
      elif probe; then
        # tunnel is still alive, so the failure was the phase's own —
        # count it; a deterministic failure must not retry forever
        mark_failed "$p"
        if [ "$(fail_count "$p")" -ge "$MAX_PHASE_FAILS" ]; then
          mark_done "$p"
          note "phase $p GIVEN UP after $MAX_PHASE_FAILS failures"
        else
          note "phase $p FAILED (will retry)"
        fi
      else
        # tunnel died mid-phase: not the phase's fault — back to
        # polling with per-arm state intact, no failure counted
        note "phase $p interrupted (tunnel down)"
        break
      fi
    done
  else
    log "tunnel down"
  fi
  sleep "$POLL"
done
