#!/bin/bash
# Round-4 on-chip queue, phase 2: the matmul-DFT arms (fft_impl knob,
# built after phase 1 launched) plus the repaired fft microbenchmark.
#
# Waits for phase 1 (scripts/onchip_queue.sh) to finish — the tunnel is
# single-client — then appends to the SAME onchip_r4.jsonl so the arm
# picker compares against phase 1's baseline.
set -u
cd "$(dirname "$0")/.."
OUT=onchip_r4.jsonl
LOG=/tmp/onchip_queue2.log

probe() {
  timeout 60 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform in ('tpu', 'axon')
x = jnp.ones((128, 128)); float((x @ x).sum())
" > /dev/null 2>&1
}

note() { echo "{\"note\": \"$1\", \"at\": \"$(date +%H:%M:%S)\"}" >> "$OUT"; }

run_bench() { # label, env pairs...
  local label=$1; shift
  echo "=== $label $(date +%H:%M:%S)" >> "$LOG"
  local line
  line=$(env "$@" CCSC_BENCH_TIMEOUT=2400 timeout 5400 python bench.py 2>> "$LOG" | tail -1)
  # only record stdout tails that actually parse as a JSON object —
  # a crashed bench can leave a partial line that would corrupt the
  # record and silently drop the arm from tuning
  if [ -n "$line" ] && echo "$line" | python -c \
      'import json,sys; json.load(sys.stdin)' > /dev/null 2>&1; then
    echo "{\"run\": \"$label\", \"result\": $line}" >> "$OUT"
  else
    note "$label FAILED/empty"
  fi
}

pick() {
  python scripts/pick_tuned.py >> "$LOG" 2>&1
}

# wait for phase 1 to finish (its process exits after 'queue complete')
while pgrep -f "scripts/onchip_queue.sh" | grep -qv $$ 2>/dev/null; do
  echo "$(date +%H:%M:%S) phase 1 still running" >> "$LOG"
  sleep 120
done

while true; do
  if probe; then
    note "phase 2 start (matmul-DFT arms)"
    run_bench matmul CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=none \
      CCSC_BENCH_STORAGE=float32 CCSC_BENCH_FFTIMPL=matmul
    pick
    run_bench matmul_bf16 CCSC_BENCH_PALLAS=0 CCSC_BENCH_FFTPAD=none \
      CCSC_BENCH_STORAGE=bfloat16 CCSC_BENCH_FFTIMPL=matmul
    pick
    echo "=== microbench2 $(date +%H:%M:%S)" >> "$LOG"
    timeout 3600 python scripts/fft_microbench.py >> "$OUT" 2>> "$LOG" \
      || note "fft_microbench (repaired) FAILED"
    note "phase 2 complete"
    break
  fi
  echo "$(date +%H:%M:%S) tunnel down" >> "$LOG"
  sleep 240
done
