#!/usr/bin/env python
"""Train THIS framework's own 3D / 4D / hyperspectral filter banks.

The reference ships pretrained banks for all four families (SURVEY.md
section 1 L1) but its 3D/4D/HS TRAINING data blobs are absent from the
repo (.MISSING_LARGE_BLOBS, SURVEY.md section 5 defect list), so a
full-data reproduction is impossible for anyone. What IS possible — and
what this script does — is to synthesize training sets with the real
structure each filter family exists to model, from the only images the
reference ships (2D/Inpainting/Test/*.jpg):

  3D  video clips = a window translating across a contrast-normalized
      image (true spatiotemporal structure: motion parallax of edges),
      protocol of learn_kernels_3D.m (k=49 11^3, 64 clips of 50^3,
      ni=8, rho 5000/1, max_it=20, tol=1e-2).
  4D  lightfield patches = per-view disparity shifts of a window
      (true parallax: depth-dependent view correlation), protocol of
      learn_kernels_4D.m (k=49 11x11x5x5, 64 patches 50x50 x 5x5
      views, ni=8, rho 500/50 — conv4D :105,119,159,162).
  HS  hyperspectral cubes = two-material mixtures with smooth spectral
      envelopes (low-rank spectra + spatial detail), protocol of
      learn_hyperspectral.m (k=100 11x11x31, masked learner,
      max_it=40, Gaussian smooth_init).

Each bank is evaluated against the SHIPPED reference bank of the same
family on a held-out reconstruction task (masked subsampling for 3D,
view synthesis for 4D, spectral demosaicing for HS) with identical
masks. Artifacts: bank .mat + central-slice mosaic + ARTIFACTS_<fam>.md
per family under --out.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

TEST_DIR = "/root/reference/2D/Inpainting/Test"
SHIPPED = {
    "3d": "/root/reference/3D/Filters/3D_video_filters.mat",
    "4d": "/root/reference/4D/Filters/4d_filters_lightfield.mat",
    "hs": "/root/reference/2-3D/Filters/2D-3D-Hyperspectral.mat",
}


# moved to utils.perfmodel (r7: the auto-degrade ladder in
# apps._dispatch shares the exact same pre-flight); re-exported here so
# scripts/continue_3d.py and older callers keep importing it from this
# script
from ccsc_code_iccv2017_tpu.utils.perfmodel import (  # noqa: E402
    inmem_learn_estimate,
)


def _imgs(contrast="local_cn"):
    import numpy as np

    from ccsc_code_iccv2017_tpu.data.images import load_images

    return np.asarray(
        load_images(TEST_DIR, contrast_normalize=contrast,
                    zero_mean=(contrast != "none")),
        np.float32,
    )


def synth_video(n, side, frames, seed=0):
    """[n, side, side, frames] clips (TIME LAST — the repo's canonical
    3D layout, io_mat._TO_MATLAB['3d']): window translating across an
    image along a random direction (wrapping at borders)."""
    import numpy as np

    imgs = _imgs()
    rng = np.random.default_rng(seed)
    H, W = imgs.shape[1:]
    out = np.empty((n, side, side, frames), np.float32)
    for i in range(n):
        im = imgs[rng.integers(len(imgs))]
        vy, vx = rng.uniform(-2.0, 2.0, 2)
        y0 = rng.integers(0, H - side)
        x0 = rng.integers(0, W - side)
        for t in range(frames):
            y = int(round(y0 + vy * t)) % (H - side)
            x = int(round(x0 + vx * t)) % (W - side)
            out[i, :, :, t] = im[y : y + side, x : x + side]
    return out


def synth_lightfield(n, side, views, seed=0):
    """[n, views, views, side, side] patches: view (u, v) is the
    window shifted by disparity * (u - c, v - c) — planar-scene
    parallax."""
    import numpy as np

    imgs = _imgs()
    rng = np.random.default_rng(seed)
    H, W = imgs.shape[1:]
    c = views // 2
    pad = 3 * c + 2
    out = np.empty((n, views, views, side, side), np.float32)
    for i in range(n):
        im = imgs[rng.integers(len(imgs))]
        disp = rng.uniform(-1.5, 1.5)
        y0 = rng.integers(pad, H - side - pad)
        x0 = rng.integers(pad, W - side - pad)
        for u in range(views):
            for v in range(views):
                dy = int(round(disp * (u - c)))
                dx = int(round(disp * (v - c)))
                out[i, u, v] = im[
                    y0 + dy : y0 + dy + side, x0 + dx : x0 + dx + side
                ]
    return out


def synth_hyperspectral(n, side, bands, seed=0):
    """[n, bands, side, side] cubes: two-material mixture with smooth
    per-material spectral envelopes (the low-rank-spectra structure
    hyperspectral filters model) plus band-correlated detail."""
    import numpy as np

    imgs = _imgs(contrast="none")
    rng = np.random.default_rng(seed)
    H, W = imgs.shape[1:]
    lam = np.linspace(0.0, 1.0, bands)
    out = np.empty((n, bands, side, side), np.float32)
    for i in range(n):
        im = imgs[rng.integers(len(imgs))]
        y0 = rng.integers(0, H - side)
        x0 = rng.integers(0, W - side)
        patch = im[y0 : y0 + side, x0 : x0 + side]
        gy, gx = np.gradient(patch)
        grad = np.sqrt(gy * gy + gx * gx)
        grad = grad / max(float(grad.max()), 1e-6)
        # three materials: bright regions, dark regions, edges — each
        # with its own smooth spectral envelope (rank-3 spectra with
        # spatially coherent abundances)
        mats = (patch, 1.0 - patch, grad)

        def env():
            c = rng.uniform(0.1, 0.9)
            w = rng.uniform(0.1, 0.5)
            a = rng.uniform(0.4, 1.0)
            return a * np.exp(-((lam - c) ** 2) / (2 * w * w))

        cube = np.zeros((bands, side, side), np.float32)
        for m in mats:
            cube += m[None] * env()[:, None, None]
        out[i] = cube
    return out


def heldout_psnr_3d(d, side, eval_max_it=80):
    """Held-out 3D evaluation — THE protocol both the bank trainer and
    the continuation tool (scripts/continue_3d.py) score against: 50%%
    random masked subsampling on 4 seed-99 synth clips, reconstruction
    PSNR over the full volume. One definition so the two comparisons
    cannot desynchronize. ``d``: [k, s, s, s] filter bank."""
    import numpy as np

    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.config import ProblemGeom, SolveConfig
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem, reconstruct,
    )

    d = np.asarray(d)
    geom = ProblemGeom(d.shape[1:], d.shape[0])
    test = synth_video(4, side, side, seed=99)
    rng = np.random.default_rng(5)
    mask = (rng.uniform(size=test.shape) > 0.5).astype(np.float32)
    scfg = SolveConfig(
        lambda_residual=100.0, lambda_prior=0.5,
        max_it=eval_max_it, tol=1e-5, verbose="none",
    )
    r = reconstruct(
        jnp.asarray(test * mask), jnp.asarray(d),
        ReconstructionProblem(geom), scfg, mask=jnp.asarray(mask),
    )
    rec = np.asarray(r.recon)
    mse = np.mean((rec - test) ** 2)
    span = float(test.max() - test.min()) or 1.0
    return 10 * np.log10(span**2 / mse)


def central_slice(d, fam):
    """[k, ...] -> [k, s, s] 2D view for the mosaic."""
    if fam == "3d":
        return d[:, :, :, d.shape[-1] // 2]
    if fam == "4d":
        return d[:, d.shape[1] // 2, d.shape[2] // 2]
    return d[:, d.shape[1] // 2]  # hs: central band


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--families", default="3d,4d,hs",
        help="comma list of 3d, 4d, hs",
    )
    ap.add_argument("--n", type=int, default=64, help="3d/4d samples")
    ap.add_argument("--hs-n", type=int, default=16)
    ap.add_argument("--side", type=int, default=50)
    ap.add_argument("--hs-side", type=int, default=96)
    ap.add_argument("--max-it", type=int, default=20)
    ap.add_argument("--hs-max-it", type=int, default=40)
    ap.add_argument("--eval-max-it", type=int, default=80)
    ap.add_argument("--out", default="artifacts_family")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes (CI/CPU check)")
    args = ap.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.config import (
        LearnConfig, ProblemGeom, SolveConfig,
    )
    from ccsc_code_iccv2017_tpu.models.learn import learn
    from ccsc_code_iccv2017_tpu.models.learn_masked import learn_masked
    from ccsc_code_iccv2017_tpu.models.reconstruct import (
        ReconstructionProblem, reconstruct,
    )
    from ccsc_code_iccv2017_tpu.utils import display
    from ccsc_code_iccv2017_tpu.utils.io_mat import save_filters

    os.makedirs(args.out, exist_ok=True)
    plat = jax.devices()[0].platform
    print("platform:", plat, flush=True)

    def _learn_memory_bounded(b, geom, cfg):
        """In-memory consensus learn, falling back to the block-
        sequential streaming learner (same math — parallel/streaming.py)
        when the all-blocks-resident path exceeds HBM. The r5
        full-scale 3D train OOMed the 16G v5e; a pre-flight estimate
        of the in-memory learner's full-batch spectra temps skips the
        doomed ~5-minute compile-then-OOM attempt outright."""
        import numpy as np

        from ccsc_code_iccv2017_tpu.parallel.streaming import (
            learn_streaming,
        )

        est, budget = inmem_learn_estimate(b.shape, geom, cfg)
        if plat in ("tpu", "axon") and est > budget:
            print(f"in-memory learn pre-flight: ~{est/1e9:.1f} GB "
                  f"full-batch temps > {budget/1e9:.0f} GB budget; going "
                  "straight to the streaming learner", flush=True)
            return learn_streaming(
                np.asarray(b, np.float32), geom, cfg,
                key=jax.random.PRNGKey(0),
            )

        try:
            return learn(jnp.asarray(b), geom, cfg,
                         key=jax.random.PRNGKey(0))
        except Exception as e:
            if "memory" not in str(e).lower():
                raise
            print(f"in-memory learn OOM ({type(e).__name__}); "
                  "retrying with the host-streaming learner", flush=True)
        # run the retry OUTSIDE the except block: the caught
        # exception's traceback frames pin the failed attempt's device
        # buffers, and the streaming run needs that HBM back
        return learn_streaming(
            np.asarray(b, np.float32), geom, cfg,
            key=jax.random.PRNGKey(0),
        )

    if args.smoke:
        args.n, args.hs_n = 16, 4
        args.side, args.hs_side = 20, 24
        args.max_it = args.hs_max_it = 2
        args.eval_max_it = 5

    fams = [f.strip() for f in args.families.split(",") if f.strip()]
    results = {}

    # Execution-strategy knobs per family (used both for training and
    # for the resume fingerprint below). 3D on TPU: the measured-
    # accurate tuned strategy (PERF.md); HS per the r5 family A/B.
    on_tpu = plat in ("tpu", "axon")
    knobs_3d = (
        dict(fft_impl="matmul", storage_dtype="bfloat16",
             d_storage_dtype="bfloat16")
        if on_tpu else {}
    )
    hs_knobs = (
        dict(fft_impl="matmul", storage_dtype="bfloat16",
             carry_freq=False)
        if on_tpu else dict(carry_freq=True)
    )

    def _run_params(fam):
        """Fingerprint of every input that shapes a family's result.
        Stored inside result_<fam>.json; resume only skips the family
        on an EXACT match, so a rerun with different --n/--side/
        --max-it or knob picks cannot silently report stale results as
        current (ADVICE r5)."""
        base = dict(eval_max_it=args.eval_max_it)
        if fam == "3d":
            return dict(n=args.n, side=args.side, max_it=args.max_it,
                        knobs=knobs_3d, **base)
        if fam == "4d":
            return dict(n=args.n, side=args.side, max_it=args.max_it,
                        knobs={}, **base)
        return dict(n=args.hs_n, side=args.hs_side,
                    max_it=args.hs_max_it, knobs=hs_knobs, **base)

    # Per-family resume: each completed family writes result_<fam>.json
    # next to its bank; a rerun after an interruption (the tunnel died
    # 27 min into the r5 banks phase) skips families whose result file
    # already exists — and whose embedded run parameters exactly match
    # this invocation — instead of re-burning hours of chip time.
    def _result_path(fam):
        return os.path.join(args.out, f"result_{fam}.json")

    def _record(fam):
        results[fam]["platform"] = plat
        results[fam]["params"] = _run_params(fam)
        if not args.smoke:
            # atomic: a kill mid-write must not leave a truncated file
            # that poisons every later resume (the motivating failure
            # was exactly a mid-run death); smoke runs never write —
            # tiny-shape smoke results must not be resumable as real
            tmp = _result_path(fam) + ".tmp"
            with open(tmp, "w") as f:
                json.dump(results[fam], f)
            os.replace(tmp, _result_path(fam))
        print(json.dumps({"family": fam, **results[fam]}), flush=True)

    if not args.smoke:
        for fam in list(fams):
            if os.path.exists(_result_path(fam)):
                try:
                    with open(_result_path(fam)) as f:
                        stored = json.load(f)
                except ValueError:
                    continue  # truncated/corrupt: re-run the family
                if stored.get("params") != _run_params(fam):
                    # different flags (or a pre-fingerprint file):
                    # the stored result answers a different question
                    print(
                        f"resume: {fam} result exists but was produced "
                        f"with params {stored.get('params')} != current "
                        f"{_run_params(fam)}; re-running", flush=True,
                    )
                    continue
                results[fam] = stored
                print(f"resume: {fam} already complete, skipping",
                      flush=True)
                fams.remove(fam)

    def load_shipped(fam, key):
        from ccsc_code_iccv2017_tpu.utils import io_mat

        loaders = {
            "3d": io_mat.load_filters_3d,
            "4d": io_mat.load_filters_lightfield,
            "hs": io_mat.load_filters_hyperspectral,
        }
        try:
            return loaders[fam](SHIPPED[fam])
        except Exception as e:  # pragma: no cover
            print(f"shipped {fam} bank unavailable: {e}")
            return None

    # ---------------- 3D video --------------------------------------
    if "3d" in fams:
        fam = "3d"
        support = 11 if not args.smoke else 5
        k = 49 if not args.smoke else 6
        b = synth_video(args.n, args.side, args.side)
        geom = ProblemGeom((support,) * 3, k)
        # On TPU: the measured-accurate tuned strategy (PERF.md) — the
        # matmul-DFT also sidesteps the XLA-FFT's padded
        # f32[..,60,60,60] temps that OOMed the full-scale (n=64) 3D
        # train on the 16G chip, and bf16 state halves the rest. On
        # CPU (tunnel-outage fallback) keep pocketfft/f32: the DFT
        # matmuls are an MXU trade, not a host-CPU one.
        knobs = knobs_3d
        cfg = LearnConfig(
            max_it=args.max_it, tol=1e-2, rho_d=5000.0, rho_z=1.0,
            num_blocks=8 if not args.smoke else 2,
            verbose="brief", track_objective=True, **knobs,
        )
        t0 = time.time()
        res = _learn_memory_bounded(b, geom, cfg)
        t = time.time() - t0
        save_filters(
            os.path.join(args.out, "bank_3d.mat"), res.d, res.trace,
            layout="3d",
        )
        display.save_filter_mosaic(
            os.path.join(args.out, "mosaic_3d.png"),
            central_slice(np.asarray(res.d), fam),
            title=f"3D bank, central temporal slice ({args.max_it} it)",
        )
        # eval: heldout_psnr_3d — the shared protocol (also scored by
        # scripts/continue_3d.py; one definition, no drift)
        own = heldout_psnr_3d(np.asarray(res.d), args.side,
                              args.eval_max_it)
        shipped_d = None if args.smoke else load_shipped(fam, "d")
        ship = (
            heldout_psnr_3d(shipped_d, args.side, args.eval_max_it)
            if shipped_d is not None else float("nan")
        )
        results[fam] = dict(t_learn_s=round(float(t), 1),
                            own_psnr=round(float(own), 2),
                            shipped_psnr=round(float(ship), 2),
                            obj=float(res.trace["obj_vals_z"][-1]))
        _record(fam)

    # ---------------- 4D lightfield ---------------------------------
    if "4d" in fams:
        fam = "4d"
        support = 11 if not args.smoke else 5
        k = 49 if not args.smoke else 6
        views = 5 if not args.smoke else 3
        b = synth_lightfield(args.n, args.side, views)
        geom = ProblemGeom((support, support), k, (views, views))
        cfg = LearnConfig(
            max_it=args.max_it, tol=1e-3, rho_d=500.0, rho_z=50.0,
            num_blocks=8 if not args.smoke else 2,
            verbose="brief", track_objective=True,
        )
        t0 = time.time()
        res = learn(jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0))
        t = time.time() - t0
        save_filters(
            os.path.join(args.out, "bank_4d.mat"), res.d, res.trace,
            layout="lightfield",
        )
        display.save_filter_mosaic(
            os.path.join(args.out, "mosaic_4d.png"),
            central_slice(np.asarray(res.d), fam),
            title=f"4D bank, central view ({args.max_it} it)",
        )
        # eval: view synthesis — mask out everything except the border
        # views (reconstruct_subsampling_lightfield.m:29-34 intent)
        test = synth_lightfield(4, args.side, views, seed=77)
        mask = np.zeros_like(test)
        mask[:, 0, :], mask[:, -1, :] = 1.0, 1.0
        mask[:, :, 0], mask[:, :, -1] = 1.0, 1.0
        prob = ReconstructionProblem(geom, pad=False)
        scfg = SolveConfig(
            lambda_residual=10000.0, lambda_prior=1.0,
            max_it=args.eval_max_it, tol=1e-5, verbose="none",
        )

        def psnr4(d):
            r = reconstruct(
                jnp.asarray(test * mask), jnp.asarray(d), prob, scfg,
                mask=jnp.asarray(mask),
            )
            rec = np.asarray(r.recon)
            hidden = mask == 0.0
            mse = np.mean((rec[hidden] - test[hidden]) ** 2)
            span = float(test.max() - test.min()) or 1.0
            return 10 * np.log10(span**2 / mse)

        own = psnr4(np.asarray(res.d))
        shipped_d = None if args.smoke else load_shipped(fam, "d")
        ship = psnr4(shipped_d) if shipped_d is not None else float("nan")
        results[fam] = dict(t_learn_s=round(float(t), 1),
                            own_psnr=round(float(own), 2),
                            shipped_psnr=round(float(ship), 2),
                            obj=float(res.trace["obj_vals_z"][-1]))
        _record(fam)

    # ---------------- hyperspectral ---------------------------------
    if "hs" in fams:
        fam = "hs"
        support = 11 if not args.smoke else 5
        k = 100 if not args.smoke else 6
        bands = 31 if not args.smoke else 5
        b = synth_hyperspectral(args.hs_n, args.hs_side, bands)
        geom = ProblemGeom((support, support), k, (bands,))
        # Gaussian smooth_init (learn_hyperspectral.m:16-17)
        from scipy.ndimage import gaussian_filter

        sm = gaussian_filter(b, sigma=(0, 0, 4.0, 4.0)).astype(np.float32)
        # Execution strategy per platform, from the r5 family A/B
        # (onchip_r5.jsonl): on chip matmul-DFT + bf16 state WITHOUT
        # carry wins (0.260 vs 0.201 baseline; carry LOSES on chip,
        # 0.237); on CPU carry wins 1.25x and pocketfft/f32 stays.
        # Bank quality is judged by held-out PSNR either way.
        # (hs_knobs hoisted above — shared with the resume fingerprint)
        cfg = LearnConfig(
            max_it=args.hs_max_it, tol=1e-3, verbose="brief",
            track_objective=True, **hs_knobs,
        )
        t0 = time.time()
        res = learn_masked(
            jnp.asarray(b), geom, cfg, key=jax.random.PRNGKey(0),
            smooth_init=jnp.asarray(sm),
        )
        t = time.time() - t0
        save_filters(
            os.path.join(args.out, "bank_hs.mat"), res.d, res.trace,
            layout="hyperspectral",
        )
        display.save_filter_mosaic(
            os.path.join(args.out, "mosaic_hs.png"),
            central_slice(np.asarray(res.d), fam),
            title=f"HS bank, central band ({args.hs_max_it} it)",
        )
        # eval: spectral demosaicing — each pixel observes one band
        test = synth_hyperspectral(2, args.hs_side, bands, seed=55)
        rng = np.random.default_rng(3)
        wl = rng.integers(0, bands, size=test.shape[-2:])
        mask = np.zeros_like(test)
        for w in range(bands):
            mask[:, w][:, wl == w] = 1.0
        # normalized convolution: gaussian(x*mask) / gaussian(mask) —
        # a NaN-masked filter would propagate NaN everywhere at 1/31
        # observed pixels per band
        num = gaussian_filter(test * mask, sigma=(0, 0, 3, 3))
        den = gaussian_filter(mask, sigma=(0, 0, 3, 3))
        smt = (num / np.maximum(den, 1e-6)).astype(np.float32)
        prob = ReconstructionProblem(geom, pad=False)
        scfg = SolveConfig(
            lambda_residual=100000.0, lambda_prior=1.0,
            max_it=args.eval_max_it, tol=1e-5, verbose="none",
        )

        def psnrh(d):
            r = reconstruct(
                jnp.asarray(test * mask), jnp.asarray(d), prob, scfg,
                mask=jnp.asarray(mask),
                smooth_init=jnp.asarray(smt.astype(np.float32)),
            )
            rec = np.asarray(r.recon)
            hidden = mask == 0.0
            mse = np.mean((rec[hidden] - test[hidden]) ** 2)
            span = float(test.max() - test.min()) or 1.0
            return 10 * np.log10(span**2 / mse)

        own = psnrh(np.asarray(res.d))
        shipped_d = None if args.smoke else load_shipped(fam, "d")
        ship = psnrh(shipped_d) if shipped_d is not None else float("nan")
        results[fam] = dict(t_learn_s=round(float(t), 1),
                            own_psnr=round(float(own), 2),
                            shipped_psnr=round(float(ship), 2),
                            obj=float(res.trace["obj_vals_z"][-1]))
        _record(fam)

    # ---------------- summary ---------------------------------------
    lines = [
        "# ARTIFACTS — self-trained 3D / 4D / hyperspectral banks",
        "",
        "The reference's own 3D/4D/HS training blobs are absent from "
        "its repo (SURVEY.md section 5), so these banks are trained on "
        "SYNTHESIZED data carrying each family's real structure "
        "(motion for 3D, parallax for 4D, low-rank spectra for HS) "
        "derived from the 10 shipped Test images — provenance is in "
        "scripts/family_banks.py. Evaluation: held-out reconstruction "
        "with identical masks, own bank vs the shipped reference bank.",
        "",
        "| family | learn time (s) | platform | own-bank PSNR | "
        "shipped-bank PSNR | final objective |",
        "|---|---|---|---|---|---|",
    ]
    for fam, r in results.items():
        lines.append(
            f"| {fam} | {r['t_learn_s']} | {r.get('platform', plat)} | "
            f"{r['own_psnr']} | {r['shipped_psnr']} | {r['obj']:.6g} |"
        )
    with open(os.path.join(args.out, "ARTIFACTS_FAMILY.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    print(json.dumps({"families": results}))


if __name__ == "__main__":
    main()
