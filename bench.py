#!/usr/bin/env python
"""Benchmark: consensus ADMM dictionary-learning throughput on TPU.

The BASELINE.json north-star is the 2D learning workload of
2D/learn_kernels_2D_large.m (100 filters of 11x11, consensus blocks,
20 outer iterations) with target "<5 min end-to-end on a v5e-8".
This benchmark runs the same outer-step shape on ONE chip and reports
outer iterations/sec; vs_baseline is measured pace divided by the
north-star pace (20 iters / 300 s), i.e. > 1.0 beats the target pace.

Prints exactly one JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Robustness: the measured workload runs in a SUBPROCESS with a watchdog
(the axon TPU tunnel can wedge and hang a client indefinitely; a hung
bench would record nothing for the round). If the TPU attempt times
out or dies, the bench reruns on CPU and says so in the metric name —
a degraded-but-present number beats a hang.

Env knobs: CCSC_BENCH_N (images, default 128), CCSC_BENCH_SIZE (image
side, default 100), CCSC_BENCH_K (filters, default 100),
CCSC_BENCH_BLOCKS (default 8), CCSC_BENCH_ITERS (timed outer
iterations, default 3), CCSC_BENCH_TIMEOUT (seconds per attempt,
default 900), CCSC_BENCH_INPROCESS=1 (skip the watchdog wrapper),
CCSC_BENCH_PALLAS=1 (route the z-solve through the fused Pallas
kernel — for on-chip A/B against the default einsum path),
CCSC_BENCH_CARRY=1 (LearnConfig.carry_freq — recorded in the knob
dict; a masked-family lever, no-op for this consensus workload),
CCSC_BENCH_SERVE=1 (run the SERVING arm instead: serve.CodecEngine
vs the per-request driver loop, scripts/serve_bench.py — knobs
CCSC_SERVE_*, record via emit_serve).
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def run_workload():
    """The measured workload. Runs in-process; called in the child."""
    from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    # CCSC_BENCH_SERVE=1: the serving arm — CodecEngine (per-bank
    # plans + shape-bucketed AOT programs + micro-batching) vs the
    # one-reconstruct()-per-request driver loop, emitted in the same
    # record format (scripts/serve_bench.py is the standalone CLI)
    if os.environ.get("CCSC_BENCH_SERVE") == "1":
        from ccsc_code_iccv2017_tpu.serve.bench import run_serve_workload

        return run_serve_workload()

    import jax
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
    from ccsc_code_iccv2017_tpu.parallel import consensus
    from ccsc_code_iccv2017_tpu.utils import memwatch, obs as obs_mod

    # measured HBM watermark (utils.memwatch, sampled at the fences
    # below) and compile count for the record + the perf ledger —
    # installed before the first trace so warmup compiles count too
    mw = memwatch.MemWatch()
    cmon = obs_mod.CompileMonitor().install()

    n = int(os.environ.get("CCSC_BENCH_N", 128))
    size = int(os.environ.get("CCSC_BENCH_SIZE", 100))
    k = int(os.environ.get("CCSC_BENCH_K", 100))
    blocks = int(os.environ.get("CCSC_BENCH_BLOCKS", 8))
    iters = int(os.environ.get("CCSC_BENCH_ITERS", 3))

    # The tuned-knob store (tune.store, written by scripts/pick_tuned
    # after the on-chip A/Bs and by scripts/autotune.py sweeps)
    # carries the winning knob settings keyed by (chip, shape bucket);
    # explicit env vars always override. Same problem, same math
    # (equality-tested knobs) — only the execution strategy changes.
    # TPU-only: the knobs were picked on chip, and applying e.g.
    # bf16/fused arms to the CPU-degrade fallback would defeat the
    # "degraded-but-present number beats a hang" design. The legacy
    # bench_tuned.json is a read-compat migration shim consulted only
    # when the store holds nothing for the key on ANY chip; a store
    # with entries for a DIFFERENT chip refuses (cross-chip knobs are
    # exactly the hazard the store closes).
    tuned = {}
    if jax.default_backend() in ("tpu", "axon"):
        from ccsc_code_iccv2017_tpu.tune import store as tune_store
        from ccsc_code_iccv2017_tpu.utils import perfmodel as _pm

        tuned, tuned_src = tune_store.bench_lookup(
            _pm.detect_chip(), k=k, support=(11, 11), n=n,
            size=(size, size), blocks=blocks, repo=REPO,
        )
        if tuned_src.startswith("refused"):
            print(f"bench: tuned store {tuned_src}", file=sys.stderr)
    use_pallas = os.environ.get(
        "CCSC_BENCH_PALLAS", "1" if tuned.get("use_pallas") else "0"
    ) == "1"
    fft_pad = os.environ.get(
        "CCSC_BENCH_FFTPAD", tuned.get("fft_pad", "none")
    )
    storage = os.environ.get(
        "CCSC_BENCH_STORAGE", tuned.get("storage_dtype", "float32")
    )
    fft_impl = os.environ.get(
        "CCSC_BENCH_FFTIMPL", tuned.get("fft_impl", "xla")
    )
    fused_z = os.environ.get(
        "CCSC_BENCH_FUSEDZ", "1" if tuned.get("fused_z") else "0"
    ) == "1"
    d_storage = os.environ.get(
        "CCSC_BENCH_DSTORAGE", tuned.get("d_storage_dtype", "float32")
    )
    fused_prec = os.environ.get(
        "CCSC_BENCH_FUSEDZ_PREC", tuned.get("fused_z_precision", "highest")
    )
    # chunked/donated outer driver (LearnConfig.outer_chunk /
    # donate_state): >1 runs that many outer iterations per dispatch
    # with one readback; donation aliases the state buffers in place
    outer_chunk = int(
        os.environ.get("CCSC_BENCH_CHUNK", tuned.get("outer_chunk", 1))
    )
    donate = os.environ.get(
        "CCSC_BENCH_DONATE", "1" if tuned.get("donate_state") else "0"
    ) == "1"
    # carry_freq (LearnConfig) is the MASKED-family lever (PERF.md r5:
    # 1.25x CPU on the HS step; the consensus learner has no redundant
    # re-transform to skip, so it is a no-op for THIS workload) — the
    # knob still rides the config + record so masked-family arms driven
    # through the same env vocabulary are reproducible from the record
    carry = os.environ.get(
        "CCSC_BENCH_CARRY", "1" if tuned.get("carry_freq") else "0"
    ) == "1"
    # the Gram-inverse implementation is an env-level switch (same math
    # to float rounding, freq_solvers.hermitian_inverse) — apply the
    # tuned pick unless the caller overrides; with neither, leave the
    # env unset so the library's platform/size-aware default fires
    if "herm_inv" in tuned:
        os.environ.setdefault("CCSC_HERM_INV", tuned["herm_inv"])
    # record the method that will actually execute, not the literal
    # 'auto' — the jsonl knob records are authoritative for what ran
    # (the north-star's one Gram is the d-pass [F, Ni, Ni], Ni = n/blocks)
    from ccsc_code_iccv2017_tpu.ops.freq_solvers import resolve_herm_method

    herm_inv = resolve_herm_method(n // blocks)
    geom = ProblemGeom((11, 11), k)
    cfg = LearnConfig(
        max_it=iters,
        max_it_d=5,
        max_it_z=10,
        num_blocks=blocks,
        rho_d=5000.0,
        rho_z=1.0,
        # tol=0 so the chunked scan's in-jit early-stop can never fire
        # mid-bench (the per-step bench loop never checked tol either)
        tol=0.0,
        verbose="none",
        use_pallas=use_pallas,
        fft_pad=fft_pad,
        storage_dtype=storage,
        d_storage_dtype=d_storage,
        fft_impl=fft_impl,
        fused_z=fused_z,
        fused_z_precision=fused_prec,
        outer_chunk=outer_chunk,
        donate_state=donate,
        carry_freq=carry,
    )
    fg = common.FreqGeom.create(
        geom, (size, size), fft_pad=fft_pad, fft_impl=fft_impl
    )

    key = jax.random.PRNGKey(0)
    ni = n // blocks
    # synthetic data on device — the benchmark measures the solver, not IO
    b_blocks = jax.random.normal(
        jax.random.PRNGKey(1), (blocks, ni, size, size), jnp.float32
    )
    state = learn_mod.init_state(
        key, geom, fg, blocks, ni, z_dtype=jnp.dtype(storage),
        d_dtype=jnp.dtype(d_storage),
    )

    chunked = cfg.chunked_driver
    if chunked:
        # chunked arm: one dispatch per outer_chunk iterations; with
        # donate the state buffers alias in place call-to-call. The
        # warmup consumes `state` (donated) — keep a copy only if the
        # component profile will need it afterwards.
        if donate and os.environ.get("CCSC_BENCH_PROFILE") == "1":
            state_profile = jax.tree.map(jnp.copy, state)
        else:
            state_profile = state
        step = consensus.make_outer_chunk_step(
            geom, cfg, fg, outer_chunk, mesh=None, donate=donate
        )
        fence = lambda out: float(out.metrics.d_diff[-1])
    else:
        state_profile = state
        step = consensus.make_outer_step(geom, cfg, fg, mesh=None)
        fence = lambda out: float(out.d_diff)

    # ONE AOT compile, reused for warmup, timing, and cost analysis
    # (a second .lower().compile() would recompile from scratch —
    # slow, and one more chance for the axon tunnel to wedge).
    try:
        compiled = step.lower(state, b_blocks).compile()
    except Exception:
        compiled = step  # backends without full AOT support

    mw.sample()  # post-AOT-compile allocator state
    # warmup. NB: jax.block_until_ready is a no-op on the axon TPU
    # platform — a scalar readback is the only reliable fence.
    s1, m0 = compiled(state, b_blocks)
    fence(m0)  # real scalar computed from the chain, not the
    # constant-0 objective (verbose='none' skips the objective)
    mw.sample()  # post-warmup fence: state + metrics resident

    calls = max(1, iters // outer_chunk) if chunked else iters
    eff_iters = calls * outer_chunk if chunked else iters
    t0 = time.perf_counter()
    cur = s1
    for _ in range(calls):
        cur, m = compiled(cur, b_blocks)
    fence(m)  # fences the whole chain
    dt = time.perf_counter() - t0
    mw.sample()  # post-timed-loop fence

    # optional xprof capture (CCSC_BENCH_XPROF=<dir>) of two EXTRA
    # steps AFTER the timed loop — tracing costs real time, and a
    # traced rate would land in onchip_r*.jsonl as if it were the
    # chip's true rate. scripts/xprof_report.py attributes the trace.
    xprof_dir = os.environ.get("CCSC_BENCH_XPROF") or None
    if xprof_dir:
        from ccsc_code_iccv2017_tpu.utils.profiling import xla_trace

        with xla_trace(xprof_dir):
            for _ in range(2):
                cur, m = compiled(cur, b_blocks)
            fence(m)
    ips = eff_iters / dt

    # ---- utilization: XLA's cost model, analytic fallback ----------
    from ccsc_code_iccv2017_tpu.utils import perfmodel

    # with the fused z kernel, XLA's cost analysis sees the pallas
    # custom_call as opaque (undercounts) — the analytic model with
    # the fused traffic is the honest source then
    cost = (
        perfmodel.compiled_cost(compiled)
        if compiled is not step and not fused_z
        else None
    )
    cost_src = "xla_cost_analysis"
    if cost is not None and chunked:
        # the compiled executable is a CHUNK of outer_chunk steps;
        # utilization() wants per-step cost
        cost = {kk: v / outer_chunk for kk, v in cost.items()}
    if cost is None:
        cost = perfmodel.analytic_outer_step_cost(
            num_blocks=blocks,
            ni=n // blocks,
            k=k,
            spatial=fg.spatial_shape,
            num_freq=fg.num_freq,
            max_it_d=cfg.max_it_d,
            max_it_z=cfg.max_it_z,
            state_dtype_bytes=2 if storage == "bfloat16" else 4,
            d_state_dtype_bytes=2 if d_storage == "bfloat16" else 4,
            fft_impl=fft_impl,
            fused_z=fused_z,
            donate_state=donate,
        )
        cost_src = "analytic_fused_z" if fused_z else "analytic"
    util = perfmodel.utilization(cost, ips)
    util["cost_source"] = cost_src

    platform = jax.devices()[0].platform
    # measured vs modeled HBM watermark: the modeled estimate is the
    # same preflight the auto-degrade ladder trusts
    # (perfmodel.inmem_learn_estimate) — recording both per round is
    # what keeps the model honest
    modeled_hbm = None
    try:
        est, _budget = perfmodel.inmem_learn_estimate(
            (n, size, size), geom, cfg
        )
        modeled_hbm = int(est)
    except Exception:
        pass
    n_compiles = cmon.summary()["n_compiles"]
    cmon.uninstall()
    # optional telemetry stream for the bench itself
    # (CCSC_BENCH_METRICS_DIR): run metadata + the measured numbers as
    # a summary record; the emitted jsonl record points at it via
    # event_stream so PERF.md numbers are traceable to raw telemetry
    event_stream = None
    metrics_dir = os.environ.get("CCSC_BENCH_METRICS_DIR") or None
    if metrics_dir:
        from ccsc_code_iccv2017_tpu.utils import obs

        brun = obs.start_run(
            metrics_dir, algorithm="bench", verbose="none", cfg=cfg,
            geom=geom, workload="2d_consensus_outer_step",
        )
        # the bench's own sampler carries the fence watermarks; its
        # close() then emits the mem_watermark record into the stream
        brun.memwatch = mw if mw.enabled else None
        brun.modeled_hbm_bytes = modeled_hbm
        brun.chunk(0, eff_iters, eff_iters, dt, cost=cost)
        brun.close(
            status="ok", iters_per_sec=round(ips, 4), n=n, size=size,
            k=k, blocks=blocks, platform=platform,
        )
        event_stream = brun.writer.path
    out = {
        "iters_per_sec": ips,
        "event_stream": event_stream,
        "n": n,
        "size": size,
        "k": k,
        "blocks": blocks,
        "platform": platform,
        "peak_hbm_bytes": mw.peak_bytes,
        "modeled_hbm_bytes": modeled_hbm,
        "n_compiles": n_compiles,
        "util": util,
        "knobs": {
            "fft_pad": fft_pad,
            "storage_dtype": storage,
            "d_storage_dtype": d_storage,
            "use_pallas": use_pallas,
            "fft_impl": fft_impl,
            "fused_z": fused_z,
            "fused_z_precision": fused_prec,
            "herm_inv": herm_inv,
            "outer_chunk": outer_chunk,
            "donate_state": donate,
            "carry_freq": carry,
        },
    }
    if os.environ.get("CCSC_BENCH_PROFILE") == "1":
        out["components"] = profile_components(
            geom, cfg, fg, state_profile, b_blocks
        )
    return out


def profile_components(geom, cfg, fg, state, b_blocks, reps=None):
    """Wall-clock split of the outer step's stages (the FFT vs Gram vs
    solve mix VERDICT asks for): each stage jitted separately, fenced
    by a real-scalar readback, timed over ``reps`` runs. Overlap/fusion
    across stages is lost, so the parts can sum to more than the fused
    step — the table is for MIX, not absolute totals."""
    if reps is None:
        # fewer reps = fewer tunnel round-trips = fewer chances for the
        # axon client to wedge mid-profile (it did, twice, in r4)
        reps = int(os.environ.get("CCSC_BENCH_PROFILE_REPS", 3))
    import jax
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.models import common
    from ccsc_code_iccv2017_tpu.ops import fourier, freq_solvers, proxes

    radius = geom.psf_radius
    b_pad = fourier.pad_spatial(b_blocks, radius, target=fg.spatial_shape)
    # ALL stage inputs are produced inside jit — eager complex ops
    # fail on the axon platform
    bhat = jax.jit(
        jax.vmap(lambda bp: common.data_to_freq(bp, fg))
    )(b_pad)

    f_zhat = jax.jit(
        lambda z: jax.vmap(lambda zl: common.codes_to_freq(zl, fg))(z)
    )
    zhat = f_zhat(state.z)
    f_kern = jax.jit(
        jax.vmap(lambda zh: freq_solvers.precompute_d_kernel(zh, cfg.rho_d))
    )
    kern = f_kern(zhat)
    xi_hat = jax.jit(
        jax.vmap(lambda x: common.full_filters_to_freq(x, fg))
    )(state.d_local)
    f_solve_d = jax.jit(
        jax.vmap(
            lambda kn, bh, xh: freq_solvers.solve_d(kn, bh, xh, cfg.rho_d)
        )
    )
    dhat_z = jax.jit(
        lambda d: common.full_filters_to_freq(d, fg)
    )(state.dbar)
    zkern = jax.jit(
        lambda dh: freq_solvers.precompute_z_kernel(dh, cfg.rho_z)
    )(dhat_z)
    # zkern must be an ARGUMENT, not a closure: a device array closed
    # over by a jitted fn is embedded as a constant, which requires a
    # host readback the axon platform cannot do (UNIMPLEMENTED)
    f_solve_z = jax.jit(
        lambda zk, bh, xh: jax.vmap(
            lambda bh1, xh1: freq_solvers.solve_z(
                zk, bh1, xh1, cfg.rho_z, use_pallas=cfg.use_pallas
            )
        )(bh, xh)
    )
    f_izhat = jax.jit(
        lambda zh: jax.vmap(lambda z1: common.codes_from_freq(z1, fg))(zh)
    )
    f_prox = jax.jit(
        lambda z: proxes.soft_threshold(z, cfg.lambda_prior / cfg.rho_z)
    )

    stages = {
        "codes_rfft": (f_zhat, (state.z,), lambda o: o.real.sum()),
        "gram_cholesky": (f_kern, (zhat,), lambda o: o.ginv.real.sum()),
        "solve_d": (
            f_solve_d,
            (kern, bhat, xi_hat),
            lambda o: o.real.sum(),
        ),
        "solve_z": (
            f_solve_z,
            (zkern, bhat, zhat),
            lambda o: o.real.sum(),
        ),
        "codes_irfft": (f_izhat, (zhat,), lambda o: o.sum()),
        "soft_threshold": (f_prox, (state.z,), lambda o: o.sum()),
    }
    table = {}
    for name, (fn, args, red) in stages.items():
        # jit fn+scalar-reduction together: no eager complex ops (axon
        # can't do them), and the full output stays an executable
        # output so it is still materialized to HBM.
        def g(*a, _fn=fn, _red=red):
            o = _fn(*a)
            return o, jnp.real(jnp.asarray(_red(o))).astype(jnp.float32)

        gj = jax.jit(g)
        _, s = gj(*args)  # compile
        float(s)
        t0 = time.perf_counter()
        for _ in range(reps):
            _, s = gj(*args)
        float(s)
        table[name] = (time.perf_counter() - t0) / reps * 1e3  # ms
    return {k: round(v, 3) for k, v in table.items()}


def last_onchip_record():
    """Most recent real-chip bench record from onchip_r*.jsonl.

    When the tunnel is down at snapshot time the fallback number is
    200x off the chip's; carrying the last on-chip result (with its
    source + age) keeps rounds comparable (VERDICT r4 weak #2)."""
    import glob

    entries = []  # ends as the NEWEST nonempty file's records — older
    # rounds ran older code (mirrors pick_tuned's file restriction)
    for path in sorted(
        glob.glob(os.path.join(REPO, "onchip_r*.jsonl")),
        key=os.path.getmtime,
    ):
        age_h = (time.time() - os.path.getmtime(path)) / 3600.0
        try:
            lines = open(path).read().splitlines()
        except OSError:
            continue
        found = []
        for line in lines:
            try:
                rec = json.loads(line)
            except Exception:
                continue
            res = rec.get("result") or {}
            metric = res.get("metric", "")
            if (
                rec.get("run")
                and ", 1 chip" in metric
                # serving-arm records measure requests/sec of another
                # workload — not comparable to the north-star pace
                and res.get("unit", "outer_iters/sec")
                == "outer_iters/sec"
                and float(res.get("value", 0.0)) > 0
            ):
                found.append({
                    "run": rec["run"],
                    "value": res["value"],
                    "vs_baseline": res.get("vs_baseline"),
                    "knobs": res.get("knobs"),
                    "source": os.path.basename(path),
                    "source_age_hours": round(age_h, 1),
                })
        if found:
            entries = found
    if not entries:
        return None, None
    # 'fastest' may be an accuracy-gated opt-in arm — the knobs say which
    return entries[-1], max(entries, key=lambda e: e["value"])


def emit(r, degraded=False):
    if r.get("serve"):
        return emit_serve(r, degraded=degraded)
    target_pace = 20.0 / 300.0  # north-star: 20 outer iters in 5 min
    if degraded:
        # only the fallback path after a failed TPU attempt is DEGRADED;
        # an intentional JAX_PLATFORMS=cpu run is labeled neutrally
        suffix = f", DEGRADED: TPU unreachable, ran on {r['platform']}"
    elif r["platform"] in ("tpu", "axon"):
        suffix = ", 1 chip"
    else:
        suffix = f", {r['platform']}"
    # telemetry provenance (utils.obs): an explicit machine-readable
    # degraded boolean (the four-of-five degraded-CPU records of r5
    # were only distinguishable by parsing the metric STRING), the git
    # sha of the producing tree, and the event-stream path when the
    # bench wrote one (CCSC_BENCH_METRICS_DIR)
    from ccsc_code_iccv2017_tpu.utils import obs as _obs

    out = {
        "metric": (
            f"2D consensus ADMM outer iters/sec "
            f"(k={r['k']} 11x11 filters, n={r['n']}x{r['size']}^2, "
            f"{r['blocks']} blocks{suffix})"
        ),
        "value": round(r["iters_per_sec"], 4),
        "unit": "outer_iters/sec",
        "vs_baseline": round(r["iters_per_sec"] / target_pace, 3),
        "degraded": bool(degraded),
        "git_sha": _obs.git_sha(),
        "event_stream": r.get("event_stream"),
    }
    if r.get("knobs"):
        out["knobs"] = r["knobs"]
    if r.get("peak_hbm_bytes") is not None:
        out["peak_hbm_bytes"] = r["peak_hbm_bytes"]
    if r.get("modeled_hbm_bytes") is not None:
        out["modeled_hbm_bytes"] = r["modeled_hbm_bytes"]
    if r.get("n_compiles") is not None:
        out["n_compiles"] = r["n_compiles"]
    u = r.get("util")
    if u:
        out["mfu"] = round(u["mfu_vs_bf16_peak"], 5)
        out["hbm_frac"] = round(u["hbm_frac"], 4)
        out["achieved_tflops"] = round(u["achieved_tflops"], 3)
        out["achieved_gbps"] = round(u["achieved_gbps"], 2)
        out["flops_per_step"] = u["flops_per_step"]
        out["bytes_per_step"] = u["bytes_per_step"]
        out["chip"] = u["chip"]
        out["cost_source"] = u["cost_source"]
    _ledger_append_bench(r, out, degraded)
    if degraded:
        last, fastest = last_onchip_record()
        if last is not None:
            out["last_onchip"] = last
        # compare VALUES, not object identity: an earlier arm that
        # merely ties the newest record is not a distinct faster
        # record and must not be re-emitted as one (ADVICE r5)
        if (
            fastest is not None
            and last is not None
            and fastest["value"] > last["value"]
        ):
            out["best_onchip"] = fastest
    print(json.dumps(out))


def _ledger_append_bench(r, out, degraded):
    """Append this arm's normalized record to the durable perf ledger
    (analysis.ledger; no-op unless CCSC_PERF_LEDGER is set). Keyed by
    the chip that actually measured it — a degraded CPU fallback
    accrues cpu history, never poisons a TPU key."""
    from ccsc_code_iccv2017_tpu.analysis import ledger as _ledger

    if not _ledger.enabled():
        return
    from ccsc_code_iccv2017_tpu.tune import store as _tstore

    u = r.get("util") or {}
    chip = u.get("chip") or r.get("platform")
    if not chip:
        return
    _ledger.maybe_append(
        chip=chip,
        kind="bench",
        workload="consensus2d",
        shape_key=_tstore.learn_shape_key(
            "consensus2d", k=r["k"], support=(11, 11), n=r["n"],
            size=(r["size"], r["size"]), blocks=r["blocks"],
        ),
        knobs=r.get("knobs") or {},
        value=r["iters_per_sec"],
        unit="outer_iters/sec",
        git_sha=out.get("git_sha"),
        mfu=u.get("mfu_vs_bf16_peak"),
        hbm_frac=u.get("hbm_frac"),
        n_compiles=r.get("n_compiles"),
        peak_hbm_bytes=r.get("peak_hbm_bytes"),
        modeled_hbm_bytes=r.get("modeled_hbm_bytes"),
        degraded=bool(degraded),
        source="bench.py",
    )


def emit_serve(r, degraded=False):
    """The CCSC_BENCH_SERVE arm's record: engine requests/sec, with
    vs_baseline = speedup over the one-reconstruct()-per-request
    driver loop on the same stream (the acceptance comparison); the
    loop's warm rate, latency percentiles, occupancy, and the
    zero-recompile assertion ride along."""
    from ccsc_code_iccv2017_tpu.utils import obs as _obs

    if degraded:
        suffix = f", DEGRADED: TPU unreachable, ran on {r['platform']}"
    elif r["platform"] in ("tpu", "axon"):
        suffix = ", 1 chip"
    else:
        suffix = f", {r['platform']}"
    out = {
        "metric": f"serving engine requests/sec ({r['workload']}{suffix})",
        "value": r["engine_requests_per_sec"],
        "unit": "requests/sec",
        "vs_baseline": r["speedup_vs_loop"],
        "degraded": bool(degraded),
        "git_sha": _obs.git_sha(),
        "event_stream": r.get("event_stream"),
        "loop_requests_per_sec": r["loop_requests_per_sec"],
        "loop_warm_requests_per_sec": r["loop_warm_requests_per_sec"],
        "p50_ms": r["p50_ms"],
        "p99_ms": r["p99_ms"],
        "mean_occupancy": r["mean_occupancy"],
        "zero_recompile_ok": r["zero_recompile_ok"],
        "max_rel_err_vs_loop": r["max_rel_err_vs_loop"],
        "warmup_s": r["warmup_s"],
        "knobs": r.get("knobs"),
    }
    if r.get("peak_hbm_bytes") is not None:
        out["peak_hbm_bytes"] = r["peak_hbm_bytes"]
    if r.get("n_compiles") is not None:
        out["n_compiles"] = r["n_compiles"]
    # durable perf ledger (env-gated CCSC_PERF_LEDGER): the serving
    # arm's record, keyed by the chip that measured it — the parent
    # knows the degraded-ness the child workload cannot
    from ccsc_code_iccv2017_tpu.analysis import ledger as _ledger

    _ledger.append_serve_record(
        r, degraded=bool(degraded), git_sha=out.get("git_sha"),
        source="bench.py:serve",
    )
    print(json.dumps(out))


def attempt(extra_env, timeout):
    """Run the workload in a watched subprocess; return dict or None."""
    env = dict(os.environ)
    env.update(extra_env)
    env["CCSC_BENCH_INPROCESS"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            timeout=timeout,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        # surface the child's failure (r5: the fused_z arms died in ~70s
        # with the traceback swallowed by capture_output); the runner
        # appends our stderr to its log, so the tail lands there
        tail = (out.stderr or "").strip().splitlines()[-30:]
        print(
            "bench attempt failed (rc=%d):\n%s"
            % (out.returncode, "\n".join(tail)),
            file=sys.stderr,
        )
        return None
    for line in out.stdout.splitlines()[::-1]:
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    return None


def main():
    if os.environ.get("CCSC_BENCH_INPROCESS"):
        print(json.dumps(run_workload()))
        return
    timeout = float(os.environ.get("CCSC_BENCH_TIMEOUT", 900))
    r = attempt({}, timeout)
    if r is not None:
        # A first attempt landing on CPU is DEGRADED unless the caller
        # explicitly asked for a non-TPU platform (JAX_PLATFORMS set):
        # with the axon plugin registering zero devices JAX silently
        # falls back to CPU, and that must not read as a normal run.
        unexpected_cpu = r["platform"] not in (
            "tpu",
            "axon",
        ) and not os.environ.get("JAX_PLATFORMS")
        emit(r, degraded=unexpected_cpu)
        return
    # TPU attempt hung or crashed. The on-chip queue sets
    # CCSC_BENCH_NO_FALLBACK=1: an A/B arm's CPU fallback would be
    # DEGRADED (ignored by the picker) yet cost another full timeout of
    # the scarce tunnel window — fail fast instead. The driver's
    # end-of-round run keeps the fallback (a degraded number beats a
    # hang there).
    if os.environ.get("CCSC_BENCH_NO_FALLBACK") == "1":
        from ccsc_code_iccv2017_tpu.utils import obs as _obs

        print(
            json.dumps(
                {
                    "metric": "2D consensus ADMM outer iters/sec "
                    "(FAILED: TPU attempt did not complete; fallback "
                    "disabled by CCSC_BENCH_NO_FALLBACK)",
                    "value": 0.0,
                    "unit": "outer_iters/sec",
                    "vs_baseline": 0.0,
                    "degraded": True,
                    "git_sha": _obs.git_sha(),
                }
            )
        )
        return
    # degrade to CPU so the round still records a number (and says so)
    r = attempt({"JAX_PLATFORMS": "cpu"}, timeout)
    if r is not None:
        emit(r, degraded=True)
        return
    from ccsc_code_iccv2017_tpu.utils import obs as _obs

    print(
        json.dumps(
            {
                "metric": "2D consensus ADMM outer iters/sec (FAILED: "
                "no backend completed within timeout)",
                "value": 0.0,
                "unit": "outer_iters/sec",
                "vs_baseline": 0.0,
                "degraded": True,
                "git_sha": _obs.git_sha(),
            }
        )
    )


if __name__ == "__main__":
    main()
