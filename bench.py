#!/usr/bin/env python
"""Benchmark: consensus ADMM dictionary-learning throughput on TPU.

The BASELINE.json north-star is the 2D learning workload of
2D/learn_kernels_2D_large.m (100 filters of 11x11, consensus blocks,
20 outer iterations) with target "<5 min end-to-end on a v5e-8".
This benchmark runs the same outer-step shape on ONE chip and reports
outer iterations/sec; vs_baseline is measured pace divided by the
north-star pace (20 iters / 300 s), i.e. > 1.0 beats the target pace.

Prints exactly one JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Robustness: the measured workload runs in a SUBPROCESS with a watchdog
(the axon TPU tunnel can wedge and hang a client indefinitely; a hung
bench would record nothing for the round). If the TPU attempt times
out or dies, the bench reruns on CPU and says so in the metric name —
a degraded-but-present number beats a hang.

Env knobs: CCSC_BENCH_N (images, default 128), CCSC_BENCH_SIZE (image
side, default 100), CCSC_BENCH_K (filters, default 100),
CCSC_BENCH_BLOCKS (default 8), CCSC_BENCH_ITERS (timed outer
iterations, default 3), CCSC_BENCH_TIMEOUT (seconds per attempt,
default 900), CCSC_BENCH_INPROCESS=1 (skip the watchdog wrapper),
CCSC_BENCH_PALLAS=1 (route the z-solve through the fused Pallas
kernel — for on-chip A/B against the default einsum path).
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def run_workload():
    """The measured workload. Runs in-process; called in the child."""
    from ccsc_code_iccv2017_tpu.utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    import jax
    import jax.numpy as jnp

    from ccsc_code_iccv2017_tpu.config import LearnConfig, ProblemGeom
    from ccsc_code_iccv2017_tpu.models import common, learn as learn_mod
    from ccsc_code_iccv2017_tpu.parallel import consensus

    n = int(os.environ.get("CCSC_BENCH_N", 128))
    size = int(os.environ.get("CCSC_BENCH_SIZE", 100))
    k = int(os.environ.get("CCSC_BENCH_K", 100))
    blocks = int(os.environ.get("CCSC_BENCH_BLOCKS", 8))
    iters = int(os.environ.get("CCSC_BENCH_ITERS", 3))

    use_pallas = os.environ.get("CCSC_BENCH_PALLAS") == "1"
    geom = ProblemGeom((11, 11), k)
    cfg = LearnConfig(
        max_it=iters,
        max_it_d=5,
        max_it_z=10,
        num_blocks=blocks,
        rho_d=5000.0,
        rho_z=1.0,
        verbose="none",
        use_pallas=use_pallas,
    )
    fg = common.FreqGeom.create(geom, (size, size))

    key = jax.random.PRNGKey(0)
    ni = n // blocks
    # synthetic data on device — the benchmark measures the solver, not IO
    b_blocks = jax.random.normal(
        jax.random.PRNGKey(1), (blocks, ni, size, size), jnp.float32
    )
    state = learn_mod.init_state(key, geom, fg, blocks, ni)

    step = consensus.make_outer_step(geom, cfg, fg, mesh=None)

    # warmup / compile. NB: jax.block_until_ready is a no-op on the
    # axon TPU platform — a scalar readback is the only reliable fence.
    s1, m0 = step(state, b_blocks)
    float(m0.obj_z)

    t0 = time.perf_counter()
    cur = s1
    for _ in range(iters):
        cur, m = step(cur, b_blocks)
    float(m.obj_z)  # fences the whole chain
    dt = time.perf_counter() - t0

    platform = jax.devices()[0].platform
    return {
        "iters_per_sec": iters / dt,
        "n": n,
        "size": size,
        "k": k,
        "blocks": blocks,
        "platform": platform,
    }


def emit(r, degraded=False):
    target_pace = 20.0 / 300.0  # north-star: 20 outer iters in 5 min
    suffix = (
        f", DEGRADED: TPU unreachable, ran on {r['platform']}"
        if degraded
        else ", 1 chip"
    )
    print(
        json.dumps(
            {
                "metric": (
                    f"2D consensus ADMM outer iters/sec "
                    f"(k={r['k']} 11x11 filters, n={r['n']}x{r['size']}^2, "
                    f"{r['blocks']} blocks{suffix})"
                ),
                "value": round(r["iters_per_sec"], 4),
                "unit": "outer_iters/sec",
                "vs_baseline": round(r["iters_per_sec"] / target_pace, 3),
            }
        )
    )


def attempt(extra_env, timeout):
    """Run the workload in a watched subprocess; return dict or None."""
    env = dict(os.environ)
    env.update(extra_env)
    env["CCSC_BENCH_INPROCESS"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            timeout=timeout,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    for line in out.stdout.splitlines()[::-1]:
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    return None


def main():
    if os.environ.get("CCSC_BENCH_INPROCESS"):
        print(json.dumps(run_workload()))
        return
    timeout = float(os.environ.get("CCSC_BENCH_TIMEOUT", 900))
    r = attempt({}, timeout)
    if r is not None:
        emit(r, degraded=r["platform"] not in ("tpu", "axon"))
        return
    # TPU attempt hung or crashed — degrade to CPU so the round still
    # records a number (and says so).
    r = attempt({"JAX_PLATFORMS": "cpu"}, timeout)
    if r is not None:
        emit(r, degraded=True)
        return
    print(
        json.dumps(
            {
                "metric": "2D consensus ADMM outer iters/sec (FAILED: "
                "no backend completed within timeout)",
                "value": 0.0,
                "unit": "outer_iters/sec",
                "vs_baseline": 0.0,
            }
        )
    )


if __name__ == "__main__":
    main()
