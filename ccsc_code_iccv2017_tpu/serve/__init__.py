"""Reconstruction serving: many requests against one pinned bank.

The reference's serving story is a MATLAB for-loop — one image, one
full solver invocation, all operator precompute re-derived per call
(reconstruct_2D_subsampling.m:35-60). This package is the
production-shape replacement: :class:`CodecEngine` pins a dictionary
bank + ReconstructionProblem + SolveConfig once and serves many
requests fast — per-bank solve plans (models.reconstruct.ReconPlan),
shape-bucketed AOT-compiled programs warmed at startup, and a
micro-batching request queue. :class:`ServeFleet` (serve.fleet) is the
fault-tolerance layer above it: N replicated engines behind one front
queue with health-driven requeue, idempotent result delivery, and
admission control with a predictable overload ladder.
:class:`WorkloadRecorder` (serve.capture) records every admitted
request durably — payloads content-addressed by sha256, outcomes
digested — and :class:`ReplayDriver` (serve.replay) re-serves a
captured stream against a fresh fleet with bit-identity
verification: the recorded workload is the fleet's measuring
instrument. :class:`DurableQueue` (serve.dqueue) and
:class:`FederatedHost` / :class:`FederatedFrontend`
(serve.federation) take the same contracts cross-host: fleets in
separate processes drain one shared file-lease queue, and a
whole-host SIGKILL is just an expired lease the survivors reap.
:class:`BankRegistry` (serve.registry) and the tenancy layer
(serve.tenancy) make the stack multi-tenant: durable bank manifests,
request routing by bank id with a per-bank plan LRU, zero-downtime
hot-swap of a republished bank (``publish_bank`` — in-flight
requests finish on the old plan, the cutover is a ``bank_swap``
event), per-tenant SLO histograms (serve.slo.TenantSlos), and
weighted-fair admission with per-tenant quotas so one tenant's burst
gets its own ``Overloaded`` rejections while other tenants' latency
bands hold. :class:`ArtifactStore` (serve.artifacts) is the
pre-warmed-elasticity layer: a shared content-addressed store of
AOT-serialized bucket executables keyed by program fingerprint x
chip x mesh, so a joining host FETCHES its programs instead of
compiling them, and staged warmup (ServeConfig.staged_warmup) serves
the hottest bucket the moment its program is ready — cold buckets
build in the background behind explicit :class:`BucketCold`
retry-after refusals. :class:`CapacityController` (serve.controller)
closes the capacity loop: a strictly-advisory control plane reading
one sensor snapshot per tick (queue depth vs the derived ceiling,
SLO p99, warmup ETAs, HBM watermark) and driving
``ServeFleet.set_replica_count`` grow/shrink, the brownout rung, and
:class:`FederatedHostPool` host spin-up/down — with hysteresis,
cooldowns, fail-safe stale-sensor holdoffs, and a stuck-actuator
circuit breaker, so its death leaves the fleet serving exactly as
configured.
"""
from .artifacts import (  # noqa: F401
    ArtifactStore,
    artifact_key,
    deserialize_program,
    program_fingerprint,
    rank_buckets,
    resolve_artifact_dir,
    serialize_program,
)
from .capture import WorkloadRecorder  # noqa: F401
from .controller import CapacityController  # noqa: F401
from .dqueue import DurableQueue  # noqa: F401
from .engine import (  # noqa: F401
    BucketCold,
    CodecEngine,
    DeadlineExceeded,
    ServedResult,
    enable_compile_cache,
    pick_bucket,
)
from .federation import (  # noqa: F401
    FederatedFrontend,
    FederatedHost,
    FederatedHostPool,
    FederatedResult,
)
from .fleet import Overloaded, ServeFleet  # noqa: F401
from .metricsd import MetricsD  # noqa: F401
from .registry import BankRegistry, PlanCache, bank_digest  # noqa: F401
from .replay import ReplayDriver, generate_diurnal  # noqa: F401
from .slo import Histogram, SloMonitor, TenantSlos  # noqa: F401
from .tenancy import (  # noqa: F401
    TenantSpec,
    TenantTable,
    WeightedFairScheduler,
    parse_tenant_spec,
)
