"""Compiled-program artifact store: content-addressed AOT executables.

Production scale multiplies compiles — every (bucket x mesh x knob arm
x chip) pair is its own XLA program, and a freshly scaled host pays
full AOT warmup for every bucket before serving a request. The
per-process persistent XLA cache (``CCSC_COMPILE_CACHE``) only fixes
warm RESTARTS of the same machine; a NEW host starts cold. This module
is the compiled-program analog of the bank registry
(:mod:`.registry`): the program, not the process, is the unit of reuse
(the MPAX jit-cache fleet pattern, PAPERS.md 2412.09734).

- :func:`program_fingerprint` — content identity of one bucket
  program: bucket shape, problem geometry, the engine's RESOLVED knob
  dict (solve arm + tune + mesh topology), the plan's pytree structure
  and leaf avals (a structural change — blur OTF present, bf16
  factors — is a different program even under identical knobs), and
  the jax version (serialized executables do not cross jax releases).
- :func:`artifact_key` — the store key: fingerprint x chip kind x
  mesh shape. A v5e executable must never be offered to a CPU host —
  cross-chip fetches are REFUSED, mirroring the tuned-store stance.
- :class:`ArtifactStore` — durable store with the registry's
  discipline: one ``manifest.jsonl`` appended line-per-record and read
  with the ``analysis.ledger`` torn-tail stance (a torn or truncated
  record reads as ABSENT, never as an error), payloads under
  ``programs/<key>.bin`` written tmp + ``os.link`` (O_EXCL first-wins:
  exactly one of N concurrent publishers links the payload and appends
  the manifest record; losers discard). Fetch re-verifies the payload
  sha against the manifest — a truncated or hand-edited artifact reads
  as absent and the engine falls back to live compile, then
  re-publishes (the repair path replaces the corrupt payload
  atomically).
- :func:`serialize_program` / :func:`deserialize_program` — the AOT
  executable wire format (``jax.experimental.serialize_executable`` +
  the arg/result treedefs in one self-describing blob).
- :func:`rank_buckets` — the staged-warmup ordering: declared order
  first, else request frequency from a workload capture
  (serve.capture), else the configured volume order. The hottest
  bucket's program is built/fetched FIRST so a joining host serves it
  while cold buckets warm in the background.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils import env as _env
from ..utils import obs as _obs

__all__ = [
    "ArtifactStore",
    "artifact_key",
    "bucket_label",
    "deserialize_program",
    "program_fingerprint",
    "rank_buckets",
    "resolve_artifact_dir",
    "serialize_program",
]

_MANIFEST_NAME = "manifest.jsonl"
_PROGRAM_DIR = "programs"
_SCHEMA = 1
# payload blob schema: bumped whenever the pickle layout changes so a
# reader can refuse a future format instead of mis-parsing it
_PAYLOAD_SCHEMA = 1


def resolve_artifact_dir(explicit: Optional[str]) -> Optional[str]:
    """The one resolution chain for the artifact-store location: an
    explicit path wins, ``""`` is explicitly off (even with the env
    knob armed), else ``CCSC_ARTIFACT_STORE``, else no store (None) —
    the ``resolve_registry_dir`` convention."""
    if explicit == "":
        return None
    return explicit or _env.env_str("CCSC_ARTIFACT_STORE") or None


def bucket_label(slots: int, spatial: Sequence[int]) -> str:
    """The engine's bucket naming (``"slots@HxW"``) without importing
    the engine (this module must stay import-light for tooling)."""
    return f"{int(slots)}@" + "x".join(str(int(s)) for s in spatial)


def _mesh_token(mesh_shape: Optional[Sequence[int]]) -> str:
    if not mesh_shape:
        return "single"
    return "mesh" + "x".join(str(int(a)) for a in mesh_shape)


def program_fingerprint(
    *,
    bucket: Tuple[int, Tuple[int, ...]],
    geom,
    problem: Optional[Dict[str, Any]] = None,
    knobs: Optional[Dict[str, Any]] = None,
    mesh_shape: Optional[Sequence[int]] = None,
    plan=None,
) -> str:
    """Content identity of one bucket program (sha256, first 20 hex).

    Everything that changes the LOWERED program must be in here:
    bucket shape, geometry, the problem's static solve structure, the
    resolved knob dict (the serving engine's ``_knob_dict`` — solve
    arm, tune resolution, mesh topology), and — when a built plan is
    given — the plan pytree's STRUCTURE and leaf avals: a plan with a
    blur OTF leaf, or bf16 solve factors, lowers to a different
    executable than one without, even under an identical knob dict.
    The jax version is folded in because serialized executables do not
    cross releases (deserialization refuses them anyway; the version
    in the key just keeps incompatible artifacts from colliding)."""
    import jax

    slots, spatial = bucket
    desc: Dict[str, Any] = {
        "schema": _SCHEMA,
        "jax": jax.__version__,
        "bucket": [int(slots), [int(s) for s in spatial]],
        "geom": {
            "num_filters": int(geom.num_filters),
            "spatial_support": list(geom.spatial_support),
            "reduce_shape": list(geom.reduce_shape),
        },
        "problem": dict(problem or {}),
        "knobs": dict(knobs or {}),
        "mesh": list(mesh_shape) if mesh_shape else None,
    }
    if plan is not None:
        desc["plan_tree"] = str(jax.tree_util.tree_structure(plan))
        desc["plan_avals"] = [
            [list(getattr(leaf, "shape", ())),
             str(getattr(leaf, "dtype", type(leaf).__name__))]
            for leaf in jax.tree_util.tree_leaves(plan)
        ]
    blob = json.dumps(desc, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:20]


def artifact_key(
    fingerprint: str,
    chip: str,
    mesh_shape: Optional[Sequence[int]] = None,
) -> str:
    """The store key: one program fingerprint on one chip kind and
    mesh shape. Human-readable on purpose — ``ls programs/`` answers
    "what is cached for which chip" without parsing the manifest."""
    return f"{chip}-{_mesh_token(mesh_shape)}-{fingerprint}"


def serialize_program(compiled) -> bytes:
    """One self-describing blob for an AOT-compiled executable:
    the ``jax.experimental.serialize_executable`` payload plus the
    arg/result treedefs the loader needs (all picklable — treedef aux
    data is digest-canonicalized strings/ints by the time a bucket
    program is lowered)."""
    from jax.experimental import serialize_executable as _se

    payload, in_tree, out_tree = _se.serialize(compiled)
    return pickle.dumps(
        (_PAYLOAD_SCHEMA, payload, in_tree, out_tree),
        protocol=pickle.HIGHEST_PROTOCOL,
    )


def deserialize_program(blob: bytes):
    """Load a serialized bucket program back into a callable
    executable — no trace, no XLA compile. Raises on a foreign or
    torn blob (the caller treats that as a miss and live-compiles)."""
    from jax.experimental import serialize_executable as _se

    ver, payload, in_tree, out_tree = pickle.loads(blob)
    if ver != _PAYLOAD_SCHEMA:
        raise ValueError(
            f"artifact payload schema {ver} != {_PAYLOAD_SCHEMA}"
        )
    return _se.deserialize_and_load(payload, in_tree, out_tree)


class ArtifactStore:
    """Durable content-addressed store of serialized bucket programs.

    Concurrency discipline (hosts share a filesystem, nothing else):

    - payloads: written to a pid/thread-suffixed tmp file, then
      ``os.link``\\ ed into place — the link either creates the final
      name (this publisher WON) or raises ``FileExistsError`` (a
      concurrent publisher won; discard). ``os.replace`` fallback for
      filesystems without hard links.
    - manifest: one flushed JSONL line per publish through
      ``utils.obs.EventWriter`` (torn-tail terminated on open); reads
      via ``read_events`` drop torn/corrupt lines — a killed publisher
      leaves an absent record, never a poisoned store.
    - repair: a publish whose payload DIFFERS from the bytes already
      on disk (a corrupt artifact a fetch just refused) atomically
      replaces them and appends a fresh manifest record — newest
      record wins on read, so the store heals forward.

    ``emit`` is an optional obs-event callable (``run.event``-shaped):
    every publish is then announced as an ``artifact_publish`` event.
    """

    def __init__(self, path: str, emit=None):
        self.path = path
        self._emit = emit
        self._lock = threading.Lock()
        os.makedirs(os.path.join(path, _PROGRAM_DIR), exist_ok=True)
        self._seq = max(
            (int(r.get("seq", 0)) for r in self._read_manifest()),
            default=0,
        )
        self._writer = _obs.EventWriter(
            os.path.join(path, _MANIFEST_NAME)
        )

    # -- read side ----------------------------------------------------
    def _read_manifest(self) -> List[Dict[str, Any]]:
        return [
            r
            for r in _obs.read_events(
                os.path.join(self.path, _MANIFEST_NAME)
            )
            if r.get("key") and r.get("sha256")
        ]

    def keys(self) -> List[str]:
        """Every artifact key ever published, insertion order."""
        seen: Dict[str, None] = {}
        for rec in self._read_manifest():
            seen.setdefault(rec["key"], None)
        return list(seen)

    def resolve(self, key: str) -> Optional[Dict[str, Any]]:
        """The NEWEST manifest record for ``key`` (a repair republish
        supersedes the record of the corrupt payload it replaced), or
        None."""
        newest = None
        for rec in self._read_manifest():
            if rec["key"] == key:
                newest = rec
        return newest

    def fetch(
        self,
        key: str,
        *,
        fingerprint: Optional[str] = None,
        chip: Optional[str] = None,
    ) -> Tuple[Optional[bytes], str]:
        """The verified payload for ``key``, or ``(None, reason)``.

        Refusals — all read as a MISS by the caller, which then
        live-compiles (and republishes, healing the store):

        - ``miss``: no durable manifest record (includes a torn one);
        - ``chip_mismatch`` / ``fingerprint_mismatch``: the record is
          for a different chip kind or program identity than asked —
          a foreign executable must never be loaded;
        - ``version_skew``: published under a different jax release;
        - ``missing_payload`` / ``corrupt``: payload unreadable, or
          its bytes drifted from the manifest sha (truncation, torn
          write, hand edit).
        """
        rec = self.resolve(key)
        if rec is None:
            return None, "miss"
        if chip is not None and rec.get("chip") != chip:
            return None, "chip_mismatch"
        if (
            fingerprint is not None
            and rec.get("fingerprint") != fingerprint
        ):
            return None, "fingerprint_mismatch"
        import jax

        if rec.get("jax") != jax.__version__:
            return None, "version_skew"
        try:
            with open(
                os.path.join(self.path, rec["path"]), "rb"
            ) as f:
                blob = f.read()
        except OSError:
            return None, "missing_payload"
        if hashlib.sha256(blob).hexdigest() != rec["sha256"]:
            return None, "corrupt"
        return blob, "hit"

    # -- write side ---------------------------------------------------
    def publish(
        self,
        key: str,
        payload: bytes,
        *,
        fingerprint: str,
        chip: str,
        mesh_shape: Optional[Sequence[int]] = None,
        bucket: Optional[str] = None,
        **meta,
    ) -> Tuple[Optional[Dict[str, Any]], str]:
        """Durably publish one serialized program. Returns
        ``(manifest_record, status)`` with status one of:

        - ``won``: this call linked the payload in and appended the
          manifest record (exactly one of N concurrent publishers);
        - ``lost``: a concurrent publisher linked first — payload
          discarded, their record (possibly not yet durable) wins;
        - ``exists``: identical bytes already stored — deduped, no new
          record;
        - ``repair``: the on-disk payload differed (corrupt store) —
          replaced atomically and re-recorded.
        """
        import jax

        sha = hashlib.sha256(payload).hexdigest()
        rel = os.path.join(_PROGRAM_DIR, f"{key}.bin")
        fpath = os.path.join(self.path, rel)
        status = "won"
        if os.path.exists(fpath):
            existing = None
            with contextlib.suppress(OSError):
                with open(fpath, "rb") as f:
                    existing = hashlib.sha256(f.read()).hexdigest()
            if existing == sha:
                status = "exists"
            else:
                status = "repair"
        if status != "exists":
            tmp = (
                fpath
                + f".tmp.{os.getpid()}.{threading.get_ident()}"
            )
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            if status == "won":
                try:
                    # O_EXCL discipline: the link either creates the
                    # final name or a concurrent publisher beat us
                    os.link(tmp, fpath)
                except FileExistsError:
                    status = "lost"
                except OSError:  # pragma: no cover - no-hardlink fs
                    os.replace(tmp, fpath)
                    tmp = None
                if tmp:
                    with contextlib.suppress(OSError):
                        os.unlink(tmp)
            else:
                os.replace(tmp, fpath)
        rec: Optional[Dict[str, Any]]
        if status in ("won", "repair"):
            rec = dict(
                schema=_SCHEMA,
                key=str(key),
                fingerprint=str(fingerprint),
                chip=str(chip),
                mesh=list(mesh_shape) if mesh_shape else None,
                bucket=bucket,
                jax=jax.__version__,
                sha256=sha,
                size=len(payload),
                path=rel,
                host=socket.gethostname(),
                pid=os.getpid(),
                t=time.time(),
                **meta,
            )
            with self._lock:
                self._seq += 1
                rec["seq"] = self._seq
                self._writer.write(dict(rec))
        else:
            rec = self.resolve(key)
        if self._emit is not None:
            self._emit(
                "artifact_publish",
                key=str(key),
                status=status,
                bucket=bucket,
                chip=str(chip),
                size=len(payload),
                store=self.path,
            )
        return rec, status

    def close(self) -> None:
        with self._lock:
            self._writer.close()

    def __enter__(self) -> "ArtifactStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def rank_buckets(
    buckets: Sequence[Tuple[int, Tuple[int, ...]]],
    declared: Optional[Sequence[str]] = None,
    capture_dir: Optional[str] = None,
) -> List[Tuple[int, Tuple[int, ...]]]:
    """Hot-to-cold ordering of a bucket table for staged warmup.

    ``declared`` (bucket labels, ``"slots@HxW"``) wins — an operator
    who knows the traffic shape states it; labels must name configured
    buckets (a typo must not silently demote the hot bucket), and
    unlisted buckets follow in configured (volume) order. Else, when
    ``capture_dir`` holds a workload capture (serve.capture), buckets
    are ranked by recorded request frequency — the measured
    distribution of the traffic the engine is about to serve. Else the
    configured volume order stands (smallest first — also the
    cheapest program to build, so time-to-first-serveable is minimized
    even without traffic knowledge)."""
    from ..utils import validate

    table = list(buckets)
    labels = {bucket_label(s, sp): (s, sp) for s, sp in table}
    if declared:
        order: List[Tuple[int, Tuple[int, ...]]] = []
        for name in declared:
            if name not in labels:
                raise validate.CCSCInputError(
                    f"warm_order names bucket {name!r} which is not "
                    f"configured — buckets: {sorted(labels)}"
                )
            key = labels[name]
            if key not in order:
                order.append(key)
        order.extend(k for k in table if k not in order)
        return order
    if capture_dir:
        from . import capture as _capture

        counts: Dict[str, int] = {}
        for rec in _capture.read_workload(capture_dir):
            name = rec.get("bucket")
            if name:
                counts[name] = counts.get(name, 0) + 1
        if counts:
            return sorted(
                table,
                key=lambda k: -counts.get(bucket_label(*k), 0),
            )
    return table
