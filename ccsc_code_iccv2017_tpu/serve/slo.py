"""SLO layer: streaming log-bucketed latency histograms + declared
latency targets, checked continuously in-process.

The serving stack measured latency but never WATCHED it: percentiles
were computed from raw sample lists at ``stats()`` time (unbounded
memory on a long-lived engine, and nothing fired while p99 was
quietly blowing past its budget). This module replaces both:

- :class:`Histogram` — fixed-size log-bucketed latency histogram
  (Prometheus ``le`` semantics): O(1) memory forever, O(#buckets)
  percentile queries, mergeable, and serializable as an obs
  ``slo_histogram`` record so any stream reader can recompute
  fleet-wide percentiles. This is THE percentile implementation of
  the serving stack — engine ``stats()``, fleet ``stats()``,
  ``serve.bench`` and ``scripts/obs_report.py`` all quote it (the
  exact nearest-rank ``utils.obs.percentile`` remains for small
  one-shot samples).
- :class:`SloMonitor` — per-phase histograms (submit→result
  ``total``, queue wait, solve) plus declared targets
  (``ServeConfig.slo_p50_ms`` / ``slo_p99_ms``, env
  ``CCSC_SLO_P50_MS`` / ``CCSC_SLO_P99_MS``). ``tick()`` checks the
  targets every ``CCSC_SLO_CHECK_S`` seconds and returns breach
  records (emitted as ``slo_breach`` events) and periodic histogram
  snapshots (``slo_histogram`` events). A breach can additionally
  arm a ONE-SHOT ``utils.profiling.xla_trace`` capture around the
  engine's next dispatch (``ServeConfig.slo_profile_dir`` /
  ``CCSC_SLO_XPROF_DIR``) — the "why was p99 slow" answer becomes an
  xprof trace instead of a guess.

Thread-safe: ``observe`` is called from worker threads, ``tick`` from
the fleet monitor thread; all state mutations hold the internal lock,
and nothing is emitted under it (the caller emits the returned
records — the thread-safety lint forbids stream writes under a held
lock).
"""
from __future__ import annotations

import math
import threading
import time
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import env as _env

__all__ = [
    "Histogram",
    "SloMonitor",
    "TenantSlos",
    "DEFAULT_BOUNDS_MS",
    "default_bounds",
    "resolve_targets",
    "from_snapshot",
]


def default_bounds(
    lo_ms: float = 0.1, hi_ms: float = 600_000.0, growth: float = 1.6
) -> Tuple[float, ...]:
    """Log-spaced bucket upper edges from ``lo_ms`` to past ``hi_ms``
    (0.1 ms .. 10 min at the defaults — 34 buckets + overflow covers
    a CPU test engine and a TPU fleet with the same table, so
    histograms from any stream merge)."""
    out = [round(lo_ms * growth**i, 6) for i in
           range(1 + int(math.ceil(math.log(hi_ms / lo_ms, growth))))]
    return tuple(out)


DEFAULT_BOUNDS_MS = default_bounds()


class Histogram:
    """Streaming log-bucketed histogram (bucket i counts observations
    <= bounds[i]; one extra overflow bucket past the last bound)."""

    __slots__ = ("bounds", "counts", "n", "sum_ms", "max_ms")

    def __init__(self, bounds: Sequence[float] = DEFAULT_BOUNDS_MS):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.n = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    @classmethod
    def of(cls, values_ms, bounds: Sequence[float] = DEFAULT_BOUNDS_MS):
        h = cls(bounds)
        for v in values_ms:
            h.observe(v)
        return h

    def observe(self, ms: float) -> None:
        ms = float(ms)
        self.counts[bisect_left(self.bounds, ms)] += 1
        self.n += 1
        self.sum_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms

    def bucket_width_ms(self, ms: float) -> float:
        """Width of the bucket containing ``ms`` — the histogram's
        resolution at that latency (percentile answers are honest to
        within one width)."""
        i = bisect_left(self.bounds, float(ms))
        if i >= len(self.bounds):
            return max(self.max_ms - self.bounds[-1], 0.0)
        lo = self.bounds[i - 1] if i > 0 else 0.0
        return self.bounds[i] - lo

    def _rank_bucket(self, q: float) -> Optional[int]:
        if self.n == 0:
            return None
        rank = max(1, int(math.ceil(q * self.n)))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return i
        return len(self.counts) - 1  # pragma: no cover - sums to n

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile, answered as the containing
        bucket's upper edge (clamped to the max observed value so the
        answer never exceeds reality). None when empty. Within one
        bucket width of the exact sample percentile by construction —
        the acceptance contract obs_report and the tests hold it to."""
        i = self._rank_bucket(q)
        if i is None:
            return None
        if i >= len(self.bounds):
            return self.max_ms
        return min(self.bounds[i], self.max_ms)

    def percentile_floor(self, q: float) -> Optional[float]:
        """LOWER edge of the rank bucket — the conservative bound the
        breach check compares against a target: every observation in
        the bucket is strictly above this edge (buckets hold
        ``(lower, upper]``), so ``floor >= target`` proves the true
        quantile exceeds the target, while the reported upper edge
        alone could overstate it by a bucket width and false-fire a
        breach (burning the one-shot xprof capture on a non-event)."""
        i = self._rank_bucket(q)
        if i is None:
            return None
        if i >= len(self.bounds):
            return self.bounds[-1]
        return self.bounds[i - 1] if i > 0 else 0.0

    def merge(self, other: "Histogram") -> None:
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.n += other.n
        self.sum_ms += other.sum_ms
        self.max_ms = max(self.max_ms, other.max_ms)

    def snapshot(self) -> Dict:
        """JSON-able state (the ``slo_histogram`` record body and the
        metricsd scrape source)."""
        return {
            "bounds_ms": list(self.bounds),
            "counts": list(self.counts),
            "n": self.n,
            "sum_ms": round(self.sum_ms, 3),
            "max_ms": round(self.max_ms, 3),
            "p50_ms": self.percentile(0.50),
            "p95_ms": self.percentile(0.95),
            "p99_ms": self.percentile(0.99),
        }


def from_snapshot(rec: Dict) -> Histogram:
    """Rebuild a histogram from an ``slo_histogram`` record (or a
    ``snapshot()`` dict) — how a stream reader recomputes fleet-wide
    percentiles offline."""
    h = Histogram(rec.get("bounds_ms") or DEFAULT_BOUNDS_MS)
    counts = rec.get("counts") or []
    for i, c in enumerate(counts[: len(h.counts)]):
        h.counts[i] = int(c)
    h.n = int(rec.get("n", sum(h.counts)))
    h.sum_ms = float(rec.get("sum_ms", 0.0))
    h.max_ms = float(rec.get("max_ms", 0.0))
    return h


def resolve_targets(
    p50_ms: Optional[float] = None, p99_ms: Optional[float] = None
) -> Dict[float, float]:
    """Quantile -> target-ms map from config values, falling back to
    the CCSC_SLO_* env knobs; empty when no SLO is declared."""
    if p50_ms is None:
        p50_ms = _env.env_float("CCSC_SLO_P50_MS")
    if p99_ms is None:
        p99_ms = _env.env_float("CCSC_SLO_P99_MS")
    out: Dict[float, float] = {}
    if p50_ms is not None and p50_ms > 0:
        out[0.50] = float(p50_ms)
    if p99_ms is not None and p99_ms > 0:
        out[0.99] = float(p99_ms)
    return out


class SloMonitor:
    """Per-phase latency histograms + continuous target checks.

    Phases are free-form labels; the serving stack uses ``total``
    (submit→result — the phase the targets apply to), ``queue`` and
    ``solve``. All methods are thread-safe; ``tick``/``final`` return
    records for the CALLER to emit (never emits under its own lock).
    """

    TARGET_PHASE = "total"

    def __init__(
        self,
        targets: Optional[Dict[float, float]] = None,
        check_s: Optional[float] = None,
        bounds: Sequence[float] = DEFAULT_BOUNDS_MS,
    ):
        self.targets = dict(targets or {})
        if check_s is None:
            check_s = _env.env_float("CCSC_SLO_CHECK_S")
        self.check_s = max(0.0, float(check_s))
        self._bounds = tuple(bounds)
        self._hists: Dict[str, Histogram] = {}
        self._last_check = 0.0
        self._last_n: Dict[float, int] = {}
        self._lock = threading.Lock()

    def observe(self, phase: str, ms: float) -> None:
        with self._lock:
            h = self._hists.get(phase)
            if h is None:
                h = self._hists[phase] = Histogram(self._bounds)
            h.observe(ms)

    def percentile(self, phase: str, q: float) -> Optional[float]:
        with self._lock:
            h = self._hists.get(phase)
            return h.percentile(q) if h is not None else None

    def n(self, phase: str) -> int:
        with self._lock:
            h = self._hists.get(phase)
            return h.n if h is not None else 0

    def _check_locked(self) -> List[Dict]:
        breaches: List[Dict] = []
        h = self._hists.get(self.TARGET_PHASE)
        if h is None or h.n == 0:
            return breaches
        for q, target in sorted(self.targets.items()):
            # only re-judge a quantile once new observations arrived —
            # a breached-and-idle engine must not re-fire every tick
            if self._last_n.get(q) == h.n:
                continue
            self._last_n[q] = h.n
            observed = h.percentile(q)
            floor = h.percentile_floor(q)
            # conservative: fire only when the rank bucket's LOWER
            # edge already meets the target — the true quantile is
            # then provably past it. Comparing the reported upper
            # edge would false-breach whenever the target merely
            # falls inside the rank bucket.
            if floor is not None and floor >= target:
                breaches.append(
                    {
                        "phase": self.TARGET_PHASE,
                        "quantile": q,
                        "target_ms": target,
                        "observed_ms": round(observed, 3),
                        "n": h.n,
                    }
                )
        return breaches

    def _snapshots_locked(self) -> List[Dict]:
        out = []
        for phase in sorted(self._hists):
            h = self._hists[phase]
            if h.n == 0:
                continue
            snap = {"phase": phase}
            snap.update(h.snapshot())
            out.append(snap)
        return out

    def tick(self, now: Optional[float] = None) -> Tuple[List[Dict], List[Dict]]:
        """(breach records, histogram snapshots) when the check
        cadence elapsed, else ([], []). The caller emits them
        (``slo_breach`` / ``slo_histogram``)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._last_check and now - self._last_check < self.check_s:
                return [], []
            self._last_check = now
            return self._check_locked(), self._snapshots_locked()

    def final(self) -> Tuple[List[Dict], List[Dict]]:
        """Unconditional closing flush (run summary path): the stream
        always ends with one complete histogram per phase, so a short
        run's percentiles are recomputable offline."""
        with self._lock:
            return self._check_locked(), self._snapshots_locked()

    def raw_snapshots(self) -> List[Dict]:
        """Current per-phase snapshots WITHOUT touching the breach
        bookkeeping — the metricsd scrape source (a scrape must never
        consume a pending breach trigger)."""
        with self._lock:
            return self._snapshots_locked()


class TenantSlos:
    """Per-TENANT latency SLO monitors (the multi-tenant face of
    :class:`SloMonitor`): one monitor per declared
    :class:`~..config.TenantSpec`, each judging its OWN declared
    p50/p99 targets against its own streaming histogram — one
    tenant's burst cannot move another tenant's quantiles, which is
    what makes "the other tenant's latency band held" a measurable
    claim rather than a fleet-average guess.

    Targets come from the spec ONLY (no CCSC_SLO_* env fallback here:
    a fleet-wide knob must not silently become every tenant's
    contract). Every record returned by ``tick``/``final``/
    ``raw_snapshots`` carries the ``tenant`` name, and snapshots also
    carry the declared targets (``target_p50_ms``/``target_p99_ms``)
    so a stream reader can judge "within band" offline without the
    fleet config in hand. Untenanted traffic (tenant None) and
    unknown tenants are ignored — the fleet-wide monitor owns them.
    Thread-safe via the per-monitor locks; same caller-emits
    discipline as :class:`SloMonitor`.
    """

    def __init__(self, specs=None, check_s: Optional[float] = None,
                 bounds: Sequence[float] = DEFAULT_BOUNDS_MS):
        self._mons: Dict[str, SloMonitor] = {}
        self.targets: Dict[str, Dict[float, float]] = {}
        for spec in specs or ():
            targets: Dict[float, float] = {}
            if spec.slo_p50_ms is not None and spec.slo_p50_ms > 0:
                targets[0.50] = float(spec.slo_p50_ms)
            if spec.slo_p99_ms is not None and spec.slo_p99_ms > 0:
                targets[0.99] = float(spec.slo_p99_ms)
            self.targets[spec.tenant] = targets
            self._mons[spec.tenant] = SloMonitor(
                targets, check_s=check_s, bounds=bounds
            )

    def __bool__(self) -> bool:
        return bool(self._mons)

    def observe(self, tenant: Optional[str], ms: float) -> None:
        mon = self._mons.get(tenant) if tenant is not None else None
        if mon is not None:
            mon.observe(SloMonitor.TARGET_PHASE, ms)

    def percentile(
        self, tenant: str, q: float
    ) -> Optional[float]:
        mon = self._mons.get(tenant)
        if mon is None:
            return None
        return mon.percentile(SloMonitor.TARGET_PHASE, q)

    def n(self, tenant: str) -> int:
        mon = self._mons.get(tenant)
        return mon.n(SloMonitor.TARGET_PHASE) if mon else 0

    def _stamp(self, tenant: str, recs: List[Dict]) -> List[Dict]:
        t = self.targets.get(tenant, {})
        for rec in recs:
            rec["tenant"] = tenant
            if "counts" in rec:  # histogram snapshots carry the
                # declared band so offline readers judge them alone
                rec["target_p50_ms"] = t.get(0.50)
                rec["target_p99_ms"] = t.get(0.99)
        return recs

    def tick(
        self, now: Optional[float] = None
    ) -> Tuple[List[Dict], List[Dict]]:
        breaches: List[Dict] = []
        snaps: List[Dict] = []
        for tenant in sorted(self._mons):
            br, sn = self._mons[tenant].tick(now)
            breaches.extend(self._stamp(tenant, br))
            snaps.extend(self._stamp(tenant, sn))
        return breaches, snaps

    def final(self) -> Tuple[List[Dict], List[Dict]]:
        breaches: List[Dict] = []
        snaps: List[Dict] = []
        for tenant in sorted(self._mons):
            br, sn = self._mons[tenant].final()
            breaches.extend(self._stamp(tenant, br))
            snaps.extend(self._stamp(tenant, sn))
        return breaches, snaps

    def raw_snapshots(self) -> List[Dict]:
        out: List[Dict] = []
        for tenant in sorted(self._mons):
            out.extend(
                self._stamp(
                    tenant, self._mons[tenant].raw_snapshots()
                )
            )
        return out
