"""The reconstruction serving engine.

Reconstruction is the serving workload of this framework: a ~30
iteration inpaint/demosaic solve finishes in well under 200 ms on chip
(PERF.md per-family table), yet the naive driver loop pays, PER
REQUEST, (a) a trace + XLA compile for every new observation shape
(~0.5-2 s each on CPU), (b) the full operator precompute — filter
spectra, per-frequency solve factors, dirac gradient diagonal, blur
OTF — re-derived inside the jit, and (c) one dispatch per request.
:class:`CodecEngine` removes all three:

1. **Per-bank plans** — ``models.reconstruct.build_plan`` hoists
   everything that depends only on the operator out of the request
   path; the engine builds one plan per shape bucket at startup and
   every request reuses it (the solver-plan pattern of MPAX/JAX-AMG,
   PAPERS.md). Plans live in a digest-keyed LRU (serve.registry
   PlanCache) so one engine serves MANY banks: requests route by
   ``bank_id``, bind their bank's ``d_digest`` at admission, and
   ``publish_bank`` hot-swaps a bank id to a new digest with zero
   downtime — plans are stored digest-canonical, so every
   same-geometry bank shares the bucket's ONE compiled program and a
   swap rebuilds a plan, never a program.
2. **Shape buckets + AOT warmup** — a small configured set of
   (slots, spatial) bucket shapes; requests are padded to the next
   bucket with the padding excluded through the existing mask path
   (valid-region results unchanged), and each bucket's program is
   AOT-compiled (``jax.jit(...).lower().compile()``) at engine
   startup. With the persistent XLA compilation cache wired
   (``CCSC_COMPILE_CACHE`` / ServeConfig.compile_cache) a warm engine
   restart skips backend compilation entirely.
3. **Micro-batching** — a request queue that flushes a bucket when it
   holds ``slots`` requests or its oldest request has waited
   ``max_wait_ms``; the batch rides ONE dispatch and per-request
   results are sliced back out.

Exactness: each occupied slot runs as its own n=1 solve under
``jax.vmap`` — per-request gamma heuristic, objective/PSNR traces and
tol termination (converged slots are frozen by the vmapped
while_loop's select), so a served result is BIT-IDENTICAL to a direct
``reconstruct()`` call at the same padded shape (tests/test_serve.py),
and matches the exact-shape call on the valid region to boundary
tolerance when bucket padding engaged.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..config import ProblemGeom, ServeConfig, SolveConfig
from ..utils import trace as trace_util
from . import quality as _quality_mod
from . import slo as _slo


# the directory the process-wide XLA cache is actually latched to:
# the cache is a PROCESS singleton, so the first enable_compile_cache
# wins and later calls with a different path must say so out loud —
# an engine believing it warmed cache B while every artifact landed
# in cache A is a silent cold-restart regression
_compile_cache_path: Optional[str] = None


def enable_compile_cache(path: Optional[str]) -> Optional[str]:
    """Point XLA's persistent compilation cache at ``path`` (resolving
    None through the CCSC_COMPILE_CACHE env var) so identical programs
    compiled by a previous process are LOADED, not rebuilt — the
    warm-restart half of the serving cold-start story. Returns the
    directory actually enabled, or None. Thresholds are zeroed so the
    small bucket programs qualify; best-effort (an unsupported backend
    just keeps compiling).

    The cache is per-process and latched: the first enabled path
    stays in force for the process lifetime. A second call with the
    SAME path is a cheap no-op; a second call with a DIFFERENT path
    keeps the first and warns via the obs console with both paths —
    never a silent no-op (the second engine must know its compiles
    are landing in the first engine's cache)."""
    global _compile_cache_path
    from ..utils import env as _env
    from ..utils import obs as _obs_mod

    path = path or _env.env_str("CCSC_COMPILE_CACHE") or None
    if not path:
        return None
    if _compile_cache_path is not None:
        if os.path.abspath(path) != os.path.abspath(
            _compile_cache_path
        ):
            _obs_mod.console(
                "serve: compile cache already latched to "
                f"{_compile_cache_path!r} for this process — ignoring "
                f"the new path {path!r} (the XLA cache is per-process; "
                "compiles keep landing in the first directory)",
                tier="always",
            )
        return _compile_cache_path
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        # the cache initializes AT MOST ONCE per process, latched at the
        # first compile — any compile before this point (another
        # module's jit, an eager op) locks in "no cache dir" and every
        # later write silently no-ops. Reset the latch so the dir just
        # configured actually takes effect.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
        _compile_cache_path = path
        return path
    except Exception:  # pragma: no cover - backend without cache support
        return None


def parse_mesh_shape(spec: str) -> Tuple[int, ...]:
    """Parse a serving-mesh spec string — ``"BATCH"`` or
    ``"BATCHxFREQ"`` (e.g. ``"8"``, ``"4x2"``) — into the
    ServeConfig.mesh_shape tuple. Shared by the CCSC_SERVE_MESH env
    fallback, ``apps/serve.py --mesh`` and the bench's mesh arm so
    the spec grammar cannot drift between surfaces."""
    # empty segments are NOT filtered: a truncated '4x' must refuse,
    # not silently serve a (4,) batch-only mesh under the wrong
    # ledger configuration
    parts = spec.lower().replace("*", "x").split("x")
    try:
        shape = tuple(int(p) for p in parts)
    except ValueError:
        shape = ()
    if not 1 <= len(shape) <= 2 or any(a < 1 for a in shape):
        raise ValueError(
            f"mesh spec {spec!r} is not BATCH or BATCHxFREQ with "
            "positive integer axes (e.g. '8' or '4x2')"
        )
    return shape


def _resolve_mesh(serve_cfg: ServeConfig):
    """Resolve the engine's device mesh: ServeConfig.mesh_shape, else
    the CCSC_SERVE_MESH env knob, else None (single device). Returns
    ``(mesh, shape, note)`` — mesh is a jax Mesh (batch axis first,
    'freq' second when 2-D, reusing parallel.mesh's builders), note a
    console message when a non-strict resolution fell back. With
    fewer visible devices than the mesh needs, CCSC_SERVE_MESH_STRICT
    (default on) refuses with the forced-host-device recipe; 0 falls
    back to a single-device engine instead of dying."""
    import math

    from ..utils import env as _envmod
    from ..utils import validate

    shape = serve_cfg.mesh_shape
    if shape == ():
        # explicitly single-device (the bench's baseline engine):
        # the env knob must not re-arm it
        return None, None, None
    if shape is None:
        spec = _envmod.env_str("CCSC_SERVE_MESH")
        if not spec:
            return None, None, None
        try:
            shape = parse_mesh_shape(spec)
        except ValueError as e:
            raise validate.CCSCInputError(str(e))
    import jax

    need = math.prod(shape)
    devs = jax.devices()
    if serve_cfg.mesh_devices is not None:
        missing = [i for i in serve_cfg.mesh_devices if i >= len(devs)]
        if missing:
            raise validate.CCSCInputError(
                f"mesh_devices {serve_cfg.mesh_devices} names device "
                f"index(es) {missing} but only {len(devs)} device(s) "
                "are visible"
            )
        devs = [devs[i] for i in serve_cfg.mesh_devices]
    if len(devs) < need:
        msg = (
            f"serving mesh {shape} needs {need} device(s) but only "
            f"{len(devs)} are visible — on CPU run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need}, shrink "
            "the mesh, or set CCSC_SERVE_MESH_STRICT=0 to fall back "
            "to a single-device engine"
        )
        if _envmod.env_flag("CCSC_SERVE_MESH_STRICT"):
            raise validate.CCSCInputError(msg)
        return None, None, f"serve: {msg}; serving single-device"
    from ..parallel import mesh as mesh_mod

    if len(shape) == 1:
        mesh = mesh_mod.block_mesh(devices=devs[:need])
    else:
        mesh = mesh_mod.block_freq_mesh(
            shape[0], shape[1], devices=devs[:need]
        )
    return mesh, shape, None


class BucketCold(RuntimeError):
    """Admission refusal for a bucket whose program is still
    building/fetching under STAGED warmup (ServeConfig.staged_warmup):
    the engine is live and serving its warm buckets — only this
    bucket isn't ready yet. Carries ``retry_after_s`` like the
    fleet's ``Overloaded`` (the client backs off and resubmits; the
    federation layer defers the item instead of failing it).
    Deliberately NOT an Overloaded subclass: the engine must not
    import the fleet, and the two refusals mean different things — an
    overloaded fleet has too much work, a cold bucket has a program
    in flight."""

    def __init__(self, bucket: str, retry_after_s: float):
        super().__init__(
            f"bucket {bucket} is still warming (staged warmup) — "
            f"retry in {retry_after_s:.2f}s"
        )
        self.bucket = bucket
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(RuntimeError):
    """Refusal for a request whose end-to-end deadline (absolute
    wall-clock epoch seconds, stamped at admission) expired before a
    solve slot would have been spent on it. Raised at every boundary
    a dead request can be caught at — fleet admission, the engine's
    pre-dispatch queue sweep, a durable-queue claim — with the same
    emit-outside-the-lock refusal discipline as ``Overloaded``/
    ``BucketCold``. Defined here beside ``BucketCold`` for the same
    reason: the engine must not import the fleet, and both layers
    refuse with it. Carries the stamped deadline and where the
    request died (``admission`` | ``engine`` | ``queue`` | ``claim``
    | ``dispatch``) so the refusal is auditable from the exception
    alone, matching the ``deadline_exceeded`` obs event."""

    def __init__(self, where: str, deadline: float):
        super().__init__(
            f"request deadline expired at {where} (deadline epoch "
            f"{deadline:.3f}, now past it)"
        )
        self.where = where
        self.deadline = float(deadline)


class ServedResult(NamedTuple):
    """One request's result, cropped back to the request shape."""

    recon: np.ndarray  # [*reduce, *request_spatial]
    # models.reconstruct.ReconTrace (numpy leaves). NB for a request
    # padded into a larger bucket, psnr_vals are the SOLVE-canvas
    # values (pad pixels included); ``psnr`` below is the honest
    # valid-region number.
    trace: "object"
    # final-iterate PSNR over the request's VALID region (computed
    # from the cropped reconstruction with the same psf-radius border
    # crop as common.psnr, so it matches an exact-shape solve); None
    # unless x_orig was given AND the pinned SolveConfig tracks PSNR
    # (cfg.with_psnr — a plausible-looking 0.0 from an untracked solve
    # must never masquerade as a measurement)
    psnr: Optional[float]
    bucket: str  # bucket the request dispatched in
    wait_s: float  # queue time (submit -> dispatch start)
    latency_s: float  # submit -> result ready
    z: Optional[np.ndarray]  # codes, ServeConfig.return_codes only


@dataclasses.dataclass
class _Pending:
    b: np.ndarray
    mask: Optional[np.ndarray]
    smooth_init: Optional[np.ndarray]
    x_orig: Optional[np.ndarray]
    spatial: Tuple[int, ...]
    future: Future
    t_submit: float
    # multi-tenant routing (serve.registry / serve.tenancy): the bank
    # DIGEST this request was bound to at admission — a hot-swap
    # republishing the bank id mid-queue must not retarget already
    # admitted requests — plus the request-carried identities for
    # telemetry and capture
    digest: str = ""
    bank_id: Optional[str] = None
    tenant: Optional[str] = None
    # request-level tracing (utils.trace): every request carries a
    # trace_id; parent_span is the fleet's ownership span when this
    # engine is a replica (the engine's dispatch/solve spans nest
    # under it), None for a standalone engine (which then emits the
    # root span itself)
    trace_id: Optional[str] = None
    parent_span: Optional[str] = None
    # True only for a STANDALONE submit (no fleet above): the engine
    # then owns the root span. A fleet request whose ownership span
    # was claimed away mid-hang arrives with parent_span None but
    # own_root False — its engine spans ride parentless rather than
    # fabricating a second root for the same trace.
    own_root: bool = False
    # workload-capture key (serve.capture; standalone engines only):
    # pairs this request's capture record with its outcome digest
    cap_key: Optional[str] = None
    # absolute end-to-end deadline (wall-clock epoch seconds, the
    # fleet-admission stamp); None = no deadline. The work loop
    # expires dead requests BEFORE they cost a solve slot and never
    # micro-batch-waits past the earliest in-queue deadline.
    deadline: Optional[float] = None


class _InFlight(NamedTuple):
    """One launched-but-not-fenced dispatch (pipelined dispatch,
    ServeConfig.pipeline_depth): the async program call's result
    pytree plus everything the completion half needs to fence, read
    back, and resolve futures. At depth 1 exactly one of these exists
    for exactly the span of the old synchronous dispatch."""

    key: Tuple  # ((slots, spatial), digest)
    batch: List[_Pending]
    depth_after: int
    out: object  # the in-flight ReconResult (device arrays)
    t0: float  # perf_counter at launch (batch canvas fill start)


def _bucket_name(slots: int, spatial: Tuple[int, ...]) -> str:
    return f"{slots}@" + "x".join(str(s) for s in spatial)


def pick_bucket(
    buckets: Sequence[Tuple[int, Tuple[int, ...]]],
    spatial: Sequence[int],
) -> Tuple[int, Tuple[int, ...]]:
    """Smallest bucket (of a volume-sorted table) that fits
    ``spatial`` — shared by the engine's ``bucket_for`` and the
    fleet's admission boundary (serve.ServeFleet must refuse an
    oversize request BEFORE queueing it, not after a replica takes
    it)."""
    from ..utils import validate

    spatial = tuple(int(s) for s in spatial)
    for slots, bsp in buckets:  # sorted by volume
        if len(spatial) == len(bsp) and all(
            s <= t for s, t in zip(spatial, bsp)
        ):
            return (slots, bsp)
    raise validate.CCSCInputError(
        f"request spatial {spatial} exceeds every configured "
        f"bucket {[sp for _, sp in buckets]} — add a larger "
        "bucket to ServeConfig.buckets"
    )


# THE valid-region PSNR implementation lives in serve.quality (shared
# with capture/replay verification and the probe/shadow scorers — one
# definition, so a recorded dB and a recomputed dB can never drift);
# the historical private name stays importable for existing callers.
_valid_region_psnr = _quality_mod.valid_region_psnr


class CodecEngine:
    """Pin (bank, problem, config) once; serve many requests fast.

    Construction does all the expensive work exactly once — full
    bank/geometry/config validation (utils.validate), per-bucket plan
    precompute, AOT compilation of every bucket program — so the
    per-request path is: cheap shape/finite checks, queue, one batched
    dispatch, slice. Thread-safe: ``submit`` may be called from any
    thread; a single worker thread owns dispatch order.

    Telemetry (ServeConfig.metrics_dir, utils.obs): ``run_meta``,
    per-bucket ``serve_warmup`` (compile seconds, persistent-cache
    hits), per-dispatch ``serve_dispatch`` (bucket occupancy, queue
    depth, achieved iteration rate vs the perfmodel serving bound),
    per-request ``serve_request`` (wait/latency/iterations/PSNR), the
    compile monitor's recompile tracking, and a closing summary with
    request-latency percentiles.
    """

    def __init__(
        self,
        d,
        prob,
        cfg: SolveConfig,
        serve_cfg: ServeConfig,
        blur_psf=None,
    ):
        from ..utils import obs, validate

        # close/drain machinery FIRST, before anything can fail: a
        # caller's `finally: engine.close()` must be a no-op on an
        # engine whose constructor raised, and close itself must be
        # re-entrant (a fleet drain racing a user close)
        self._close_lock = threading.Lock()
        self._close_started = False
        self._close_done = threading.Event()

        self.prob = prob
        self.cfg = cfg
        self.serve_cfg = serve_cfg
        # fleet identity: every serve_* record names its replica so
        # per-replica health is readable from a merged stream; a
        # standalone engine is replica 0
        self._replica_id = (
            0 if serve_cfg.replica_id is None
            else int(serve_cfg.replica_id)
        )
        geom: ProblemGeom = prob.geom
        self.geom = geom
        ndim_s = geom.ndim_spatial

        # ---- once-per-engine validation (hoisted off the hot path):
        # the pinned bank, config positivity, and bucket geometry are
        # checked HERE; requests only get the cheap data checks
        validate.check_solve_config(cfg)
        validate.check_filters(d, geom)
        for slots, spatial in serve_cfg.buckets:
            if len(spatial) != ndim_s:
                raise validate.CCSCInputError(
                    f"bucket spatial {spatial} has {len(spatial)} dims "
                    f"but the problem family has {ndim_s}"
                )
            if any(s < k for s, k in zip(spatial, geom.spatial_support)):
                raise validate.CCSCInputError(
                    f"bucket spatial {spatial} is smaller than the "
                    f"kernel support {geom.spatial_support}"
                )
        if blur_psf is not None:
            validate.check_finite("blur_psf", blur_psf)

        # device mesh (the big-iron replica): ServeConfig.mesh_shape
        # or CCSC_SERVE_MESH shards every bucket program's slot axis
        # (and optionally the per-frequency solves) over a mesh via
        # shard_map — resolved BEFORE telemetry/tuning so the run
        # meta and the tuned-knob key both carry the real topology
        self._mesh, self._mesh_shape, mesh_note = _resolve_mesh(
            serve_cfg
        )

        # SLO layer (serve.slo): streaming latency histograms per
        # phase + declared targets, checked on the dispatch path; a
        # breach may arm a one-shot xprof capture of the next dispatch
        from ..utils import env as _envmod

        self._slo = _slo.SloMonitor(
            _slo.resolve_targets(
                serve_cfg.slo_p50_ms, serve_cfg.slo_p99_ms
            ),
            check_s=serve_cfg.slo_check_s,
        )
        # quality plane (serve.quality): per-(bank, tenant, bucket)
        # dB histograms + per-bucket solve diagnostics, same check
        # cadence as the SLO monitor. Floors/drift live at the fleet
        # scope (the engine has no tenant specs or ledger context).
        self._quality = _quality_mod.QualityMonitor(
            check_s=serve_cfg.slo_check_s
        )
        self._slo_profile_dir = (
            serve_cfg.slo_profile_dir
            or _envmod.env_str("CCSC_SLO_XPROF_DIR")
        )
        self._profile_armed: Optional[str] = None
        self._profiled = False

        # pipelined dispatch depth (ServeConfig.pipeline_depth, env
        # fallback CCSC_SERVE_PIPELINE): how many batches the worker
        # may hold in flight before fencing the oldest. Depth 1 is
        # EXACTLY the historical launch-then-fence loop.
        depth = serve_cfg.pipeline_depth
        if depth is None:
            depth = _envmod.env_int("CCSC_SERVE_PIPELINE")
        self._pipeline_depth = max(1, int(depth or 1))

        self.cache_dir = enable_compile_cache(serve_cfg.compile_cache)
        self._run = obs.start_run(
            serve_cfg.metrics_dir,
            algorithm="serve",
            verbose=serve_cfg.verbose,
            geom=geom,
            cfg=cfg,
            # a fleet replica's run nests under the fleet's open run:
            # compile events are process-wide, so only the fleet
            # stream harvests them (once) — N replica monitors would
            # each record every sibling's compiles and cache hits
            compile_monitor=serve_cfg.replica_id is None,
            mesh=self._mesh,
            buckets=[
                {"slots": s, "spatial": list(sp)}
                for s, sp in serve_cfg.buckets
            ],
            compile_cache=self.cache_dir,
            # the replica's device topology, queryable from the
            # stream alone (obs_report SERVING)
            serve_devices=self.devices,
            serve_mesh=(
                list(self._mesh_shape) if self._mesh_shape else None
            ),
            problem={
                "pad": prob.pad,
                "dirac": prob.dirac,
                "data_term": prob.data_term,
            },
        )
        if mesh_note:
            self._run.console(mesh_note, tier="always")

        self._capture = None
        self._cap_seq = 0
        # per-engine key salt: a recorder reopened on the same capture
        # dir (engine restart) must never reuse a previous engine's
        # keys — read_workload pairs outcomes by key, and a collision
        # would weld run 2's request to run 1's outcome digest
        self._cap_prefix = f"req-{trace_util.new_trace_id()[:8]}"
        try:
            if serve_cfg.tune != "off":
                # startup knob resolution (tune/): one pinned config
                # serves every bucket, so the shape key is the LARGEST
                # bucket (the engine's dominant program); the numerics
                # guard runs before an arm first configures this chip,
                # a failing arm is demoted and the next-best applied.
                # tune='off' (default) keeps the given config verbatim
                # — served results stay bit-identical to direct
                # reconstruct() calls.
                from ..tune import autotune, store as tune_store

                cfg, self._tune_picked = autotune.resolve_solve(
                    # the serving engine's tune switch lives on
                    # ServeConfig; the pinned SolveConfig rides with
                    # tune='off' so direct reconstruct() callers of the
                    # same config never re-resolve
                    dataclasses.replace(cfg, tune=serve_cfg.tune),
                    geom,
                    serve_cfg.buckets[-1][1],
                    workload=tune_store.solve_workload(geom),
                    store=tune_store.TunedStore(serve_cfg.tune_store),
                    emit=self._run.event,
                    # a mesh engine resolves under its own store key:
                    # a single-device winner is a measurement of a
                    # DIFFERENT program than the shard_map'd bucket
                    mesh=self._mesh_shape,
                )
                self.cfg = cfg
            else:
                self._tune_picked = None
            # the resolved knob dict every request is served under —
            # recorded per bucket warmup so the stream says which arm
            # produced which program (obs_report SERVING section).
            # The device topology rides in it too: a mesh engine's
            # serving records (and their perf-ledger knob digest) are
            # a different configuration than a single-device engine's.
            from ..tune.space import arm_knob_dict

            self._knob_dict = dict(
                arm_knob_dict(cfg, "solve"),
                tune=serve_cfg.tune,
                tuned=self._tune_picked is not None,
            )
            if self._mesh_shape:
                # only mesh engines carry the topology keys: a
                # single-device engine's knob dict (and therefore its
                # perf-ledger knob digest / history key) stays exactly
                # the pre-mesh one
                self._knob_dict["devices"] = self.devices
                self._knob_dict["mesh"] = "x".join(
                    str(a) for a in self._mesh_shape
                )
                # the DECLARED collective budget (analysis.comms) —
                # static per topology, so it keys artifact
                # fingerprints and ledger history stably; MEASURED
                # counts ride the comm_audit event, the artifact
                # manifest, and the bench record instead
                from ..analysis import comms as _comms

                self._knob_dict["comm_budget"] = (
                    _comms.declared_budget(self._mesh_shape)
                )
            if self._pipeline_depth != 1:
                # only a non-default depth keys the knob dict: depth-1
                # engines keep their historical knob digest (and so
                # their perf-ledger history keys) bit-for-bit
                self._knob_dict["pipeline"] = self._pipeline_depth
            if serve_cfg.replica_id is None:
                # standalone engines capture their own workload; a
                # fleet replica's stream is captured ONCE at the
                # fleet's admission boundary instead. Built AFTER
                # tune resolution: the recorded solve params must be
                # the ones requests are actually served under, or a
                # replay pinned to them fails bit-parity spuriously.
                from . import capture as _capture_mod

                cap_dir = _capture_mod.resolve_capture_dir(
                    serve_cfg.capture_dir
                )
                if cap_dir:
                    self._capture = _capture_mod.WorkloadRecorder(
                        cap_dir,
                        emit=self._emit,
                        meta={
                            "source": "serve_engine",
                            "buckets": [
                                {"slots": s, "spatial": list(sp)}
                                for s, sp in serve_cfg.buckets
                            ],
                            "geom": {
                                "spatial_support": list(
                                    geom.spatial_support
                                ),
                                "num_filters": geom.num_filters,
                            },
                            "solve": {
                                "max_it": cfg.max_it,
                                "tol": cfg.tol,
                                "lambda_residual": cfg.lambda_residual,
                                "lambda_prior": cfg.lambda_prior,
                            },
                            "knobs": self._knob_dict,
                        },
                    )
            self._build(d, prob, cfg, serve_cfg, blur_psf)
        except BaseException:
            # a failed construction (bad blur rank, OOM compiling an
            # oversized bucket) must not leak the open telemetry run or
            # leave the process-global CompileMonitor installed — later
            # runs would double-count compiles against it. The close
            # latch is consumed too: a later close() is a clean no-op.
            with self._close_lock:
                self._close_started = True
            self._close_done.set()
            if self._capture is not None:
                try:
                    self._capture.close(status_note="init_failed")
                except Exception:
                    pass
            self._run.close(status="error")
            raise

    def _build(self, d, prob, cfg, serve_cfg, blur_psf):
        from ..models.reconstruct import (
            ReconResult,
            ReconTrace,
            SolveExtras,
            _reconstruct_impl,
            build_plan,
            plan_freq_specs,
        )

        import jax
        import jax.numpy as jnp

        geom = self.geom
        self._jnp = jnp
        reduce_shape = geom.reduce_shape
        mesh = self._mesh
        has_freq = mesh is not None and "freq" in mesh.axis_names
        nf = mesh.shape["freq"] if has_freq else 1

        def _slot(b1, m1, s1, x1, plan):
            # one request = one n=1 solve: per-request gamma,
            # objective/PSNR traces, and tol termination — the vmapped
            # while_loop freezes converged slots, so slot results are
            # bit-identical to a standalone reconstruct() call. On a
            # 2-D mesh the slot's per-frequency solves additionally
            # shard over the 'freq' axis; the plan's solve factors
            # arrive as this device's own bin shard (kern_presliced:
            # the program's in_specs partition the kern leaves, see
            # plan_freq_specs) — same bits per bin, no replicated
            # kern residency and no in-program slice.
            return _reconstruct_impl(
                b1[None], None, prob, cfg, m1[None], s1[None], None,
                x1[None], plan=plan,
                freq_axis_name="freq" if has_freq else None,
                num_freq_shards=nf,
                kern_presliced=has_freq,
            )

        def _vmapped(bb, mm, ss, xx, plan):
            return jax.vmap(_slot, in_axes=(0, 0, 0, 0, None))(
                bb, mm, ss, xx, plan
            )

        # result/trace out-specs of the mesh programs: every result
        # leaf carries the slot axis first (vmap), sharded like the
        # inputs; traces are per-slot too, so nothing is replicated
        # back. With solve diagnostics on, the trace carries the
        # extras subtree (per-slot scalars, sharded the same way);
        # off, the None default is an empty pytree subtree and the
        # historical spec matches exactly.
        def _mesh_out_specs(bs):
            return ReconResult(
                bs,
                bs,
                ReconTrace(
                    bs, bs, bs, bs,
                    SolveExtras(bs, bs, bs)
                    if cfg.track_diagnostics
                    else None,
                ),
            )

        self._plan_specs_fn = None
        if mesh is None:
            _bucket_program = _vmapped
        elif not has_freq:
            # the batch-mesh bucket program: the slot axis sharded
            # over the mesh's only axis via shard_map — each device
            # runs the SAME vmap-of-independent-n=1-solves body over
            # its slots/batch shard, with the plan (spectra + solve
            # factors) replicated. No cross-slot collectives exist in
            # the body — the program lowers to ZERO collective HLO
            # ops, enforced by the analysis.comms audit at warmup —
            # so per-slot results are bit-identical to the
            # single-device program's (tests/test_serve_mesh.py).
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import shard_map

            axis = mesh.axis_names[0]
            bs, rep = P(axis), P()
            _bucket_program = shard_map(
                _vmapped,
                mesh=mesh,
                in_specs=(bs, bs, bs, bs, rep),
                out_specs=_mesh_out_specs(bs),
                # the while_loop carry mixes varying (data-derived)
                # and invarying (zero-init) components; skip vma
                # tracking like the learner's sharded solver
                check_vma=False,
            )
        else:
            # the (batch, freq) bucket program is built PER BUCKET
            # (self._program_fn_for, called from _warm_bucket where a
            # concrete plan exists): its in_specs carry the plan's
            # own partition-spec tree (plan_freq_specs — kern leaves
            # sharded by frequency bin), and a spec tree's aux data
            # (the plan's FreqGeom) is bucket-specific. Each device's
            # bin slice of the solve factors stays RESIDENT across
            # dispatches; the program's only collective is the single
            # tiled all_gather at the z-solve tail (budget 1,
            # enforced by the analysis.comms audit).
            _bucket_program = None
            self._plan_specs_fn = plan_freq_specs

        if _bucket_program is not None:
            # the jitted program carries a STABLE name so the compile
            # monitor's events are filterable by program: "a
            # warm-store startup performed ZERO bucket compiles" is
            # asserted from the obs stream by matching fun_name
            # against this
            with contextlib.suppress(AttributeError):
                _bucket_program.__name__ = "ccsc_bucket_program"
        self._vmapped_fn = _vmapped
        self._mesh_out_specs_fn = _mesh_out_specs
        # the slot-axis sharding every per-dispatch data canvas is
        # uploaded onto (mesh engines): device_put straight to the
        # program's in_specs so the async dispatch starts its
        # host->device transfer immediately — under pipelined dispatch
        # batch N+1's upload overlaps batch N's solve
        if mesh is not None:
            from jax.sharding import (
                NamedSharding as _NS,
                PartitionSpec as _P,
            )

            self._data_sharding = _NS(mesh, _P(mesh.axis_names[0]))
        else:
            self._data_sharding = None

        # ---- per-bucket plans + AOT-compiled programs --------------
        # Multi-bank serving (serve.registry): plans live in a
        # digest-keyed LRU (evict-and-rebuild on miss), the bank
        # bytes are retained for rebuilds, and requests bind a digest
        # at admission via the bank_id route table. The compiled
        # bucket PROGRAM is shared across banks — plans are stored
        # with the digest canonicalized out of the pytree aux data
        # (the reconstruct(plan=...) jit-cache discipline), so a
        # hot-swap republishing a bank id rebuilds a plan, never a
        # program.
        from ..utils import env as _envmod
        from ..utils import perfmodel as _perfmodel
        from . import artifacts as _artifacts
        from . import registry as _registry

        self._buckets: List[Tuple[int, Tuple[int, ...]]] = list(
            serve_cfg.buckets
        )
        self._plan_cfg = cfg
        self._blur_psf = blur_psf
        self._build_plan = build_plan
        default_digest = _registry.bank_digest(d)
        self._banks: Dict[str, object] = {default_digest: d}
        self._routes: Dict[Optional[str], str] = {
            None: default_digest
        }
        self._default_digest = default_digest
        self._plan_cache = _registry.PlanCache()
        self._programs: Dict[Tuple, object] = {}
        self._bucket_program_fn = _bucket_program
        # per-bucket measured collective counts (analysis.comms audit
        # at warmup; surfaced via the comm_counts property and the
        # bench's ledger rows)
        self._comm_counts: Dict[Tuple, Dict[str, int]] = {}

        # ---- micro-batch queue (BEFORE warmup: under staged warmup
        # the engine serves its hottest bucket while cold programs
        # still build, so the queue and worker must already exist
        # when the first bucket comes warm) --------------------------
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # keyed (bucket_key, digest): one bank's batch rides one
        # dispatch against one plan; lanes appear lazily as banks
        # receive traffic (bounded by banks x buckets)
        self._pending: Dict[Tuple, List[_Pending]] = {
            ((s, sp), default_digest): [] for s, sp in self._buckets
        }
        self._n_pending = 0
        # digests of the batches the worker has launched but not yet
        # released (one list entry PER in-flight batch — pipelined
        # dispatch can hold pipeline_depth of them, possibly the same
        # digest twice): retire_bank must refuse them — the worker
        # consults the plan after releasing the queue lock, and a
        # retire in that window would fail the whole batch
        self._dispatch_digests: List[str] = []
        self._closed = False
        # live flush deadline (set_max_wait_ms): the fleet's overload
        # ladder sheds micro-batch waiting without rebuilding engines
        self._max_wait_s = serve_cfg.max_wait_ms / 1e3
        self._last_it_rate = 0.0  # newest dispatch's measured it/s
        self._n_dispatches = 0
        self._occupancy_sum = 0.0
        self._worker = threading.Thread(
            target=self._work_loop, name="ccsc-serve", daemon=True
        )
        self._worker.start()

        # ---- staged warmup + compiled-artifact store ---------------
        # Pre-warmed elasticity (serve.artifacts): each bucket's
        # program is FETCHED from the shared artifact store (keyed by
        # program fingerprint x chip x mesh) instead of compiled when
        # a matching executable exists, and whatever had to be
        # live-compiled is published back so the next joining host
        # fetches it. Staged mode warms hot-to-cold and returns from
        # the constructor after the FIRST bucket is serveable; the
        # rest build/fetch in a background thread while submits to
        # still-cold buckets get a BucketCold retry-after refusal.
        self._chip = _perfmodel.detect_chip()
        staged = serve_cfg.staged_warmup
        if staged is None:
            staged = _envmod.env_flag("CCSC_SERVE_STAGED")
        # lazy engines (aot_warmup off) have nothing to stage: every
        # bucket is "warm" immediately and compiles on first use
        self._staged = bool(staged) and bool(serve_cfg.aot_warmup)
        store_dir = _artifacts.resolve_artifact_dir(
            serve_cfg.artifact_store
        )
        self._artifacts = (
            _artifacts.ArtifactStore(store_dir, emit=self._emit)
            if store_dir and serve_cfg.aot_warmup
            else None
        )
        self._artifact_publish = _envmod.env_flag(
            "CCSC_ARTIFACT_PUBLISH"
        )
        rank_dir = serve_cfg.warm_rank_capture
        if rank_dir == "":
            rank_dir = None
        else:
            rank_dir = (
                rank_dir
                or _envmod.env_str("CCSC_WARM_RANK_CAPTURE")
                or None
            )
        self._warm_order = _artifacts.rank_buckets(
            self._buckets,
            declared=serve_cfg.warm_order,
            capture_dir=rank_dir,
        )
        self._warm: set = set()
        self._stage_s: List[float] = []
        self._warm_t0 = time.perf_counter()
        self._first_ready_s: Optional[float] = None
        self._n_fetched = 0
        self._n_compiled = 0
        self._warm_error: Optional[BaseException] = None
        self._warm_stop = threading.Event()
        self._warm_thread: Optional[threading.Thread] = None
        self._cold_retry_floor = _envmod.env_float(
            "CCSC_BUCKET_COLD_RETRY_S"
        )
        self._cold_emit_t: Dict[Tuple, float] = {}

        n_stages = len(self._warm_order)
        # the hottest bucket warms SYNCHRONOUSLY — a constructed
        # engine can always serve SOMETHING
        self._warm_bucket(self._warm_order[0], 1, n_stages)
        if self._staged and n_stages > 1:
            self._warm_thread = threading.Thread(
                target=self._warm_loop,
                name="ccsc-serve-warmup",
                daemon=True,
            )
            self._warm_thread.start()
        else:
            for i, key in enumerate(self._warm_order[1:], start=2):
                self._warm_bucket(key, i, n_stages)
            self._finish_warmup()

    def _warm_loop(self):
        """Background half of staged warmup: build/fetch the cold
        buckets hot-to-cold while the engine is already serving. A
        failed stage poisons only the REMAINING cold buckets (their
        submits fail fast instead of retrying forever); everything
        already warm keeps serving."""
        n_stages = len(self._warm_order)
        for i, key in enumerate(self._warm_order[1:], start=2):
            if self._warm_stop.is_set():
                return
            try:
                self._warm_bucket(key, i, n_stages)
            except BaseException as e:
                self._warm_error = e
                self._emit(
                    "serve_error",
                    error=(
                        "staged warmup failed at bucket "
                        f"{_bucket_name(*key)}: {e}"
                    )[:300],
                )
                self._run.console(
                    "serve: staged warmup FAILED at bucket "
                    f"{_bucket_name(*key)} — cold buckets will refuse "
                    f"requests: {e}",
                    tier="always",
                )
                return
        self._finish_warmup()

    def _program_fn_for(self, plan):
        """The bucket-program callable serving ``plan``'s bucket: the
        shared module-level program when the in_specs don't depend on
        the plan (single-device vmap; batch-only mesh with the plan
        replicated), else a per-bucket (batch, freq) shard_map whose
        in_specs carry this plan's own bin-sharded spec tree
        (plan_freq_specs) — the spec tree's aux data is the plan's
        FreqGeom, so it cannot be built before a concrete plan
        exists. Same-bucket plans of OTHER banks share the program:
        their pytrees are aux-identical (d_digest canonicalized)."""
        if self._plan_specs_fn is None:
            return self._bucket_program_fn
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import shard_map

        bs = P(self._mesh.axis_names[0])
        fn = shard_map(
            self._vmapped_fn,
            mesh=self._mesh,
            in_specs=(bs, bs, bs, bs, self._plan_specs_fn(plan)),
            out_specs=self._mesh_out_specs_fn(bs),
            check_vma=False,
        )
        with contextlib.suppress(AttributeError):
            fn.__name__ = "ccsc_bucket_program"
        return fn

    def _place_plan(self, plan):
        """Pre-place a plan onto the (batch, freq) mesh per its
        bin-sharded spec tree: each device holds only its own
        frequency bins of the solve factors (spectra replicated for
        the FFT boundary), resident across dispatches. No-op for
        single-device and batch-only engines, whose programs place
        the replicated plan themselves."""
        if self._plan_specs_fn is None:
            return plan
        from ..parallel.mesh import place_by_specs

        return place_by_specs(
            plan, self._plan_specs_fn(plan), self._mesh
        )

    def _audit_program(self, program, key, name):
        """The collective-budget gate (analysis.comms) on one AOT
        bucket program: count collective op definitions in the stable
        HLO, record the verdict (``comm_audit`` event + the
        comm_counts property the bench reads), and — enforcement on
        (CCSC_COMM_BUDGET_ENFORCE, default) — refuse an overrun with
        CommBudgetError BEFORE the program can serve. Single-device
        engines and lazily-jitted programs (no stable text yet) skip
        silently; returns the counts dict otherwise."""
        if self._mesh is None:
            return None
        from ..analysis import comms as _comms

        counts = _comms.program_counts(program)
        if counts is None:
            return None
        budget = _comms.declared_budget(self._mesh_shape)
        ok = counts["total"] <= budget
        self._comm_counts[key] = dict(counts)
        self._emit(
            "comm_audit",
            bucket=name,
            mesh="x".join(str(a) for a in self._mesh_shape),
            budget=budget,
            total=counts["total"],
            ok=ok,
            **{k: v for k, v in counts.items() if k != "total"},
        )
        _comms.check(
            counts, self._mesh_shape, bucket=name, budget=budget
        )
        return counts

    @property
    def comm_counts(self) -> Dict[Tuple, Dict[str, int]]:
        """Measured per-bucket collective counts from the warmup
        audit (empty for single-device engines / lazy programs)."""
        return dict(self._comm_counts)

    def _warm_bucket(self, key, stage: int, n_stages: int):
        """Make ONE bucket serveable: build its plan, then fetch its
        AOT executable from the artifact store (or live-compile and
        publish), install it, and mark the bucket warm. Emits
        ``artifact_fetch`` / ``serve_warmup`` / ``warmup_stage`` with
        the per-bucket source: fetched | compiled | cache-hit (the
        persistent XLA cache satisfied the compile) | lazy."""
        import jax

        jnp = self._jnp
        serve_cfg = self.serve_cfg
        slots, spatial = key
        name = _bucket_name(slots, spatial)
        t0 = time.perf_counter()
        plan = self._build_plan(
            self._banks[self._default_digest],
            self.prob,
            self._plan_cfg,
            spatial,
            blur_psf=self._blur_psf,
            # mesh compatibility is refused at plan build — batch
            # axis vs this bucket's slots, freq axis vs the FFT
            # domain — with the whole bucket table in the error
            mesh_shape=self._mesh_shape,
            slots=slots,
            buckets=self._buckets,
        )
        # digest-canonical storage: all same-geometry banks share
        # one compiled program per bucket (aux-data equality)
        plan = dataclasses.replace(plan, d_digest="")
        # bin-sharded residency: on a (batch, freq) mesh the plan's
        # solve factors land on the mesh NOW (each device holds its
        # own frequency bins), so dispatches pay no resharding
        plan = self._place_plan(plan)
        self._plan_cache.put(self._default_digest, key, plan)

        from . import artifacts as _artifacts

        program = None
        source = "lazy"
        fetch_s = None
        compile_s = None
        fp = akey = None
        if serve_cfg.aot_warmup and self._artifacts is not None:
            fp = _artifacts.program_fingerprint(
                bucket=(slots, spatial),
                geom=self.geom,
                problem={
                    "pad": self.prob.pad,
                    "dirac": self.prob.dirac,
                    "data_term": self.prob.data_term,
                },
                knobs=self._knob_dict,
                mesh_shape=self._mesh_shape,
                plan=plan,
            )
            akey = _artifacts.artifact_key(
                fp, self._chip, self._mesh_shape
            )
            tf = time.perf_counter()
            blob, status = self._artifacts.fetch(
                akey, fingerprint=fp, chip=self._chip
            )
            if blob is not None:
                try:
                    program = _artifacts.deserialize_program(blob)
                    source = "fetched"
                    self._n_fetched += 1
                except Exception:
                    # a foreign/torn executable must never serve:
                    # fall back to live compile (which republishes,
                    # healing the store)
                    program = None
                    status = "deserialize_error"
            fetch_s = round(time.perf_counter() - tf, 4)
            self._emit(
                "artifact_fetch",
                key=akey,
                status=status,
                bucket=name,
                fetch_s=fetch_s,
                store=self._artifacts.path,
            )
        if program is not None:
            # a FETCHED program is re-audited locally: the publisher
            # audited it too, but the budget knobs are this host's
            # (an overrun refuses before install — the store must not
            # be able to smuggle an over-communicating program past
            # the gate)
            self._audit_program(program, key, name)
        if program is None and serve_cfg.aot_warmup:
            fn = jax.jit(self._program_fn_for(plan))
            shp = jax.ShapeDtypeStruct(
                (slots, *self.geom.reduce_shape, *spatial),
                jnp.float32,
            )
            mon = self._run.compile_monitor
            hits0 = mon.cache_hits if mon else 0
            tc = time.perf_counter()
            program = fn.lower(shp, shp, shp, shp, plan).compile()
            compile_s = round(time.perf_counter() - tc, 4)
            # "cache-hit": the persistent XLA cache satisfied the
            # backend compile — a warm RESTART, distinct from both a
            # store fetch and a true cold compile in the stream
            source = (
                "cache-hit"
                if mon and mon.cache_hits > hits0
                else "compiled"
            )
            if source == "compiled":
                self._n_compiled += 1
            # collective-budget gate (analysis.comms): audited BEFORE
            # publish/install — a program over its declared budget
            # must neither serve nor enter the shared store
            counts = self._audit_program(program, key, name)
            if self._artifacts is not None and self._artifact_publish:
                try:
                    payload = _artifacts.serialize_program(program)
                    self._artifacts.publish(
                        akey,
                        payload,
                        fingerprint=fp,
                        chip=self._chip,
                        mesh_shape=self._mesh_shape,
                        bucket=name,
                        collectives=counts,
                    )
                except Exception as e:
                    # best-effort: a store that cannot serialize this
                    # backend's executable must not fail warmup
                    self._run.console(
                        f"serve: artifact publish failed for {name}: "
                        f"{e}",
                        tier="always",
                    )
        elif program is None:
            program = jax.jit(self._program_fn_for(plan))
        dt = time.perf_counter() - t0
        with self._cv:
            self._programs[key] = program
            self._warm.add(key)
            ready_s = time.perf_counter() - self._warm_t0
            if self._first_ready_s is None:
                self._first_ready_s = ready_s
            self._stage_s.append(dt)
            self._cv.notify_all()
        self._emit(
            "serve_warmup",
            bucket=name,
            aot=bool(serve_cfg.aot_warmup),
            source=source,
            warmup_s=round(dt, 4),
            fetch_s=fetch_s,
            compile_s=compile_s,
            devices=self.devices,
            digest=self._default_digest,
            mesh=(
                list(self._mesh_shape) if self._mesh_shape
                else None
            ),
            # the resolved knob dict, not just the bucket shape:
            # the stream must say which arm this program serves
            # under (a tuned engine and a default engine emit
            # otherwise-identical warmup events)
            knobs=self._knob_dict,
        )
        self._emit(
            "warmup_stage",
            bucket=name,
            stage=stage,
            n_stages=n_stages,
            source=source,
            ready_s=round(ready_s, 4),
        )

    def _finish_warmup(self):
        """Close out warmup (both modes): the ``serve_ready`` event +
        console line, and the join-to-first-request perf-ledger
        record — the elasticity quantity ``perf_gate`` holds steady
        per (chip, mesh, bucket-set)."""
        mon = self._run.compile_monitor
        total = time.perf_counter() - self._warm_t0
        first = (
            self._first_ready_s
            if self._first_ready_s is not None
            else total
        )
        self._emit(
            "serve_ready",
            n_buckets=len(self._buckets),
            warmup_s=round(total, 4),
            first_ready_s=round(first, 4),
            staged=self._staged,
            n_fetched=self._n_fetched,
            n_compiled=self._n_compiled,
            persistent_cache_hits=mon.cache_hits if mon else None,
            devices=self.devices,
            mesh=(
                list(self._mesh_shape) if self._mesh_shape else None
            ),
            knobs=self._knob_dict,
        )
        self._run.console(
            f"serve: {len(self._buckets)} bucket(s) ready in "
            f"{total:.2f}s (first serveable {first:.2f}s, "
            f"{self._n_fetched} fetched, {self._n_compiled} compiled)"
            + (
                f" (mesh {'x'.join(str(a) for a in self._mesh_shape)}"
                f", {self.devices} devices)"
                if self._mesh_shape
                else ""
            )
            + (
                f" (compile cache {self.cache_dir})"
                if self.cache_dir
                else ""
            )
            + (
                f" (artifact store {self._artifacts.path})"
                if self._artifacts is not None
                else ""
            ),
            tier="brief",
        )
        # join-to-first-request as a ledger configuration: replica 0
        # (or a standalone engine) records once per startup — N
        # replicas must not append N copies of the same join. Lazy
        # engines skip it: "first serveable" without a program built
        # is not the elasticity quantity.
        if (
            self.serve_cfg.replica_id in (None, 0)
            and self.serve_cfg.aot_warmup
        ):
            from ..analysis import ledger as _ledger

            try:
                _ledger.append_warmup_record(
                    chip=self._chip,
                    buckets=self._buckets,
                    join_s=first,
                    mesh_shape=self._mesh_shape,
                    knobs=self._knob_dict,
                    staged=self._staged,
                    artifact_store=self._artifacts is not None,
                    n_compiled=self._n_compiled,
                )
            except Exception as e:  # pragma: no cover - ledger I/O
                self._run.console(
                    f"serve: warmup ledger append failed: {e}",
                    tier="always",
                )

    def bucket_warm(self, key) -> bool:
        """Is ``key``'s (slots, spatial) program installed and
        serveable? The fleet's admission boundary asks this before
        queueing work for a replica set that is still staging."""
        slots, spatial = key
        key = (int(slots), tuple(int(s) for s in spatial))
        with self._cv:
            return key in self._warm

    def warmup_eta_s(self) -> float:
        """Retry-after hint for a cold bucket: the mean measured
        per-stage warmup time so far, floored by
        CCSC_BUCKET_COLD_RETRY_S."""
        with self._cv:
            stages = list(self._stage_s)
        eta = (sum(stages) / len(stages)) if stages else 0.0
        return max(float(self._cold_retry_floor), eta)

    # ------------------------------------------------------------------
    def _emit(self, type_: str, **fields) -> None:
        """Every serve_* record rides through here so it carries the
        replica identity — the per-replica health contract a lint test
        enforces (bypassing this helper for a serve event is a
        regression)."""
        self._run.event(type_, replica_id=self._replica_id, **fields)

    def _emit_span(self, type_: str, **fields) -> None:
        """Span-event adapter for utils.trace: ``_emit`` stamps this
        engine's replica_id itself, so the helper-supplied value is
        dropped rather than collide."""
        fields.pop("replica_id", None)
        self._emit(type_, **fields)

    def bucket_for(self, spatial: Sequence[int]) -> Tuple[int, Tuple[int, ...]]:
        """Smallest configured bucket that fits ``spatial``."""
        return pick_bucket(self._buckets, spatial)

    def submit(
        self, b, mask=None, smooth_init=None, x_orig=None,
        bank_id: Optional[str] = None,
        tenant: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        _validated: bool = False,
        _trace: Optional[Tuple[str, Optional[str]]] = None,
        _digest: Optional[str] = None,
        _deadline: Optional[float] = None,
    ) -> "Future[ServedResult]":
        """Enqueue one observation [*reduce, *spatial] (no batch axis);
        returns a Future resolving to :class:`ServedResult`. Only the
        cheap per-request checks run here (utils.validate
        check_serve_request) — the operator was validated at
        construction. ``bank_id`` routes the request to a published
        bank (:meth:`add_bank` / :meth:`publish_bank`; None = the
        engine's default bank); the request binds that bank's DIGEST
        here, so a concurrent hot-swap never retargets admitted work.
        ``tenant`` rides through to telemetry and capture.
        ``_validated`` is fleet-internal: the fleet runs the identical
        checks (including the O(N) finiteness scans) at admission and
        canonicalizes the arrays to float32, so its dispatch — and
        every requeue retry — must not pay them again per ownership.
        ``_trace`` is the fleet's span context ``(trace_id,
        parent_span_id)``: the engine's dispatch/solve spans nest
        under the fleet's ownership span so a request's story survives
        replica handoffs; a standalone submit gets a fresh trace_id
        and the engine emits the root span itself. ``_digest`` is the
        fleet's admission-time digest binding — the fleet owns the
        routing table, the engine just serves the named plan.
        ``deadline_ms`` bounds the request end-to-end (relative,
        converted to an absolute wall-clock stamp here); ``_deadline``
        is the fleet/federation-internal ABSOLUTE stamp from the
        original admission, which cross-layer hand-offs must carry
        unchanged so the budget shrinks instead of resetting. An
        already-expired request is refused with
        :class:`DeadlineExceeded` before it costs anything."""
        from ..utils import validate

        if not _validated:
            validate.check_serve_request(
                b, self.geom, mask=mask, smooth_init=smooth_init,
                x_orig=x_orig,
            )
        deadline = _deadline
        if deadline is None and deadline_ms is not None:
            deadline = time.time() + float(deadline_ms) / 1e3
        if deadline is not None and time.time() >= deadline:
            self._emit(
                "deadline_exceeded", where="engine",
                deadline=round(deadline, 3),
            )
            raise DeadlineExceeded("engine", deadline)
        if _trace is None:
            trace_id, parent_span, own_root = (
                trace_util.new_trace_id(), None, True,
            )
        else:
            (trace_id, parent_span), own_root = _trace, False
        spatial = tuple(int(s) for s in b.shape[self.geom.ndim_reduce:])
        key = self.bucket_for(spatial)
        p = _Pending(
            b=np.asarray(b, np.float32),
            mask=None if mask is None else np.asarray(mask, np.float32),
            smooth_init=(
                None
                if smooth_init is None
                else np.asarray(smooth_init, np.float32)
            ),
            x_orig=(
                None if x_orig is None else np.asarray(x_orig, np.float32)
            ),
            spatial=spatial,
            future=Future(),
            t_submit=time.perf_counter(),
            bank_id=bank_id,
            tenant=tenant,
            trace_id=trace_id,
            parent_span=parent_span,
            own_root=own_root,
            deadline=deadline,
        )
        cold_retry: Optional[float] = None
        with self._cv:
            if self._closed or self._close_started:
                raise RuntimeError("engine is closed")
            if key not in self._warm:
                # staged warmup: THIS bucket's program is still
                # building/fetching — refuse only it (retry-after),
                # never block the whole engine. A failed warmup
                # poisons the remaining cold buckets instead: their
                # requests must fail fast, not retry forever.
                if self._warm_error is not None:
                    raise RuntimeError(
                        f"bucket {_bucket_name(*key)} will never "
                        "warm — staged warmup failed: "
                        f"{self._warm_error}"
                    )
                stages = self._stage_s
                cold_retry = max(
                    float(self._cold_retry_floor),
                    (sum(stages) / len(stages)) if stages else 0.0,
                )
            else:
                # digest binds UNDER the queue lock: publish_bank
                # flips routes and retires stale digests under the
                # same lock, so an admission can never bind a digest
                # a concurrent retire just dropped
                if _digest is not None:
                    digest = _digest
                    if digest not in self._banks:
                        raise validate.CCSCInputError(
                            f"bank digest {digest!r} is not published "
                            "on this engine — publish the bank "
                            "(add_bank) before routing requests to it"
                        )
                else:
                    digest = self._routes.get(bank_id)
                    if digest is None:
                        raise validate.CCSCInputError(
                            f"unknown bank id {bank_id!r} — "
                            "published: "
                            f"{sorted(k for k in self._routes if k)} "
                            "(default bank routes as bank_id=None)"
                        )
                p.digest = digest
                if self._capture is not None:
                    self._cap_seq += 1
                    p.cap_key = (
                        f"{self._cap_prefix}-{self._cap_seq:08d}"
                    )
                self._pending.setdefault((key, digest), []).append(p)
                self._n_pending += 1
                self._cv.notify()
        if cold_retry is not None:
            # emit OUTSIDE the queue lock, rate-limited per bucket —
            # a tight client retry loop must not flood the stream
            now = time.monotonic()
            if now - self._cold_emit_t.get(key, 0.0) >= 1.0:
                self._cold_emit_t[key] = now
                self._emit(
                    "bucket_cold",
                    bucket=_bucket_name(*key),
                    retry_after_s=round(cold_retry, 3),
                )
            raise BucketCold(_bucket_name(*key), cold_retry)
        if self._capture is not None and p.cap_key is not None:
            # record OUTSIDE the queue lock: sha256 + the segment
            # append must not serialize submitters against dispatch
            self._capture.record_submit(
                p.cap_key, trace_id, p.b, mask=p.mask,
                smooth_init=p.smooth_init, x_orig=p.x_orig,
                bucket=_bucket_name(*key),
                bank_id=bank_id, tenant=tenant,
            )
        return p.future

    def reconstruct(
        self, b, mask=None, smooth_init=None, x_orig=None,
        bank_id: Optional[str] = None,
        tenant: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> ServedResult:
        """Synchronous submit-and-wait."""
        return self.submit(
            b, mask=mask, smooth_init=smooth_init, x_orig=x_orig,
            bank_id=bank_id, tenant=tenant,
        ).result(timeout=timeout)

    def serve_many(self, requests, timeout=None) -> List[ServedResult]:
        """Submit an iterable of request dicts (keys b/mask/
        smooth_init/x_orig) and wait for all results, in order."""
        futs = [self.submit(**req) for req in requests]
        return [f.result(timeout=timeout) for f in futs]

    # ------------------------------------------------------------------
    def _release_digest(self, digest: str) -> None:
        """Drop ONE in-flight reference to ``digest`` (the worker
        holds one per launched batch; retire_bank refuses digests
        with live references). Idempotent per reference: the
        completion path releases early — the moment the plan is no
        longer consulted — and the worker's backstop release on the
        error paths then finds nothing to remove."""
        with self._cv:
            try:
                self._dispatch_digests.remove(digest)
            except ValueError:
                pass

    def _work_loop(self):
        # pipelined dispatch (ServeConfig.pipeline_depth): up to
        # ``depth`` launched-but-unfenced dispatches ride in this
        # deque, oldest first. Launching batch N+1 is pure host work
        # plus an async device dispatch, so it overlaps batch N's
        # in-flight solve; the fence (and every trace readback behind
        # it) happens in _complete, off the launch critical path.
        # Depth 1 degenerates to launch-then-immediately-complete —
        # the classic synchronous worker, event for event.
        inflight: List[_InFlight] = []
        depth = self._pipeline_depth
        while True:
            expired: List[_Pending] = []
            key = None
            with self._cv:
                while (
                    not self._closed
                    and self._n_pending == 0
                    and not inflight
                ):
                    self._cv.wait()
                if (
                    self._closed
                    and self._n_pending == 0
                    and not inflight
                ):
                    return
                # read under the lock, every pass: set_max_wait_ms
                # (overload rung 1) retargets the deadline live, and
                # its notify lands us back here with the fresh value
                max_wait = self._max_wait_s
                now = time.perf_counter()
                # ISSUE 19: expire already-dead requests BEFORE they
                # cost a solve slot — swept out of the lanes under the
                # lock, futures failed outside it (refusal discipline:
                # never emit under a held lock). dl_min is the
                # earliest surviving deadline; the micro-batch flush
                # below must never wait past it.
                wall = time.time()
                dl_min = None
                for k, lst in self._pending.items():
                    if not lst:
                        continue
                    keep = []
                    for p in lst:
                        if p.deadline is not None and wall >= p.deadline:
                            expired.append(p)
                        else:
                            keep.append(p)
                            if p.deadline is not None:
                                dl_min = (
                                    p.deadline if dl_min is None
                                    else min(dl_min, p.deadline)
                                )
                    if len(keep) != len(lst):
                        self._pending[k] = keep
                self._n_pending -= len(expired)
                if not expired and self._n_pending:
                    # oldest-lane flush FIRST: a steady stream keeping
                    # one bucket full must not starve another bucket's
                    # lone request past its max_wait_ms contract
                    ok, ot = None, None
                    for k, lst in self._pending.items():
                        if lst and (ot is None or lst[0].t_submit < ot):
                            ok, ot = k, lst[0].t_submit
                    if self._closed or (ot is not None
                                        and now >= ot + max_wait):
                        key = ok
                    else:
                        for k, lst in self._pending.items():
                            # k = ((slots, spatial), digest): a full
                            # bank-lane flushes immediately
                            if lst and len(lst) >= k[0][0]:
                                key = k
                                break
                        if key is None and not inflight:
                            # nothing flushable and nothing in
                            # flight: sleep. With work IN flight the
                            # worker never sleeps here — it falls
                            # through to complete the oldest launch
                            # (the fence is the productive wait).
                            t_wait = ot + max_wait - now
                            if dl_min is not None:
                                # cap the wait at the earliest
                                # in-queue deadline: expiry must be
                                # noticed when it happens, not at the
                                # micro-batch flush after it
                                t_wait = min(
                                    t_wait,
                                    max(dl_min - wall, 0.0) + 1e-3,
                                )
                            self._cv.wait(timeout=t_wait)
                            continue
                if key is not None:
                    slots_k = key[0][0]
                    batch = self._pending[key][:slots_k]
                    self._pending[key] = self._pending[key][slots_k:]
                    self._n_pending -= len(batch)
                    depth_after = self._n_pending
                    self._dispatch_digests.append(key[1])
            if expired:
                for p in expired:
                    # a client-cancelled future is dropped silently
                    # (its own withdrawal event fires fleet-side); a
                    # live one fails with the stamped refusal
                    if p.future.set_running_or_notify_cancel():
                        p.future.set_exception(
                            DeadlineExceeded("dispatch", p.deadline)
                        )
                        self._emit(
                            "deadline_exceeded", where="dispatch",
                            deadline=round(p.deadline, 3),
                        )
                continue
            if key is not None:
                # transition futures to RUNNING; a client-cancelled
                # request is dropped HERE — set_result on a cancelled
                # Future raises InvalidStateError, which would poison
                # its batch siblings
                batch = [
                    p for p in batch
                    if p.future.set_running_or_notify_cancel()
                ]
                if batch:
                    try:
                        inflight.append(
                            self._launch(key, batch, depth_after)
                        )
                    except Exception as e:  # pragma: no cover
                        for p in batch:
                            if not p.future.done():
                                p.future.set_exception(e)
                        self._emit("serve_error", error=str(e)[:300])
                        self._release_digest(key[1])
                else:
                    self._release_digest(key[1])
                if len(inflight) < depth:
                    # room in the pipeline: go look for the next
                    # batch to upload before paying any fence
                    continue
            if inflight:
                inf = inflight.pop(0)
                try:
                    self._complete(inf)
                except Exception as e:  # pragma: no cover - surfacing
                    for p in inf.batch:
                        if not p.future.done():
                            p.future.set_exception(e)
                    self._emit("serve_error", error=str(e)[:300])
                finally:
                    self._release_digest(inf.key[1])

    def _launch(self, key, batch: List[_Pending],
                depth_after: int) -> _InFlight:
        """The dispatch half that needs no fence: plan fetch, batch
        canvas fill, host->device upload (onto the bucket's batch
        sharding on a mesh engine), and the async program call. With
        pipeline_depth > 1 this runs while the PREVIOUS batch's solve
        is still in flight — JAX dispatch is asynchronous, so the
        returned _InFlight holds device futures, not results."""
        jnp = self._jnp
        bkey, digest = key
        slots, spatial = bkey
        geom = self.geom
        name = _bucket_name(slots, spatial)
        # plan fetch BEFORE the batch canvas fills: an evicted plan
        # rebuilds here (evict-and-rebuild — a jitted build, never an
        # XLA recompile), and a rebuild failure fails this batch's
        # futures cleanly via the worker's surfacing path
        plan = self._plan_for(digest, bkey)
        t0 = time.perf_counter()

        shape = (slots, *geom.reduce_shape, *spatial)
        bb = np.zeros(shape, np.float32)
        mm = np.zeros(shape, np.float32)  # filler slots: observe nothing
        ss = np.zeros(shape, np.float32)
        xx = np.zeros(shape, np.float32)
        for i, p in enumerate(batch):
            # top-left placement; the zero mask over the pad region
            # excludes it from the data term, so the valid-region
            # solve is the exact-shape solve up to boundary coupling
            sl = (i, *(slice(None),) * geom.ndim_reduce) + tuple(
                slice(0, s) for s in p.spatial
            )
            bb[sl] = p.b
            mm[sl] = p.mask if p.mask is not None else 1.0
            if p.smooth_init is not None:
                ss[sl] = p.smooth_init
            if p.x_orig is not None:
                xx[sl] = p.x_orig

        # an SLO breach may have armed a ONE-SHOT xprof capture of
        # the next dispatch (serve.slo): wrap the solve + its fence so
        # the trace answers "where did the slow p99 go" with per-op
        # timelines instead of a guess
        prof_dir, self._profile_armed = self._profile_armed, None
        if prof_dir:
            from ..utils import profiling

            ctx = profiling.xla_trace(prof_dir)
        else:
            ctx = contextlib.nullcontext()
        if self._data_sharding is not None:
            # mesh engine: upload straight onto the bucket program's
            # batch sharding — the shards land on their devices here
            # (asynchronously, overlapping any in-flight solve under
            # pipelining) instead of being resharded at call time
            import jax

            sh = self._data_sharding

            def _put(a):
                return jax.device_put(a, sh)

        else:
            _put = jnp.asarray
        try:
            with ctx:
                out = self._programs[bkey](
                    _put(bb), _put(mm), _put(ss), _put(xx), plan,
                )
                if prof_dir:
                    # a profiled dispatch fences INSIDE the capture
                    # (one-shot; the trace must contain the solve,
                    # not just its async launch)
                    np.asarray(out.trace.num_iters)
        finally:
            # the capture is consumed either way (one-shot) — record
            # it even when the profiled solve RAISES: the trace on
            # disk exists precisely for the runs where things went
            # wrong, and only this event makes it discoverable
            if prof_dir:
                self._emit(
                    "slo_profile", trace_dir=prof_dir, bucket=name
                )
        return _InFlight(key, batch, depth_after, out, t0)

    def _complete(self, inf: _InFlight) -> None:
        """The dispatch half behind the fence: block on num_iters
        (THE fence — everything else in the result pytree is ready
        once it is), read back whatever the tracking flags say anyone
        consumes, resolve futures, and emit the dispatch tail
        (spans, SLO/quality ticks, serve_dispatch)."""
        from ..models.reconstruct import ReconTrace, SolveExtras
        from ..utils import perfmodel

        key, batch, depth_after, out, t0 = inf
        bkey, digest = key
        slots, spatial = bkey
        geom = self.geom
        name = _bucket_name(slots, spatial)
        iters = np.asarray(out.trace.num_iters)  # the fence
        dt = time.perf_counter() - t0
        t_done = time.perf_counter()

        # trace readbacks are GATED on the tracking flags: an
        # untracked trace is device zeros — transferring them every
        # dispatch buys nothing, so the host substitutes the same
        # zeros. obj/diff ride track_objective (diff additionally on
        # the diagnostics flag), psnr rides track_psnr; num_iters
        # above is always read — it is the fence.
        n_tr = int(self.cfg.max_it) + 1
        zeros_tr = None
        if not (self.cfg.with_objective and self.cfg.with_psnr):
            zeros_tr = np.zeros((slots, n_tr), np.float32)
        obj = (
            np.asarray(out.trace.obj_vals)
            if self.cfg.with_objective
            else zeros_tr
        )
        psnr = (
            np.asarray(out.trace.psnr_vals)
            if self.cfg.with_psnr
            else zeros_tr
        )
        diff = (
            np.asarray(out.trace.diff_vals)
            if self.cfg.with_objective or self.cfg.track_diagnostics
            else zeros_tr
        )
        recon = np.asarray(out.recon)
        z = np.asarray(out.z) if self.serve_cfg.return_codes else None

        # on-device solve diagnostics (SolveConfig.track_diagnostics):
        # the extras subtree rides the result pytree, so these
        # readbacks land at the fence already paid above — no extra
        # dispatch, asserted by tests/test_quality.py. Filler slots
        # are excluded (their zero-data solves are not diagnostics).
        extras = getattr(out.trace, "extras", None)
        if extras is not None:
            ex_fid = np.asarray(extras.obj_fid)[: len(batch)]
            ex_l1 = np.asarray(extras.obj_l1)[: len(batch)]
            ex_nonf = np.asarray(extras.nonfinite)[: len(batch)]
        else:
            ex_fid = ex_l1 = ex_nonf = None
        self._quality.observe_solve(
            name,
            iters[: len(batch)],
            self.cfg.max_it,
            obj_fid=ex_fid,
            obj_l1=ex_l1,
            nonfinite=ex_nonf,
        )

        # this dispatch's digest binding ends HERE: the solve is read
        # back and the plan is never consulted again, so the digest
        # reference must drop before any future resolves — a client
        # that calls publish_bank the moment its result lands has to
        # see the superseded digest retirable (the hot-swap sweep
        # contract; the worker loop's finally-release is the backstop
        # for the raising paths above). Another in-flight launch on
        # the same digest holds its OWN reference.
        self._release_digest(digest)

        max_it = int(iters[: len(batch)].max()) if len(batch) else 0
        for i, p in enumerate(batch):
            crop = tuple(slice(0, s) for s in p.spatial)
            rec_i = recon[i, 0][(..., *crop)]
            n_it = int(iters[i])
            has_x = p.x_orig is not None
            tracked = has_x and self.cfg.with_psnr
            tr = ReconTrace(
                obj[i],
                psnr[i] if tracked else np.zeros_like(psnr[i]),
                diff[i],
                np.int32(n_it),
                SolveExtras(ex_fid[i], ex_l1[i], ex_nonf[i])
                if ex_fid is not None
                else None,
            )
            final_psnr = (
                _valid_region_psnr(rec_i, p.x_orig, geom.psf_radius)
                if tracked
                else None
            )
            wait_s = t0 - p.t_submit
            latency = t_done - p.t_submit
            self._slo.observe("queue", wait_s * 1e3)
            self._slo.observe("solve", dt * 1e3)
            self._slo.observe("total", latency * 1e3)
            self._quality.observe(
                final_psnr,
                bank_id=p.bank_id,
                tenant=p.tenant,
                bucket=name,
            )
            # span emission is RETROSPECTIVE (start+end written
            # together with measured times): a replica killed
            # mid-dispatch can never leave an orphan span_start in
            # its stream. Wall-clock times are reconstructed from the
            # perf-counter measurements via one shared offset.
            wall_off = time.time() - time.perf_counter()
            if p.trace_id is not None:
                parent = p.parent_span
                if p.own_root:
                    # standalone engine: the engine owns the root
                    parent = trace_util.emit_span(
                        self._emit_span,
                        trace_id=p.trace_id,
                        span=trace_util.ROOT_SPAN,
                        t_start=wall_off + p.t_submit,
                        t_end=wall_off + t_done,
                    )
                trace_util.emit_span(
                    self._emit_span,
                    trace_id=p.trace_id,
                    span="engine_queue",
                    parent_span=parent,
                    t_start=wall_off + p.t_submit,
                    t_end=wall_off + t0,
                )
                trace_util.emit_span(
                    self._emit_span,
                    trace_id=p.trace_id,
                    span="solve",
                    parent_span=parent,
                    t_start=wall_off + t0,
                    t_end=wall_off + t_done,
                    bucket=name,
                    iters=n_it,
                )
            res = ServedResult(
                recon=rec_i,
                trace=tr,
                psnr=final_psnr,
                bucket=name,
                wait_s=wait_s,
                latency_s=latency,
                z=z[i, 0] if z is not None else None,
            )
            p.future.set_result(res)
            self._emit(
                "serve_request",
                trace_id=p.trace_id,
                bucket=name,
                spatial=list(p.spatial),
                wait_ms=round(wait_s * 1e3, 3),
                latency_ms=round(latency * 1e3, 3),
                iters=n_it,
                psnr=final_psnr,
                bank_id=p.bank_id,
                tenant=p.tenant,
            )
            if self._capture is not None and p.cap_key is not None:
                self._capture.record_outcome(
                    p.cap_key, rec_i, final_psnr, latency * 1e3,
                    name, iters=n_it,
                )
        occ = len(batch) / slots
        self._n_dispatches += 1
        self._occupancy_sum += occ
        it_rate = max_it / dt if dt > 0 and max_it else 0.0
        if it_rate > 0:
            # the fleet's ceiling derivation reads the newest measured
            # rate (perfmodel.serving_bound input) without re-parsing
            # the stream
            self._last_it_rate = it_rate
        # the bound is the FULL-bucket ceiling at this dispatch's
        # measured iteration rate (occupancy=1.0) — the achieved
        # len(batch)/dt sits below it exactly by the unfilled slots,
        # so the stream records real headroom, not a tautology
        bound = perfmodel.serving_bound(
            it_rate, max(max_it, 1), slots, occupancy=1.0
        )
        self._emit(
            "serve_dispatch",
            bucket=name,
            digest=digest,
            n=len(batch),
            slots=slots,
            occupancy=round(occ, 4),
            queue_depth=depth_after,
            dt_s=round(dt, 5),
            max_iters=max_it,
            it_per_sec=round(it_rate, 3),
            requests_per_sec=round(
                len(batch) / dt if dt > 0 else 0.0, 3
            ),
            bound_requests_per_sec=round(
                bound["requests_per_sec"], 3
            ),
        )
        # continuous SLO check on the dispatch path (cadence-gated in
        # the monitor): breaches + periodic histogram snapshots land
        # in the stream, and the first breach arms the one-shot xprof
        # capture of the NEXT dispatch
        breaches, snaps = self._slo.tick()
        for br in breaches:
            self._emit("slo_breach", **br)
        for sn in snaps:
            self._emit("slo_histogram", **sn)
        if breaches and self._slo_profile_dir and not self._profiled:
            self._profiled = True
            self._profile_armed = self._slo_profile_dir
        # the quality plane's cadence-gated flush rides the same
        # dispatch tail (the engine declares no floors — breaches
        # are a fleet-scope concern — but histograms + solve
        # diagnostics land here)
        q_breaches, q_snaps, q_diags = self._quality.tick()
        for br in q_breaches:
            self._emit("quality_breach", **br)
        for sn in q_snaps:
            self._emit("quality_histogram", **sn)
        for dg in q_diags:
            self._emit("quality_solve_diag", **dg)

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """Request-latency percentiles + queue/bucket aggregates.
        Percentiles come from the streaming log-bucketed histogram
        (serve.slo — O(1) memory on a long-lived engine; honest to
        one bucket width), the same numbers the slo_histogram events
        and the metricsd scrape quote."""
        pct = lambda q: self._slo.percentile("total", q)
        to_s = lambda v: None if v is None else v / 1e3
        return {
            "n_requests": self._slo.n("total"),
            "n_dispatches": self._n_dispatches,
            "mean_occupancy": (
                self._occupancy_sum / self._n_dispatches
                if self._n_dispatches
                else 0.0
            ),
            "p50_latency_s": to_s(pct(0.50)),
            "p99_latency_s": to_s(pct(0.99)),
        }

    def metrics(self) -> Dict[str, object]:
        """Live counters/gauges/histograms in the shared shape
        ``serve.metricsd.render_prometheus`` renders — the scrape
        source of a standalone engine's metrics endpoint."""
        with self._cv:
            depth = self._n_pending
        st = self.stats()
        return {
            "counters": {
                "requests_total": st["n_requests"],
                "dispatches_total": st["n_dispatches"],
            },
            "gauges": {
                "queue_depth": depth,
                "mean_occupancy": round(st["mean_occupancy"], 4),
                # routed bank COUNT (the fleet gauge's semantics —
                # the two surfaces must agree), not retained digests
                "banks": len(self._routes),
                "plan_cache_bytes": self._plan_cache.total_bytes,
            },
            "histograms": [
                ("latency_ms", {"phase": sn["phase"]}, sn)
                for sn in self._slo.raw_snapshots()
            ]
            + [
                (
                    "psnr_db",
                    {
                        "bank_id": sn["bank_id"],
                        "tenant": sn["tenant"],
                        "bucket": sn["bucket"],
                    },
                    sn,
                )
                for sn in self._quality.raw_snapshots()
            ],
        }

    @property
    def closed(self) -> bool:
        """True once close() has been called (or construction failed)
        — the liveness poll the fleet uses before handing an engine
        more work. The engine may still be draining when this flips;
        ``close()`` from any thread blocks until the drain finishes."""
        return self._close_started

    @property
    def devices(self) -> int:
        """Number of devices this engine's bucket programs execute
        on (1 for a single-device engine) — the weight the fleet's
        derived admission ceiling and ``capacity_hint`` scale by."""
        return (
            int(self._mesh.size) if self._mesh is not None else 1
        )

    @property
    def mesh_shape(self) -> Optional[Tuple[int, ...]]:
        """The resolved serving-mesh shape ((batch,) or
        (batch, freq)), or None for a single-device engine."""
        return self._mesh_shape

    @property
    def last_it_rate(self) -> float:
        """Measured iteration rate of the newest dispatch (it/s; 0.0
        before any dispatch) — the ``perfmodel.serving_bound`` input
        the fleet's derived admission ceiling is computed from."""
        return self._last_it_rate

    # -- multi-bank serving (serve.registry) ---------------------------
    def _plan_for(self, digest: str, bkey) -> object:
        """The plan serving ``(digest, bucket)``: LRU hit, or
        evict-and-rebuild from the retained bank bytes — a jitted
        ``build_plan`` call, never an XLA recompile (the compiled
        bucket program is digest-canonical and shared across banks)."""
        plan = self._plan_cache.get(digest, bkey)
        if plan is not None:
            return plan
        d = self._banks.get(digest)
        if d is None:
            raise RuntimeError(
                f"bank digest {digest} has no retained bytes on "
                "this engine — publish the bank before routing "
                "requests to it"
            )
        return self._install_plan(digest, bkey, d)

    def _install_plan(self, digest: str, bkey, d) -> object:
        """Build one bucket's plan for one bank and insert it into
        the LRU (digests with queued work pinned against eviction).
        Runs on whatever thread needs the plan — the publishing
        caller for a hot-swap (off the hot path), the worker for a
        rebuild-on-miss."""
        t0 = time.perf_counter()
        slots, spatial = bkey
        plan = self._build_plan(
            d, self.prob, self._plan_cfg, spatial,
            blur_psf=self._blur_psf,
            mesh_shape=self._mesh_shape, slots=slots,
            buckets=self._buckets,
        )
        plan = dataclasses.replace(plan, d_digest="")
        # bin-sharded residency (freq meshes): rebuilt plans land on
        # the mesh exactly like warmup-installed ones, so a
        # rebuild-on-miss dispatch pays no resharding either
        plan = self._place_plan(plan)
        with self._cv:
            pin = {
                lane[1]
                for lane, lst in self._pending.items() if lst
            }
        evicted = self._plan_cache.put(digest, bkey, plan, pin=pin)
        self._emit(
            "bank_plan_build",
            digest=digest,
            bucket=_bucket_name(slots, spatial),
            build_s=round(time.perf_counter() - t0, 4),
            plan_bytes=self._plan_cache.total_bytes,
        )
        for ev_digest, ev_bkey in evicted:
            self._emit(
                "bank_plan_evict",
                digest=ev_digest,
                bucket=_bucket_name(*ev_bkey),
                plan_bytes=self._plan_cache.total_bytes,
            )
        return plan

    def add_bank(self, d, blur_psf=None) -> str:
        """Register a bank's bytes and build+warm its per-bucket
        plans WITHOUT touching any route — the make-servable half of
        a hot-swap, safe to run while traffic flows (plan builds are
        jitted, the compiled programs are shared). Idempotent per
        digest. Returns the bank's ``d_digest``. ``blur_psf`` must
        match the engine's pinned blur (plans compose it)."""
        from ..utils import validate

        from . import registry as _registry

        if blur_psf is not None:
            raise validate.CCSCInputError(
                "add_bank serves the engine's pinned blur operator — "
                "per-bank blur PSFs are not supported (build a "
                "second engine)"
            )
        validate.check_filters(d, self.geom)
        digest = _registry.bank_digest(d)
        with self._cv:
            if self._close_started:
                raise RuntimeError("engine is closed")
            known = digest in self._banks
            self._banks[digest] = d
        if not known:
            for slots, spatial in self._buckets:
                self._install_plan(digest, (slots, spatial), d)
        return digest

    def publish_bank(
        self, bank_id: Optional[str], d,
        tenant: Optional[str] = None,
    ) -> Tuple[Optional[str], str]:
        """Zero-downtime hot-swap: make ``d`` servable (plans built
        and warmed off the hot path), then atomically route
        ``bank_id`` (None = the engine's DEFAULT bank) to the new
        digest. In-flight and queued requests bound the old digest at
        admission and finish on the old plan; admissions after the
        flip serve the new one. The cutover is visible in the stream
        as a ``bank_swap`` carrying both digests. Returns
        ``(old_digest, new_digest)``."""
        digest = self.add_bank(d)
        with self._cv:
            if self._close_started:
                raise RuntimeError("engine is closed")
            old = self._routes.get(bank_id)
            self._routes[bank_id] = digest
            stale = [
                dg for dg in self._banks
                if dg not in self._routes.values()
            ]
        self._emit(
            "bank_swap",
            bank_id=bank_id,
            old_digest=old,
            new_digest=digest,
            tenant=tenant,
        )
        # memory-bounding sweep: superseded digests (this swap's old
        # one AND any earlier leftover a prior attempt could not
        # retire) are dropped once nothing references them —
        # in-flight/queued requests that bound them still finish
        # (retire_bank refuses while they do; the next publish
        # retries)
        for dg in stale:
            self.retire_bank(dg)
        return old, digest

    def retire_bank(self, digest: str) -> bool:
        """Drop one digest's retained bytes, cached plans, and empty
        queue lanes — the memory-bounding half of hot-swap (a fleet
        republishing continuously must not accumulate every
        superseded bank forever). REFUSED (returns False) while the
        digest is still referenced: routed by any bank id, queued in
        any lane, or mid-dispatch — a retire must never fail a
        request that already bound the digest. Returns True when the
        digest is gone."""
        with self._cv:
            if digest in self._routes.values():
                return False
            if digest in self._dispatch_digests:
                return False
            if any(
                lane[1] == digest and lst
                for lane, lst in self._pending.items()
            ):
                return False
            self._banks.pop(digest, None)
            for lane in [
                ln for ln in self._pending if ln[1] == digest
            ]:
                del self._pending[lane]
        for _dg, ev_bkey in self._plan_cache.drop_digest(digest):
            self._emit(
                "bank_plan_evict",
                digest=digest,
                bucket=_bucket_name(*ev_bkey),
                plan_bytes=self._plan_cache.total_bytes,
                retired=True,
            )
        return True

    @property
    def bank_ids(self) -> List[str]:
        """Published bank ids (the default bank routes as None and is
        not listed)."""
        with self._cv:
            return sorted(k for k in self._routes if k is not None)

    def bank_digest(self, bank_id: Optional[str] = None) -> str:
        """The digest ``bank_id`` currently routes to (None = the
        default bank)."""
        from ..utils import validate

        with self._cv:
            digest = self._routes.get(bank_id)
        if digest is None:
            raise validate.CCSCInputError(
                f"unknown bank id {bank_id!r}"
            )
        return digest

    def plan_cache_stats(self) -> Dict[str, object]:
        """The plan LRU's accounting (serve.registry.PlanCache):
        entry count, byte budget vs use, hit/miss/eviction counters,
        and the measured HBM watermark sampled at builds."""
        return self._plan_cache.stats()

    def set_max_wait_ms(self, ms: float) -> None:
        """Retarget the micro-batch flush deadline live (overload
        ladder rung 1 sheds batching waits by setting 0; leaving the
        rung restores the configured value). Assigned UNDER the queue
        lock: the worker reads the deadline under the same lock on
        every evaluation pass, so the notify can never be consumed by
        a pass that still carries the stale value."""
        with self._cv:
            self._max_wait_s = max(0.0, float(ms)) / 1e3
            self._cv.notify_all()

    def drain_pending(self) -> List[Dict]:
        """Handoff hook (serve.ServeFleet): atomically remove every
        request still in the micro-batch queue — NOT yet in a dispatch
        — and return its payload
        (``{b, mask, smooth_init, x_orig, future}`` per entry) so the
        caller can requeue it onto another replica. Each returned
        engine Future is cancelled; requests already dispatching are
        untouched and resolve normally. Safe at any lifecycle point,
        including after (or racing) close()."""
        out: List[Dict] = []
        cv = getattr(self, "_cv", None)
        if cv is None:  # construction never reached the queue
            return out
        taken: List[_Pending] = []
        with cv:
            for k in self._pending:
                taken.extend(self._pending[k])
                self._n_pending -= len(self._pending[k])
                self._pending[k] = []
        for p in taken:
            p.future.cancel()
            out.append(
                {
                    "b": p.b,
                    "mask": p.mask,
                    "smooth_init": p.smooth_init,
                    "x_orig": p.x_orig,
                    "future": p.future,
                    "bank_id": p.bank_id,
                    "tenant": p.tenant,
                    "digest": p.digest,
                }
            )
        if taken:
            self._emit("serve_drain", n=len(taken))
        return out

    def close(self):
        """Flush every pending request, stop the worker, and close the
        telemetry run with the latency summary.

        Re-entrant AND race-safe: any number of callers (the user, a
        fleet drain, ``__exit__``) may call concurrently — the first
        performs the shutdown, the rest block until it has finished
        and then return. A no-op on an engine whose constructor
        raised."""
        with self._close_lock:
            owner = not self._close_started
            self._close_started = True
        if not owner:
            self._close_done.wait()
            return
        try:
            # a constructor that raised in the pre-telemetry
            # validation block never assigned _run/_cv — the
            # documented no-op contract must hold from the first
            # statement of __init__ onward, so every late-constructed
            # attribute is getattr-guarded here
            run = getattr(self, "_run", None)
            cv = getattr(self, "_cv", None)
            # stop staged warmup first: the background thread checks
            # the stop event between stages, so a close during a long
            # cold build waits at most one stage out
            ws = getattr(self, "_warm_stop", None)
            if ws is not None:
                ws.set()
            if getattr(self, "_warm_thread", None) is not None:
                while self._warm_thread.is_alive():
                    self._warm_thread.join(timeout=60)
                    if self._warm_thread.is_alive() and run is not None:
                        run.console(
                            "serve: close() waiting on an in-flight "
                            "warmup stage",
                            tier="always",
                        )
            if cv is not None:
                with cv:
                    self._closed = True
                    cv.notify_all()
                # wait for the worker to actually finish draining —
                # closing the telemetry run while a final dispatch is
                # in flight would drop its serve_request/serve_dispatch
                # events and undercut the summary. Dispatches are
                # finite, so this terminates; a long solve just gets a
                # periodic notice.
                while self._worker.is_alive():
                    self._worker.join(timeout=60)
                    if self._worker.is_alive():
                        run.console(
                            "serve: close() waiting on an in-flight "
                            "dispatch to drain",
                            tier="always",
                        )
            store = getattr(self, "_artifacts", None)
            if store is not None:
                # warmup thread is joined above, so no publish races
                # the manifest writer close
                with contextlib.suppress(Exception):
                    store.close()
            cap = getattr(self, "_capture", None)
            if cap is not None:
                # seal the capture (meta.json counters + the
                # capture_summary overhead record) while the run is
                # still open to receive it
                try:
                    cap.close()
                except Exception:
                    pass
            if run is not None and not run.closed:
                # closing histogram flush: the stream always ends with
                # one complete slo_histogram per phase, so a short
                # run's percentiles are recomputable offline
                slo_mon = getattr(self, "_slo", None)
                if slo_mon is not None and run.active:
                    _breaches, snaps = slo_mon.final()
                    for sn in snaps:
                        self._emit("slo_histogram", **sn)
                q_mon = getattr(self, "_quality", None)
                if q_mon is not None and run.active:
                    _qb, q_snaps, q_diags = q_mon.final()
                    for sn in q_snaps:
                        self._emit("quality_histogram", **sn)
                    for dg in q_diags:
                        self._emit("quality_solve_diag", **dg)
                st = self.stats()
                run.close(
                    status="ok",
                    n_requests=st["n_requests"],
                    n_dispatches=st["n_dispatches"],
                    mean_occupancy=round(st["mean_occupancy"], 4),
                    p50_latency_s=(
                        round(st["p50_latency_s"], 5)
                        if st["p50_latency_s"] is not None
                        else None
                    ),
                    p99_latency_s=(
                        round(st["p99_latency_s"], 5)
                        if st["p99_latency_s"] is not None
                        else None
                    ),
                )
        finally:
            self._close_done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
