"""Workload capture: durably record every admitted serving request.

The fleet is deeply instrumented (traces, SLO histograms, the perf
ledger) but until now nothing recorded the WORKLOAD itself — what
bytes arrived, when, and what the fleet answered — so there was no
way to re-serve yesterday's traffic against tomorrow's fleet and
check the answers. :class:`WorkloadRecorder` closes that gap: hooked
into ``ServeFleet.submit``/``_deliver`` (and a standalone
``CodecEngine``), it appends one record per admitted request to an
append-only JSONL segment with the ledger's torn-tail durability
stance, content-addresses every payload array by sha256 into a
shared ``payloads/`` store (identical arrays across requests are
stored once), and pairs each request with its outcome digest —
sha256 of the delivered reconstruction bytes — plus valid-region
PSNR and latency. Because the serving stack is deterministic
(identical request bytes through identical bucket programs reproduce
identical results — the MPAX pinned-problem stance, PAPERS.md
arXiv:2412.09734), a captured stream is a bit-checkable oracle:
``serve.replay`` re-submits it and verifies outcomes, not just load.

Capture-dir layout::

    capture_dir/
      meta.json            # capture identity + final counters (atomic)
      requests-0000.jsonl  # request/outcome records, segment-rotated
      payloads.jsonl       # payload index: sha -> shape/dtype/bytes
      payloads/<sha>.npy   # content-addressed arrays (deduplicated)

Knobs (``CCSC_CAPTURE_*``, utils.env): ``CCSC_CAPTURE_DIR`` arms
capture on any fleet/standalone engine without a config change;
``CCSC_CAPTURE_SAMPLE`` records a deterministic per-key fraction of
the stream (outcome records follow their request's verdict, so a
sampled capture is still pairable); ``CCSC_CAPTURE_ROTATE_MB`` bounds
segment size — a long-lived fleet rotates to a fresh segment instead
of growing one file forever (:func:`read_workload` merges segments in
name order; note ``obs.EventTail`` filters on ``events*.jsonl`` and
does NOT see these ``requests-*.jsonl`` files — tail a live capture
by re-running ``read_workload``, which is cheap per segment).

Overhead is accounted, not guessed: every second spent hashing and
writing is accumulated and reported in the ``capture_summary`` obs
event (plus per-request mean), so "capture is cheap" is a measured
claim in the stream.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import env as _env
from ..utils import obs as _obs

__all__ = [
    "WorkloadRecorder",
    "resolve_capture_dir",
    "payload_sha",
    "read_workload",
    "read_payload_index",
    "load_payload",
]

_SCHEMA = 1
_SEGMENT_FMT = "requests-{:04d}.jsonl"
_INDEX_NAME = "payloads.jsonl"
_PAYLOAD_DIR = "payloads"
_ARRAY_FIELDS = ("b", "mask", "smooth_init", "x_orig")


def resolve_capture_dir(explicit: Optional[str]) -> Optional[str]:
    """The one resolution chain for the capture switch: an explicit
    config path wins, else ``CCSC_CAPTURE_DIR``, else capture is off
    (None). An explicit EMPTY STRING is "off regardless of the env"
    — the replay driver's fresh fleets use it so a replay run in a
    shell with ``CCSC_CAPTURE_DIR`` still armed can never re-capture
    itself into the directory being replayed. Shared by the fleet and
    the standalone engine so the two cannot diverge."""
    if explicit == "":
        return None
    return explicit or _env.env_str("CCSC_CAPTURE_DIR") or None


def payload_sha(arr: np.ndarray) -> str:
    """Content address of one payload array: sha256 over a dtype/shape
    header plus the raw bytes — two arrays with identical bytes but
    different shapes (a flattened copy) must not collide."""
    a = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(f"{a.dtype.str}|{a.shape}|".encode("utf-8"))
    h.update(a.tobytes())
    return h.hexdigest()


def _sample_admits(key: str, sample: float) -> bool:
    """Deterministic per-key sampling verdict: the same key always
    lands on the same side, so a request's outcome record can never be
    captured without its request (or vice versa), and a re-capture of
    the same stream samples identically."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    digest = hashlib.sha256(key.encode("utf-8")).digest()
    frac = int.from_bytes(digest[:8], "big") / float(1 << 64)
    return frac < sample


class WorkloadRecorder:
    """Durable request/outcome recorder for one serving session.

    Thread-safe: ``record_submit`` runs on submitter threads and
    ``record_outcome`` on replica worker threads; a private lock
    orders the segment appends (sha256 hashing — the expensive part —
    happens OUTSIDE it). All file I/O uses the append-only JSONL
    stance of :class:`~..utils.obs.EventWriter`: one flushed line per
    record, a torn trailing line from a killed writer is terminated
    before the next append, and readers drop torn lines instead of
    failing the stream.

    ``emit`` is an optional obs-event callable (``run.event``-shaped);
    when given, the recorder announces itself (``capture_start``),
    each segment rotation (``capture_rotate``), and its close-time
    accounting (``capture_summary`` — request/payload counts, dedup
    hits, total bytes, and the measured capture overhead).
    """

    def __init__(
        self,
        path: str,
        sample: Optional[float] = None,
        rotate_mb: Optional[float] = None,
        emit=None,
        meta: Optional[Dict[str, Any]] = None,
    ):
        self.path = path
        self.sample = (
            float(sample)
            if sample is not None
            else float(_env.env_float("CCSC_CAPTURE_SAMPLE"))
        )
        rotate = (
            float(rotate_mb)
            if rotate_mb is not None
            else float(_env.env_float("CCSC_CAPTURE_ROTATE_MB"))
        )
        self.rotate_bytes = max(1, int(rotate * 1e6))
        self._emit = emit
        self._lock = threading.Lock()
        self.t0 = time.time()
        self._t0_perf = time.perf_counter()
        self.n_requests = 0
        self.n_outcomes = 0
        self.n_sampled_out = 0
        self.n_payloads = 0
        self.n_dedup_hits = 0
        self.payload_bytes = 0
        self.overhead_s = 0.0
        self.n_errors = 0
        self._closed = False
        self._broken = False
        # capture-session identity, stamped on every record: a
        # recorder reopened on the same dir (a restarted fleet)
        # starts a NEW session, and read_workload pairs outcomes by
        # (session, key) — so a second session re-using the same
        # idempotency keys (auto-keys restart at req-00000001 per
        # fleet) can never weld its requests onto an earlier
        # session's outcomes
        self.session = os.urandom(6).hex()
        os.makedirs(os.path.join(path, _PAYLOAD_DIR), exist_ok=True)
        # resume-aware: a recorder re-opened on an existing capture dir
        # (a restarted fleet) continues the segment sequence and trusts
        # the existing payload store (content addressing makes the
        # dedup index rebuildable from the torn-tolerant index file)
        self._known_shas = set(read_payload_index(path))
        self._segment = self._next_segment_index()
        self._writer = _obs.EventWriter(self._segment_path())
        self._index = _obs.EventWriter(
            os.path.join(path, _INDEX_NAME)
        )
        self._extra_meta: Dict[str, Any] = dict(meta or {})
        self._write_meta(status="open")
        if self._emit is not None:
            self._emit(
                "capture_start",
                path=self.path,
                sample=self.sample,
                rotate_bytes=self.rotate_bytes,
                segment=self._segment,
            )

    # -- internals -----------------------------------------------------
    def _segment_path(self) -> str:
        return os.path.join(self.path, _SEGMENT_FMT.format(self._segment))

    def _next_segment_index(self) -> int:
        try:
            existing = [
                n for n in os.listdir(self.path)
                if n.startswith("requests-") and n.endswith(".jsonl")
            ]
        except OSError:
            return 0
        return len(existing)

    def _write_meta(self, status: str) -> None:
        """Atomic meta rewrite (tmp + rename): the meta file is the
        capture's identity + final counters, and a reader must never
        see a torn JSON document."""
        meta = {
            "schema": _SCHEMA,
            "t0": self.t0,
            "status": status,
            "sample": self.sample,
            "n_requests": self.n_requests,
            "n_outcomes": self.n_outcomes,
            "n_sampled_out": self.n_sampled_out,
            "n_payloads": self.n_payloads,
            "payload_bytes": self.payload_bytes,
            "n_errors": self.n_errors,
            "broken": self._broken,
            "session": self.session,
            "git_sha": _obs.git_sha(),
        }
        meta.update(self._extra_meta)
        tmp = os.path.join(self.path, "meta.json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f, default=str)
        os.replace(tmp, os.path.join(self.path, "meta.json"))

    def _store_payload(self, arr: Optional[np.ndarray]) -> Optional[str]:
        """Content-addressed store of one array; returns its sha (or
        None for an absent optional payload). Dedup across requests:
        an already-stored sha costs one set lookup."""
        if arr is None:
            return None
        arr = np.ascontiguousarray(arr)
        sha = payload_sha(arr)
        with self._lock:
            if self._closed:
                # racing a close(): drop rather than write through a
                # closed index writer
                return sha
            if sha in self._known_shas:
                self.n_dedup_hits += 1
                return sha
            self._known_shas.add(sha)
        fpath = os.path.join(self.path, _PAYLOAD_DIR, sha + ".npy")
        tmp = fpath + ".tmp"
        with open(tmp, "wb") as f:
            np.save(f, arr)
        os.replace(tmp, fpath)
        nbytes = os.path.getsize(fpath)
        self._index.write(
            {
                "sha": sha,
                "shape": list(arr.shape),
                "dtype": arr.dtype.str,
                "bytes": nbytes,
            }
        )
        with self._lock:
            self.n_payloads += 1
            self.payload_bytes += nbytes
        return sha

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if self._closed:
                return
            self._writer.write(rec)
            try:
                size = os.path.getsize(self._segment_path())
            except OSError:
                size = 0
            if size < self.rotate_bytes:
                return
            # rotate: close the full segment, open the next —
            # read_workload merges segments by name order, so a new
            # segment appearing mid-capture is picked up on the next
            # read
            self._writer.close()
            self._segment += 1
            self._writer = _obs.EventWriter(self._segment_path())
            segment = self._segment
        if self._emit is not None:
            self._emit(
                "capture_rotate",
                path=self.path,
                segment=segment,
            )

    # -- recording -----------------------------------------------------
    def record_submit(
        self,
        key: str,
        trace_id: Optional[str],
        b: np.ndarray,
        mask: Optional[np.ndarray] = None,
        smooth_init: Optional[np.ndarray] = None,
        x_orig: Optional[np.ndarray] = None,
        bucket: Optional[str] = None,
        solve: Optional[Dict[str, Any]] = None,
        t_rel: Optional[float] = None,
        bank_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> None:
        """Record one ADMITTED request: relative arrival time, identity
        (idempotency key + trace id), shape/bucket, solve params,
        multi-tenant routing (``bank_id``/``tenant`` — so a
        mixed-tenant capture replays each request against ITS bank,
        per-bank digest parity intact), and the four payload arrays
        content-addressed into the store. ``t_rel`` overrides the
        wall-clock arrival offset — synthetic generators stamp curve
        time, not generation time.

        NEVER raises: the recorder sits on the serving hot path
        (fleet ``submit``/``_deliver``, the engine worker loop), and
        a capture I/O failure — disk full, a racing close — must
        degrade capture, not kill a healthy replica or surface a
        traceback to a client whose request was already admitted.
        The first failure marks the recorder broken (recording
        stops) and is announced with a ``capture_error`` event."""
        if self._closed or self._broken:
            return
        t_in = time.perf_counter()
        try:
            if not _sample_admits(key, self.sample):
                with self._lock:
                    self.n_sampled_out += 1
                return
            rec = {
                "kind": "request",
                "session": self.session,
                "key": key,
                "trace_id": trace_id,
                "t_rel": round(
                    time.time() - self.t0 if t_rel is None else t_rel,
                    6,
                ),
                "spatial": list(np.shape(b)),
                "bucket": bucket,
                "bank_id": bank_id,
                "tenant": tenant,
                "b": self._store_payload(b),
                "mask": self._store_payload(mask),
                "smooth_init": self._store_payload(smooth_init),
                "x_orig": self._store_payload(x_orig),
            }
            if solve:
                rec["solve"] = solve
            self._append(rec)
        except Exception as e:
            self._mark_broken(e)
            return
        dt = time.perf_counter() - t_in
        with self._lock:
            self.n_requests += 1
            self.overhead_s += dt

    def record_outcome(
        self,
        key: str,
        recon: np.ndarray,
        psnr: Optional[float],
        latency_ms: float,
        bucket: str,
        iters: Optional[int] = None,
    ) -> None:
        """Record one delivered result: the outcome digest (sha256 of
        the reconstruction bytes — the bit-parity oracle replay checks
        against), valid-region PSNR, and client-visible latency.

        ``psnr`` MUST be the shared
        :func:`serve.quality.valid_region_psnr` value (the engine's
        dispatch path computes exactly that) — replay's cross-bucket
        verifier and the shadow scorer recompute with the same
        function and compare against this recorded dB, rounded to
        6 decimals here (tests/test_quality.py pins the
        bit-equality). Never raises (same hot-path contract as
        :meth:`record_submit`)."""
        # the sampler's verdict is deterministic per key, so the
        # outcome follows its request's fate even when a worker
        # thread delivers before the submitter's record lands
        if self._closed or self._broken:
            return
        t_in = time.perf_counter()
        try:
            if not _sample_admits(key, self.sample):
                return
            rec = {
                "kind": "outcome",
                "session": self.session,
                "key": key,
                "t_rel": round(time.time() - self.t0, 6),
                "digest": payload_sha(np.asarray(recon)),
                "psnr": (
                    None if psnr is None else round(float(psnr), 6)
                ),
                "latency_ms": round(float(latency_ms), 3),
                "bucket": bucket,
                "iters": None if iters is None else int(iters),
            }
            self._append(rec)
        except Exception as e:
            self._mark_broken(e)
            return
        dt = time.perf_counter() - t_in
        with self._lock:
            self.n_outcomes += 1
            self.overhead_s += dt

    def _mark_broken(self, exc: Exception) -> None:
        """First capture failure: stop recording (a half-broken
        capture is worse than an honestly truncated one) and announce
        it in the stream — best-effort, the announcement itself must
        not raise either."""
        with self._lock:
            self.n_errors += 1
            first = not self._broken
            self._broken = True
        if first and self._emit is not None:
            try:
                self._emit(
                    "capture_error",
                    path=self.path,
                    error=f"{type(exc).__name__}: {exc}"[:300],
                )
            except Exception:
                pass

    # -- lifecycle -----------------------------------------------------
    def close(self, **final_meta) -> None:
        """Flush and seal the capture: final counters land in
        ``meta.json`` (plus any caller-supplied fields — the fleet
        passes its admission counters so replay can diff admission
        behavior) and the overhead accounting lands in the obs stream
        as ``capture_summary``."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._writer.close()
            self._index.close()
        self._extra_meta.update(final_meta)
        self._write_meta(status="closed")
        if self._emit is not None:
            n = max(1, self.n_requests)
            self._emit(
                "capture_summary",
                path=self.path,
                n_requests=self.n_requests,
                n_outcomes=self.n_outcomes,
                n_sampled_out=self.n_sampled_out,
                n_payloads=self.n_payloads,
                n_dedup_hits=self.n_dedup_hits,
                payload_bytes=self.payload_bytes,
                n_errors=self.n_errors,
                overhead_s=round(self.overhead_s, 6),
                overhead_ms_per_request=round(
                    1e3 * self.overhead_s / n, 4
                ),
                elapsed_s=round(
                    time.perf_counter() - self._t0_perf, 3
                ),
            )


# ---------------------------------------------------------------------
# read side (replay, reports, tests)
# ---------------------------------------------------------------------


def read_meta(path: str) -> Dict[str, Any]:
    """The capture's meta.json (empty dict when absent/corrupt)."""
    try:
        with open(
            os.path.join(path, "meta.json"), encoding="utf-8"
        ) as f:
            meta = json.load(f)
        return meta if isinstance(meta, dict) else {}
    except (OSError, ValueError):
        return {}


def read_payload_index(path: str) -> Dict[str, Dict[str, Any]]:
    """The payload index: sha -> {shape, dtype, bytes}. Torn-tolerant
    like every reader here — a torn final line (the crash window of
    the line-granular writer) is dropped, never fatal."""
    out: Dict[str, Dict[str, Any]] = {}
    for rec in _obs.read_events(os.path.join(path, _INDEX_NAME)):
        sha = rec.get("sha")
        if isinstance(sha, str):
            out[sha] = rec
    return out


def load_payload(path: str, sha: str) -> np.ndarray:
    return np.load(
        os.path.join(path, _PAYLOAD_DIR, sha + ".npy")
    )


def read_workload(path: str) -> List[Dict[str, Any]]:
    """Parse every segment into one request list in arrival order,
    each request dict carrying its paired ``outcome`` record (or None
    when the capture ended before delivery — a replay treats those as
    unverifiable but still re-serves them). Pairing is by
    ``(session, key)``: a restarted fleet re-recording auto-assigned
    keys like ``req-00000001`` into the same dir starts a new capture
    session, so its requests can never pick up an earlier session's
    outcome digests. Torn/corrupt lines are dropped; a duplicate
    outcome for one (session, key) keeps the first (the fleet's
    at-most-once delivery means duplicates are a capture-side anomaly
    worth tolerating, not propagating)."""
    requests: List[Dict[str, Any]] = []
    outcomes: Dict[Any, Dict[str, Any]] = {}
    try:
        names = sorted(
            n for n in os.listdir(path)
            if n.startswith("requests-") and n.endswith(".jsonl")
        )
    except OSError:
        return []
    for name in names:
        for rec in _obs.read_events(os.path.join(path, name)):
            kind = rec.get("kind")
            if kind == "request" and rec.get("key"):
                requests.append(rec)
            elif kind == "outcome" and rec.get("key"):
                outcomes.setdefault(
                    (rec.get("session"), rec["key"]), rec
                )
    for req in requests:
        req["outcome"] = outcomes.get(
            (req.get("session"), req["key"])
        )
    requests.sort(key=lambda r: r.get("t_rel", 0.0))
    return requests
