"""Deterministic traffic replay: re-serve a captured workload as the
fleet's measuring instrument.

A capture (:mod:`serve.capture`) records what the fleet served and
what it answered; :class:`ReplayDriver` re-submits that stream
against a FRESH fleet (or standalone engine) and judges the answers:

- **open-loop** — requests are submitted at their recorded arrival
  times scaled by a speed factor (the recorded diurnal curve, slowed
  or accelerated), or at max speed (``speed=0``: back-to-back, the
  saturation probe — admission refusals back off for the fleet's
  retry-after hint and resubmit, so "zero lost requests" is a real
  claim, not a dropped-on-overload one);
- **closed-loop** — each request is submitted when the previous one
  resolves (the latency-isolated mode: no queueing beyond one
  request).

Every replayed result is paired with its recorded original and
verified: a request replayed in the SAME bucket must be
BIT-IDENTICAL (sha256 of the reconstruction bytes equals the
recorded outcome digest — the determinism contract of pinned
(bank, problem, config) bucket programs, PAPERS.md arXiv:2412.09734);
a request that landed in a different bucket (a replay fleet with a
different bucket table) is held to valid-region-PSNR tolerance
instead (``CCSC_REPLAY_PSNR_TOL`` dB).

The session is itself observable and gated: ``replay_request`` /
``replay_summary`` events land in the replay's own obs stream
(rendered by ``scripts/obs_report.py``'s REPLAY section — recorded
vs replayed p50/p99 side by side), and a ``kind=replay`` record is
appended to the durable perf ledger (``CCSC_PERF_LEDGER``) so
``scripts/perf_gate.py`` judges replay throughput against its own
history like any other workload.

:func:`generate_diurnal` writes a deterministic synthetic
diurnal-curve capture (sinusoidal arrival intensity, seeded
payloads, no outcomes) in the same format, for load-shape
experiments before any real traffic exists.
"""
from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import env as _env
from . import capture as _capture
from . import quality as _quality
from . import slo as _slo

__all__ = ["ReplayDriver", "generate_diurnal"]

# verification verdicts, strongest to weakest. "deadline" is a
# replayed request whose per-request budget (recorded latency x
# CCSC_REPLAY_DEADLINE_SLACK) expired — an SLO verdict, distinct
# from "mismatch" (wrong bytes) and "lost" (no resolution at all)
STATUSES = (
    "match_exact", "match_psnr", "unverified", "mismatch",
    "deadline", "lost",
)

# a recorded latency shorter than this still gets a workable budget
# (warmup jitter on the replay side must not flag honest requests)
_DEADLINE_FLOOR_MS = 1000.0


def _percentiles(lat_ms) -> Tuple[Optional[float], Optional[float]]:
    h = _slo.Histogram.of(lat_ms)
    if not h.n:
        return None, None
    return h.percentile(0.50), h.percentile(0.99)


class ReplayDriver:
    """Re-serve one captured workload against a serving target.

    ``metrics_dir`` opens the replay's own telemetry run (algorithm
    ``serve_replay``); None replays silently (the returned report
    still carries everything). ``psnr_tol`` is the dB tolerance for
    cross-bucket verification (default ``CCSC_REPLAY_PSNR_TOL``).
    """

    def __init__(
        self,
        capture_dir: str,
        metrics_dir: Optional[str] = None,
        psnr_tol: Optional[float] = None,
        verbose: str = "brief",
    ):
        self.capture_dir = capture_dir
        self.metrics_dir = metrics_dir
        self.verbose = verbose
        self.psnr_tol = (
            float(psnr_tol)
            if psnr_tol is not None
            else float(_env.env_float("CCSC_REPLAY_PSNR_TOL"))
        )
        # deadline plumbing (ISSUE 19): when set, each replayed
        # request carries budget = max(recorded latency, floor) x
        # slack instead of the one-size-fits-all 600 s future wait;
        # None keeps replay deadline-free (the legacy contract)
        self.deadline_slack = _env.env_float(
            "CCSC_REPLAY_DEADLINE_SLACK"
        )
        self.meta = _capture.read_meta(capture_dir)
        self.requests = _capture.read_workload(capture_dir)
        self._payloads: Dict[str, np.ndarray] = {}

    # -- payload access (cached: dedup means one sha loads once) -------
    def _payload(self, sha: Optional[str]) -> Optional[np.ndarray]:
        if sha is None:
            return None
        arr = self._payloads.get(sha)
        if arr is None:
            arr = _capture.load_payload(self.capture_dir, sha)
            self._payloads[sha] = arr
        return arr

    def _arrays(self, req: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "b": self._payload(req.get("b")),
            "mask": self._payload(req.get("mask")),
            "smooth_init": self._payload(req.get("smooth_init")),
            "x_orig": self._payload(req.get("x_orig")),
        }

    # -- verification --------------------------------------------------
    def _verify(self, req: Dict[str, Any], res) -> str:
        out = req.get("outcome")
        if out is None:
            return "unverified"
        if res.bucket == out.get("bucket"):
            # same bucket program, same bytes in: determinism demands
            # the same bytes out
            digest = _capture.payload_sha(np.asarray(res.recon))
            return (
                "match_exact"
                if digest == out.get("digest")
                else "mismatch"
            )
        rec_psnr = out.get("psnr")
        got_db = res.psnr
        if got_db is None and rec_psnr is not None:
            # the served result carries no dB (the replay submit
            # dropped x_orig, or the target predates PSNR plumbing):
            # recompute with the SAME shared quality.valid_region_psnr
            # the recorder quoted, from the captured ground truth
            x_orig = self._payload(req.get("x_orig"))
            radius = self._psf_radius()
            if x_orig is not None and radius is not None:
                got_db = _quality.valid_region_psnr(
                    res.recon, x_orig, radius
                )
        if rec_psnr is not None and got_db is not None:
            return (
                "match_psnr"
                if abs(float(got_db) - float(rec_psnr))
                <= self.psnr_tol
                else "mismatch"
            )
        return "unverified"

    def _deadline_ms(self, req: Dict[str, Any]) -> Optional[float]:
        """The replayed request's end-to-end budget: the recorded
        latency (floored) scaled by ``CCSC_REPLAY_DEADLINE_SLACK``.
        None when the slack knob is unset (deadline-free replay) or
        the capture carries no recorded latency to scale."""
        if self.deadline_slack is None:
            return None
        out = req.get("outcome")
        lat = None if out is None else out.get("latency_ms")
        if lat is None:
            return None
        return max(float(lat), _DEADLINE_FLOOR_MS) * float(
            self.deadline_slack
        )

    def _psf_radius(self) -> Optional[Tuple[int, ...]]:
        # the capture meta's problem geometry (capture._write_meta)
        # gives the psf-radius border the recorder's dB crop used
        g = (self.meta or {}).get("geom") or {}
        sup = g.get("spatial_support")
        if not sup:
            return None
        return tuple(int(s) // 2 for s in sup)

    # -- the replay ----------------------------------------------------
    def replay(
        self,
        target,
        speed: float = 1.0,
        mode: str = "open",
        timeout_s: float = 600.0,
    ) -> Dict[str, Any]:
        """Replay the captured stream against ``target`` (a
        :class:`~.fleet.ServeFleet` or :class:`~.engine.CodecEngine`)
        and return the verification + latency report.

        ``speed`` scales the recorded inter-arrival gaps (2.0 = twice
        as fast); ``speed<=0`` is max-speed saturation. ``mode`` is
        ``'open'`` (recorded arrival clock) or ``'closed'`` (submit
        on completion)."""
        from ..utils import obs as _obs
        from .fleet import BucketCold, Overloaded

        import os as _os

        if mode not in ("open", "closed"):
            raise ValueError(
                f"mode must be 'open' | 'closed', got {mode!r}"
            )
        rec = getattr(target, "_capture", None)
        if rec is not None and _os.path.abspath(
            rec.path
        ) == _os.path.abspath(self.capture_dir):
            raise ValueError(
                "replay target is capturing into the very directory "
                "being replayed — it would append every replayed "
                "request as a duplicate-key record and corrupt the "
                "capture (build the replay fleet with "
                "capture_dir='' to force capture off)"
            )
        is_fleet = hasattr(target, "fleet_cfg")
        run = _obs.start_run(
            self.metrics_dir,
            algorithm="serve_replay",
            verbose=self.verbose,
            compile_monitor=False,
            capture_dir=self.capture_dir,
            mode=mode,
            speed=speed,
            n_recorded=len(self.requests),
        )
        try:
            return self._replay_inner(
                target, run, speed, mode, timeout_s, is_fleet,
                # both explicit-backpressure refusals retry the same
                # way: an overloaded queue and a still-staging bucket
                # each carry a retry_after_s hint
                (Overloaded, BucketCold),
            )
        finally:
            if not run.closed:
                run.close(status="ok")

    def _submit_one(
        self, target, rkey, arrays, is_fleet, overloaded_cls,
        bank_id=None, tenant=None, deadline_ms=None,
    ):
        """Submit with explicit-backpressure retries; returns
        (future, n_overload_backoffs, t_submit). Admission refusals
        are honored (sleep the retry-after hint) and retried until
        admitted — replay's zero-lost contract sheds nothing.
        ``rkey`` is a replay-unique key, NOT the recorded one: a
        multi-session capture legitimately repeats idempotency keys
        (auto-keys restart per fleet), and resubmitting a spent key
        would be refused. ``bank_id``/``tenant`` are the RECORDED
        routing identities: a mixed-tenant capture replays each
        request against its own bank (per-bank digest parity) under
        its own tenant accounting — the replay target must have the
        same banks published and tenants declared. ``t_submit`` is
        taken after the last refusal, so backoff sleeps never inflate
        the replayed latency — the recorded side only ever measures
        admitted submit->delivery, and the comparison must too."""
        n_over = 0
        route = {"bank_id": bank_id, "tenant": tenant}
        # the budget clock starts at the ADMITTED submit, same as
        # t_sub: backoff sleeps never eat into the request's deadline
        while True:
            t_sub = time.perf_counter()
            try:
                if is_fleet:
                    return (
                        target.submit(
                            arrays["b"],
                            mask=arrays["mask"],
                            smooth_init=arrays["smooth_init"],
                            x_orig=arrays["x_orig"],
                            key=rkey,
                            deadline_ms=deadline_ms,
                            **route,
                        ),
                        n_over,
                        t_sub,
                    )
                return (
                    target.submit(
                        arrays["b"],
                        mask=arrays["mask"],
                        smooth_init=arrays["smooth_init"],
                        x_orig=arrays["x_orig"],
                        deadline_ms=deadline_ms,
                        **route,
                    ),
                    n_over,
                    t_sub,
                )
            except overloaded_cls as e:
                n_over += 1
                time.sleep(min(e.retry_after_s, 5.0))

    def _replay_inner(
        self, target, run, speed, mode, timeout_s, is_fleet,
        overloaded_cls,
    ) -> Dict[str, Any]:
        reqs = self.requests
        t_start = time.perf_counter()
        inflight: List[Tuple[Dict, Any, float]] = []
        # verdicts, not results: each ServedResult is verified (and
        # its reconstruction dropped) the moment we collect it — a
        # thousands-of-requests replay must not hold every recon
        # array until the report
        verdicts: List[Tuple[Dict, str, float, Optional[str]]] = []
        n_overloaded = 0
        for i, req in enumerate(reqs):
            arrays = self._arrays(req)
            if mode == "open" and speed > 0:
                due = t_start + req.get("t_rel", 0.0) / speed
                lag = due - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
            dl_ms = self._deadline_ms(req)
            fut, n_over, t_sub = self._submit_one(
                target, f"replay-{i:06d}", arrays, is_fleet,
                overloaded_cls,
                bank_id=req.get("bank_id"),
                tenant=req.get("tenant"),
                deadline_ms=dl_ms,
            )
            n_overloaded += n_over
            if mode == "closed":
                verdicts.append(
                    self._settle(req, fut, t_sub, timeout_s, dl_ms)
                )
            else:
                inflight.append((req, fut, t_sub, dl_ms))
        # submitted payloads now live in the target's own queue; drop
        # the reader cache so delivered requests' arrays can be freed
        self._payloads.clear()
        while inflight:
            req, fut, t_sub, dl_ms = inflight.pop(0)
            verdicts.append(
                self._settle(req, fut, t_sub, timeout_s, dl_ms)
            )
        elapsed = time.perf_counter() - t_start
        return self._report(
            run, verdicts, elapsed, speed, mode, n_overloaded,
            target, is_fleet,
        )

    def _settle(
        self, req, fut, t_sub, timeout_s, deadline_ms=None
    ) -> Tuple[Dict, str, float, Optional[str]]:
        """Wait one future out and reduce it to its verdict
        (status, latency, served bucket) — the result arrays are
        released here, not carried to the report. With deadline
        plumbing active, the wait is the request's own remaining
        budget (plus slack for the expiry round trip) instead of the
        one-size-fits-all ``timeout_s``, and an expiry resolves as
        the distinct ``deadline`` verdict, never a mismatch."""
        from .fleet import DeadlineExceeded

        wait_s = timeout_s
        if deadline_ms is not None:
            left = deadline_ms / 1e3 - (
                time.perf_counter() - t_sub
            )
            # the serving side expires it; this wait only has to
            # outlive that expiry landing on the future
            wait_s = min(timeout_s, max(left, 0.0) + 5.0)
        try:
            res = fut.result(timeout=wait_s)
        except DeadlineExceeded:
            return req, "deadline", 0.0, None
        except Exception:
            return req, "lost", 0.0, None
        lat_ms = (time.perf_counter() - t_sub) * 1e3
        return req, self._verify(req, res), lat_ms, res.bucket

    def _report(
        self, run, verdicts, elapsed, speed, mode, n_overloaded,
        target, is_fleet,
    ) -> Dict[str, Any]:
        counts = {s: 0 for s in STATUSES}
        replayed_lat: List[float] = []
        recorded_lat: List[float] = []
        for req, status, lat_ms, bucket in verdicts:
            counts[status] += 1
            if status not in ("lost", "deadline"):
                replayed_lat.append(lat_ms)
            out = req.get("outcome")
            if out is not None and out.get("latency_ms") is not None:
                recorded_lat.append(out["latency_ms"])
            run.event(
                "replay_request",
                key=req["key"],
                status=status,
                tenant=req.get("tenant"),
                bank_id=req.get("bank_id"),
                latency_ms=round(lat_ms, 3),
                recorded_latency_ms=(
                    None if out is None else out.get("latency_ms")
                ),
                bucket=bucket,
            )
        rec_p50, rec_p99 = _percentiles(recorded_lat)
        rep_p50, rep_p99 = _percentiles(replayed_lat)
        n = len(verdicts)
        rps = n / elapsed if elapsed > 0 else 0.0
        report: Dict[str, Any] = {
            "mode": mode,
            "speed": speed,
            "n_recorded": len(self.requests),
            "n_replayed": n,
            "n_lost": counts["lost"],
            "n_mismatched": counts["mismatch"],
            "n_deadline": counts["deadline"],
            "n_exact": counts["match_exact"],
            "n_psnr": counts["match_psnr"],
            "n_unverified": counts["unverified"],
            "replay_overload_backoffs": n_overloaded,
            "recorded_rejected": self.meta.get("n_rejected"),
            "recorded_p50_ms": rec_p50,
            "recorded_p99_ms": rec_p99,
            "replayed_p50_ms": rep_p50,
            "replayed_p99_ms": rep_p99,
            "elapsed_s": round(elapsed, 4),
            "requests_per_sec": round(rps, 4),
            "ok": counts["lost"] == 0 and counts["mismatch"] == 0,
        }
        run.event(
            "replay_summary",
            mode=mode,
            speed=speed,
            n_recorded=report["n_recorded"],
            n_replayed=n,
            n_lost=report["n_lost"],
            n_mismatched=report["n_mismatched"],
            n_deadline=report["n_deadline"],
            n_exact=report["n_exact"],
            n_psnr=report["n_psnr"],
            n_unverified=report["n_unverified"],
            replay_overload_backoffs=n_overloaded,
            recorded_rejected=report["recorded_rejected"],
            recorded_p50_ms=rec_p50,
            recorded_p99_ms=rec_p99,
            replayed_p50_ms=rep_p50,
            replayed_p99_ms=rep_p99,
            elapsed_s=report["elapsed_s"],
            requests_per_sec=report["requests_per_sec"],
        )
        led = self._ledger_append(report, target, is_fleet)
        if led is not None:
            run.event(
                "ledger_append",
                key=led["key"],
                value=led["value"],
                unit=led["unit"],
                path=led["path"],
            )
            report["ledger_key"] = led["key"]
        run.console(
            f"replay: {n} request(s) at {mode}/"
            + ("max-speed" if speed <= 0 else f"{speed:g}x")
            + f", {report['n_exact']} bit-exact, "
            f"{report['n_psnr']} psnr-matched, "
            f"{report['n_mismatched']} mismatched, "
            f"{report['n_deadline']} deadline, "
            f"{report['n_lost']} lost",
            tier="brief",
        )
        return report

    def _ledger_append(
        self, report: Dict[str, Any], target, is_fleet
    ) -> Optional[Dict[str, Any]]:
        """Append this replay session to the durable perf ledger
        (kind=replay, requests/sec) so scripts/perf_gate.py gates
        replay throughput against its own per-configuration history.
        Never raises — the ledger must not fail a replay."""
        try:
            from ..analysis import ledger as _ledger
            from ..tune import store as tune_store
            from ..utils import obs as _obs
            from ..utils import perfmodel

            if not _ledger.enabled() or report["n_replayed"] <= 0:
                return None
            chip = perfmodel.detect_chip()
            if not chip:
                return None
            geom = target.geom
            buckets = (
                target.buckets if is_fleet else target._buckets
            )
            spatial = max(
                (sp for _s, sp in buckets), key=lambda sp: tuple(sp)
            )
            workload = tune_store.solve_workload(geom)
            rec = _ledger.maybe_append(
                chip=chip,
                kind="replay",
                workload=workload,
                shape_key=tune_store.solve_shape_key(
                    workload,
                    k=geom.num_filters,
                    support=tuple(geom.spatial_support),
                    spatial=tuple(spatial),
                ),
                knobs={
                    "mode": report["mode"],
                    "speed": report["speed"],
                    "replicas": (
                        target.fleet_cfg.replicas if is_fleet else 1
                    ),
                },
                value=report["requests_per_sec"],
                unit="requests/sec",
                git_sha=_obs.git_sha(),
                source="serve.replay",
            )
            if rec is None:
                return None
            return {
                "key": _ledger.record_key(rec),
                "value": rec["value"],
                "unit": rec["unit"],
                "path": _ledger.default_ledger_path(),
            }
        except Exception:  # pragma: no cover - defensive
            return None


# ---------------------------------------------------------------------
# synthetic diurnal workload
# ---------------------------------------------------------------------


def generate_diurnal(
    path: str,
    n_requests: int = 64,
    duration_s: float = 60.0,
    spatial: Tuple[int, int] = (24, 24),
    keep: float = 0.5,
    amp: float = 0.8,
    seed: int = 0,
) -> str:
    """Write a deterministic synthetic diurnal-curve capture.

    Arrival times follow a sinusoidal intensity —
    ``rate(t) ∝ 1 + amp·sin(2π·t/T − π/2)`` (trough at t=0, peak at
    mid-stream, the compressed shape of a day's traffic) — placed by
    inverse-CDF of the cumulative intensity, so the same (n,
    duration, amp, seed) always yields byte-identical requests and
    the identical arrival clock. Payloads are seeded masked images
    with ground truth (``x_orig``) attached, so a replay of the
    synthetic stream still measures PSNR. No outcomes are recorded
    (there was no serve) — replay marks these ``unverified`` and the
    stream functions as a pure load shape."""
    rng = np.random.default_rng(seed)
    # inverse-CDF placement on a fine grid of the cumulative intensity
    grid = np.linspace(0.0, duration_s, 4096)
    rate = 1.0 + amp * np.sin(
        2.0 * math.pi * grid / max(duration_s, 1e-9) - math.pi / 2.0
    )
    cum = np.concatenate([[0.0], np.cumsum(rate[:-1] + rate[1:])])
    cum /= max(cum[-1], 1e-12)
    targets = (np.arange(n_requests) + 0.5) / n_requests
    arrivals = np.interp(targets, cum, grid)
    rec = _capture.WorkloadRecorder(path, sample=1.0)
    h, w = int(spatial[0]), int(spatial[1])
    for i, t_rel in enumerate(arrivals):
        x = rng.random((h, w), dtype=np.float64).astype(np.float32)
        m = (rng.random((h, w)) < keep).astype(np.float32)
        key = f"diurnal-{i:06d}"
        # curve time, not generation time: t_rel comes from the
        # intensity inversion so generation speed never leaks into
        # the workload
        rec.record_submit(
            key, None, x * m, mask=m, x_orig=x, t_rel=float(t_rel),
        )
    rec.close(
        synthetic="diurnal",
        duration_s=duration_s,
        amp=amp,
        seed=seed,
        keep=keep,
    )
    return path
