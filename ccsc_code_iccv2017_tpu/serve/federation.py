"""Cross-host federated serving over the durable file-lease queue.

One :class:`~.fleet.ServeFleet` already survives anything short of its
own process dying. Federation is the next level of the same ladder:
N fleet PROCESSES — typically one per host, each supervised by
``scripts/supervise.py --child`` — share nothing but a
:class:`~.dqueue.DurableQueue` directory (the MPAX
fleet-of-jit-cached-solvers shape scaled past one process, PAPERS.md
arXiv:2412.09734), so a SIGKILL of an entire fleet process is just an
expired lease the survivors reap:

- :class:`FederatedHost` runs the existing in-process fleet as a
  **drain worker**: claim items from the shared queue (at most the
  fleet's own slot capacity in flight), submit each ownership to the
  fleet under a per-attempt idempotency key, and on delivery write
  the result durably back through :meth:`~.dqueue.DurableQueue.
  complete` — content-digested bytes, the same sha256 the capture
  oracle records, so cross-host parity is bit-checkable. A heartbeat
  thread renews the host's lease epoch and runs the reaper, so every
  host is also every other host's undertaker.
- :class:`FederatedFrontend` is the thin client: ``submit`` writes a
  durable request (payloads content-addressed), returns a Future, and
  a poller resolves it from the durable result file whichever host
  produced it. ``seal()`` announces end-of-stream; hosts draining
  until sealed exit once the queue is empty.

Request-level traces cross the host boundary: the frontend opens the
root span, the item record carries ``trace_id``/``root_span`` through
the queue, each serving host writes its ownership RETROSPECTIVELY
(start + end in one emit — a killed host can never orphan a span),
and the reaper writes the dead host's ownership the same way when it
requeues. Merging the frontend's and every host's metrics dirs
reassembles each request as one complete story with both ownerships
visible (``utils.trace.assemble`` — the acceptance contract of
tests/test_federation.py).

Delivery semantics are PR 7's, made cross-host: at-most-once (the
spent marker is the atomic tiebreak; late stragglers are fenced by
lease epoch and suppressed), exactly-once-or-error (a cross-host
attempt budget in the item record; exhaustion writes an explicit
error result), and spent keys stay spent across the whole pool.
"""
from __future__ import annotations

import dataclasses
import os
import queue as _pyqueue
import socket
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

from ..utils import env as _env
from ..utils import trace as trace_util
from .dqueue import DurableQueue
from .fleet import (
    BucketCold,
    DeadlineExceeded,
    Overloaded,
    ServeFleet,
)

__all__ = [
    "FederatedHost",
    "FederatedHostPool",
    "FederatedFrontend",
    "FederatedResult",
]


class FederatedResult(NamedTuple):
    """One federated request's resolution, rebuilt from the durable
    result record (the cross-host analog of
    :class:`~.engine.ServedResult`)."""

    key: str
    recon: np.ndarray
    psnr: Optional[float]
    bucket: Optional[str]
    iters: Optional[int]
    latency_ms: float  # frontend-measured submit -> resolution
    host_latency_ms: Optional[float]  # serving host's solve latency
    digest: str  # sha256 of the reconstruction bytes
    host: Optional[str]  # the host that delivered
    attempts: int  # cross-host ownerships it took
    trace_id: Optional[str]


def _default_host() -> str:
    return f"{socket.gethostname()}-{os.getpid()}"


@dataclasses.dataclass
class _PendingReq:
    key: str
    future: Future
    t_submit: float  # perf_counter
    t_wall: float
    trace_id: str
    root_span: str
    deadline: Optional[float] = None  # absolute wall clock


class FederatedFrontend:
    """Submit requests into the shared queue and resolve them from
    the durable result files — no backend, no engine, importable on
    a host that has never seen a chip."""

    def __init__(
        self,
        queue_dir: str,
        client: Optional[str] = None,
        metrics_dir: Optional[str] = None,
        verbose: str = "brief",
        poll_s: Optional[float] = None,
    ):
        from ..utils import obs

        self.client = client or "client-" + _default_host()
        self.poll_s = (
            float(poll_s)
            if poll_s is not None
            else float(_env.env_float("CCSC_FED_POLL_S"))
        )
        self._run = obs.start_run(
            metrics_dir,
            algorithm="serve_federation_frontend",
            verbose=verbose,
            compile_monitor=False,
            queue_dir=queue_dir,
            client=self.client,
        )
        self.queue = DurableQueue(
            queue_dir, host=self.client, emit=self._emit
        )
        self._lock = threading.Lock()
        self._pending: Dict[str, _PendingReq] = {}
        self._seq = 0
        self.n_submitted = 0
        self.n_delivered = 0
        self.n_failed = 0
        self.n_cancelled = 0
        self._closed = False
        self._stop = threading.Event()
        self._poller = threading.Thread(
            target=self._poll_loop, name="ccsc-fed-frontend",
            daemon=True,
        )
        self._poller.start()

    def _emit(self, type_: str, **fields) -> None:
        self._run.event(type_, **fields)

    # -- submit --------------------------------------------------------
    def submit(
        self,
        b,
        mask=None,
        smooth_init=None,
        x_orig=None,
        key: Optional[str] = None,
        deadline_ms: Optional[float] = None,
    ) -> "Future[FederatedResult]":
        """Durably enqueue one request for the host pool; returns a
        Future resolved by the poller once ANY host delivers (or the
        pool fails it). A spent key is refused (ValueError) — the
        cross-host exactly-once-or-error contract.

        ``deadline_ms`` (default ``CCSC_REQ_DEADLINE_MS``) is the
        END-TO-END budget, stamped here as an absolute wall clock on
        the durable item — every hand-off downstream (claim, fleet
        admission, engine dispatch) sees the REMAINING budget shrink,
        and an expired item resolves as a durable ``deadline`` error
        instead of being solved. Cancelling the returned future is
        cooperative cancellation: the poller writes a durable cancel
        marker so no host ever solves the withdrawn request."""
        if self._closed:
            raise RuntimeError("frontend is closed")
        if deadline_ms is None:
            deadline_ms = _env.env_float("CCSC_REQ_DEADLINE_MS")
        trace_id = trace_util.new_trace_id()
        root_span = trace_util.new_span_id()
        t_wall = time.time()
        deadline = (
            None if deadline_ms is None
            else t_wall + float(deadline_ms) / 1e3
        )
        with self._lock:
            self._seq += 1
            if key is None:
                key = f"{self.client}-{self._seq:08d}"
            elif key in self._pending:
                # in-flight resubmit returns the SAME future (the
                # fleet submit contract, held at the frontend since
                # the queue cannot cheaply scan for duplicates)
                return self._pending[key].future
            # register BEFORE the durable write: check-then-register
            # split across a lock release would let two concurrent
            # submits of one key both pass the check, double-enqueue
            # the item, and strand the first caller's future
            req = _PendingReq(
                key=key,
                future=Future(),
                t_submit=time.perf_counter(),
                t_wall=t_wall,
                trace_id=trace_id,
                root_span=root_span,
                deadline=deadline,
            )
            self._pending[key] = req
            self.n_submitted += 1
        # the durable write happens OUTSIDE the lock (sha256 + file
        # I/O must not serialize submitters against the poller); the
        # poller cannot resolve the key early — no host has seen the
        # item yet
        try:
            self.queue.submit(
                key,
                b,
                mask=mask,
                smooth_init=smooth_init,
                x_orig=x_orig,
                trace_id=trace_id,
                root_span=root_span,
                deadline=deadline,
            )
        except BaseException as e:
            # a refused (spent) or failed durable write un-registers
            # the key; a concurrent duplicate submit that grabbed the
            # same future learns the refusal through it
            with self._lock:
                self._pending.pop(key, None)
                self.n_submitted -= 1
            try:
                req.future.set_exception(e)
            except Exception:
                pass
            raise
        trace_util.start_span(
            self._emit,
            trace_id=trace_id,
            span=trace_util.ROOT_SPAN,
            span_id=root_span,
            ts=t_wall,
            key=key,
            deadline=(
                None if deadline is None else round(deadline, 3)
            ),
        )
        return req.future

    def reconstruct(self, b, timeout: Optional[float] = None, **kw):
        """Synchronous submit-and-wait."""
        return self.submit(b, **kw).result(timeout=timeout)

    def serve_many(
        self, requests, timeout: Optional[float] = None
    ) -> List[FederatedResult]:
        futs = [self.submit(**req) for req in requests]
        return [f.result(timeout=timeout) for f in futs]

    def seal(self) -> None:
        """Announce end-of-stream to the host pool."""
        self.queue.seal()

    # -- the poller ----------------------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._poll_once()
            except Exception as e:
                # one bad record or transient I/O error must not kill
                # the only thread that resolves futures — every other
                # pending request would hang forever
                self._run.console(
                    f"federation: frontend poll error "
                    f"({type(e).__name__}: {e}) — retrying",
                    tier="always",
                )

    def _poll_once(self) -> int:
        from .dqueue import safe_key

        cancelled: List[_PendingReq] = []
        with self._lock:
            for ckey, creq in list(self._pending.items()):
                if creq.future.cancelled():
                    # cooperative cancellation: the client gave up on
                    # the future, so withdraw the durable item too —
                    # without the marker the item would stay live in
                    # the queue forever and some host would solve
                    # work nobody awaits
                    self._pending.pop(ckey, None)
                    cancelled.append(creq)
            keys = list(self._pending)
        for creq in cancelled:
            # durable cancel marker (spent fence): a later claim of
            # the queued/requeued item refuses it. Resolving the
            # pending entry keeps key-reuse policy-consistent with
            # spent keys — a resubmit of the key is refused by the
            # queue, not silently re-registered.
            self.queue.cancel(creq.key)
            with self._lock:
                self.n_cancelled += 1
            trace_util.end_span(
                self._emit,
                trace_id=creq.trace_id,
                span=trace_util.ROOT_SPAN,
                span_id=creq.root_span,
                status="cancelled",
                t_start=creq.t_wall,
                key=creq.key,
            )
        if not keys:
            return len(cancelled)
        # one directory scan per tick, then read only the records
        # that actually landed — N pending keys must not cost N
        # open() round trips against a shared (possibly remote)
        # filesystem every 50 ms
        present = self.queue.result_names()
        resolved = 0
        for key in keys:
            if safe_key(key) + ".json" not in present:
                continue
            rec = self.queue.result(key)
            if rec is None:
                continue  # torn mid-write: next tick
            with self._lock:
                req = self._pending.pop(key, None)
            if req is None:
                continue
            self._resolve(req, rec)
            resolved += 1
        return resolved

    def _resolve(self, req: _PendingReq, rec: Dict[str, Any]) -> None:
        lat_ms = (time.perf_counter() - req.t_submit) * 1e3
        status = rec.get("status")
        ok = status == "ok"
        err: Optional[BaseException] = None
        res: Optional[FederatedResult] = None
        if ok:
            try:
                recon = self.queue.load_array(rec.get("recon"))
            except (OSError, ValueError) as e:
                ok = False
                err = RuntimeError(
                    f"request {req.key!r}: result payload unreadable "
                    f"({type(e).__name__}: {e})"
                )
        if ok:
            res = FederatedResult(
                key=req.key,
                recon=recon,
                psnr=rec.get("psnr"),
                bucket=rec.get("bucket"),
                iters=rec.get("iters"),
                latency_ms=lat_ms,
                host_latency_ms=rec.get("latency_ms"),
                digest=rec.get("digest"),
                host=rec.get("host"),
                attempts=int(rec.get("attempts", 0)),
                trace_id=req.trace_id,
            )
        elif err is None:
            if status == "deadline":
                # the pool durably refused the expired item — the
                # client sees the SAME exception type the in-process
                # fleet raises, with the stamped deadline attached
                err = DeadlineExceeded(
                    "claim", float(rec.get("deadline") or 0.0)
                )
            else:
                err = RuntimeError(
                    rec.get("error")
                    or f"request {req.key!r} failed in the host pool"
                )
        span_status = "ok" if ok else (
            status if status in ("deadline", "cancelled") else "error"
        )
        trace_util.end_span(
            self._emit,
            trace_id=req.trace_id,
            span=trace_util.ROOT_SPAN,
            span_id=req.root_span,
            status=span_status,
            t_start=req.t_wall,
            key=req.key,
            attempts=int(rec.get("attempts", 0)),
        )
        with self._lock:
            if ok:
                self.n_delivered += 1
            else:
                self.n_failed += 1
        try:
            if ok:
                req.future.set_result(res)
            else:
                req.future.set_exception(err)
        except Exception:
            pass  # client cancelled the future; the result stands

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._poller.join(timeout=30.0)
        self._poll_once()  # final sweep: results that landed mid-stop
        with self._lock:
            leftovers = list(self._pending.values())
            self._pending.clear()
        wall = time.time()
        for req in leftovers:
            trace_util.end_span(
                self._emit,
                trace_id=req.trace_id,
                span=trace_util.ROOT_SPAN,
                span_id=req.root_span,
                status="shutdown",
                ts=wall,
                t_start=req.t_wall,
            )
            try:
                req.future.set_exception(
                    RuntimeError(
                        "frontend closed before this request resolved "
                        "(the durable item remains in the queue; a "
                        "new frontend can poll its key)"
                    )
                )
            except Exception:
                pass
        if not self._run.closed:
            self._run.close(
                status="ok",
                n_submitted=self.n_submitted,
                n_delivered=self.n_delivered,
                n_failed=self.n_failed,
                n_cancelled=self.n_cancelled,
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FederatedHost:
    """One host of the pool: the existing in-process
    :class:`~.fleet.ServeFleet` run as a drain worker against the
    shared queue.

    The drain thread claims at most the fleet's slot capacity, submits
    each ownership under a per-attempt fleet key (``key#aN`` — a
    re-claimed item after a suppressed delivery can never collide with
    this fleet's previous ownership of the same key), honors the
    fleet's :class:`~.fleet.Overloaded` backpressure by deferring the
    claimed item for the (jittered) retry hint, and writes every
    delivery durably back through the queue. The beat thread renews
    the host's heartbeat, runs the reaper, and emits
    ``fed_heartbeat``.
    """

    def __init__(
        self,
        queue_dir: str,
        d,
        prob,
        cfg,
        serve_cfg,
        fleet_cfg,
        blur_psf=None,
        host: Optional[str] = None,
        metrics_dir: Optional[str] = None,
        verbose: str = "brief",
        poll_s: Optional[float] = None,
        heartbeat_s: Optional[float] = None,
        ttl_s: Optional[float] = None,
        skew_s: Optional[float] = None,
        max_attempts: Optional[int] = None,
    ):
        from ..utils import obs

        self.host = host or _default_host()
        self.poll_s = (
            float(poll_s)
            if poll_s is not None
            else float(_env.env_float("CCSC_FED_POLL_S"))
        )
        self.heartbeat_s = (
            float(heartbeat_s)
            if heartbeat_s is not None
            else float(_env.env_float("CCSC_FED_HEARTBEAT_S"))
        )
        self._run = obs.start_run(
            metrics_dir,
            algorithm="serve_federation",
            verbose=verbose,
            queue_dir=queue_dir,
            fed_host=self.host,
        )
        self.queue = DurableQueue(
            queue_dir,
            host=self.host,
            emit=self._emit,
            ttl_s=ttl_s,
            skew_s=skew_s,
            max_attempts=max_attempts,
        )
        # the fleet's own stream nests under this host's metrics dir
        # (replica streams nest under the fleet's in turn); one
        # recursive read_events merges the whole host
        if (
            metrics_dir is not None
            and fleet_cfg.metrics_dir is None
        ):
            fleet_cfg = dataclasses.replace(
                fleet_cfg,
                metrics_dir=os.path.join(metrics_dir, "fleet"),
            )
        self._closed = False
        self._close_lock = threading.Lock()
        try:
            self.fleet = ServeFleet(
                d, prob, cfg, serve_cfg, fleet_cfg, blur_psf=blur_psf
            )
        except BaseException:
            self._run.close(status="error")
            raise
        self.capacity = self.fleet.capacity_hint * 2
        self.served = 0
        self.n_failed = 0
        self._inflight: Dict[str, Dict[str, Any]] = {}  # name -> item
        self._deferred: List = []  # (t_due_monotonic, item)
        self._done: "_pyqueue.Queue" = _pyqueue.Queue()
        self.epoch = self.queue.join()
        self._emit("fed_join", host=self.host, epoch=self.epoch)
        self._stop = threading.Event()  # stops the drain worker
        # the beat thread has ITS OWN stop: close() must keep
        # heartbeating through the (possibly long) fleet drain-close
        # or this host's own in-flight leases expire mid-drain and
        # its completes get suppressed while survivors re-solve them
        self._stop_beat = threading.Event()
        self._fatal = False  # the fleet can no longer serve, ever
        self._drained_sealed = threading.Event()
        self._drain_thread = threading.Thread(
            target=self._drain_loop, name="ccsc-fed-drain",
            daemon=True,
        )
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="ccsc-fed-beat", daemon=True,
        )
        self._drain_thread.start()
        self._beat_thread.start()
        self._run.console(
            f"federation: host {self.host} (epoch {self.epoch}) "
            f"joined {queue_dir}, claim capacity {self.capacity}",
            tier="brief",
        )

    def _emit(self, type_: str, **fields) -> None:
        self._run.event(type_, **fields)

    # -- the drain worker ----------------------------------------------
    def _drain_loop(self) -> None:
        errors = 0
        while not self._stop.is_set():
            try:
                moved = self._settle_done()
                moved += self._submit_deferred()
                # deferred items hold leases too: an Overloaded fleet
                # must not keep claiming fresh items every tick and
                # hoard the queue away from healthy hosts
                room = (
                    self.capacity
                    - len(self._inflight)
                    - len(self._deferred)
                )
                if room > 0:
                    for item in self.queue.claim(limit=room):
                        self._dispatch(item)
                        moved += 1
                if (
                    not self._inflight
                    and not self._deferred
                    and self.queue.sealed
                    and self.queue.drained
                ):
                    self._drained_sealed.set()
                errors = 0
            except Exception as e:
                # a transient I/O error (disk full, a shared-fs
                # hiccup) must not kill the drain thread while the
                # beat thread keeps this host's leases alive forever
                # — the exact stranding federation exists to prevent.
                # Back off and retry; give up for good only after a
                # sustained streak (survivors then reap our leases
                # once the heartbeat stops).
                errors += 1
                self._run.console(
                    f"federation: drain error ({type(e).__name__}: "
                    f"{e}) — retry {errors}/10",
                    tier="always",
                )
                if errors >= 10:
                    self._retire(f"sustained drain errors: {e}")
                    return
                moved = 0
                self._stop.wait(min(0.25 * errors, 2.0))
            if not moved:
                self._stop.wait(self.poll_s)

    def _retire(self, why: str) -> None:
        """This host can no longer serve (dead fleet, broken queue
        I/O): stop draining AND heartbeating so the pool sees a dead
        host and reaps whatever we still hold — a retiring host that
        kept claiming would steal items from healthy hosts in a hot
        loop. Unblocks serve_until_sealed; close() finishes the
        cleanup."""
        self._fatal = True
        self._run.console(
            f"federation: host {self.host} retiring — {why}",
            tier="always",
        )
        self._stop.set()
        self._stop_beat.set()
        self._drained_sealed.set()

    def _settle_done(self) -> int:
        n = 0
        while True:
            try:
                item, fut = self._done.get_nowait()
            except _pyqueue.Empty:
                return n
            self._settle(item, fut)
            n += 1

    def _submit_deferred(self) -> int:
        if not self._deferred:
            return 0
        now = time.monotonic()
        due = [x for x in self._deferred if x[0] <= now]
        self._deferred = [x for x in self._deferred if x[0] > now]
        for _t, item in due:
            self._dispatch(item)
        return len(due)

    def _dispatch(self, item: Dict[str, Any]) -> None:
        from ..utils import validate

        dl = item.get("deadline")
        dl = None if dl is None else float(dl)
        if dl is not None and time.time() >= dl:
            # the budget ran out AFTER our claim (typically while the
            # item sat deferred behind an Overloaded/BucketCold
            # fleet): resolve it durably as expired before paying for
            # the payload loads
            self.queue.expire(item)
            return
        try:
            arrays = {
                f: self.queue.load_array(item.get(f))
                for f in ("b", "mask", "smooth_init", "x_orig")
            }
        except (OSError, ValueError) as e:
            self.queue.fail(
                item, f"payload unreadable: {type(e).__name__}: {e}"
            )
            return
        # per-attempt fleet key: this host may legitimately own the
        # same queue key twice (suppressed delivery, later re-claim)
        # and the in-process fleet's spent-key refusal must not
        # conflate the two ownerships
        fkey = f"{item['key']}#a{item['attempts']}"
        try:
            fut = self.fleet.submit(
                arrays["b"],
                mask=arrays["mask"],
                smooth_init=arrays["smooth_init"],
                x_orig=arrays["x_orig"],
                key=fkey,
                # ABSOLUTE pass-through: the remaining budget shrinks
                # through the hand-off instead of resetting
                _deadline=dl,
            )
        except DeadlineExceeded:
            # fleet admission judged it already dead (must be caught
            # BEFORE the RuntimeError release path — expiry is a
            # verdict on the request, not on this host's fleet)
            self.queue.expire(item)
            return
        except (Overloaded, BucketCold) as e:
            # explicit backpressure: hold OUR lease (heartbeats keep
            # it live) and re-offer after the jittered hint. A
            # BucketCold host (staged warmup still building this
            # bucket's program) defers exactly like an overloaded
            # one — the request is fine, the host just isn't ready
            # for THAT bucket yet
            self._deferred.append(
                (time.monotonic() + e.retry_after_s, item)
            )
            return
        except validate.CCSCInputError as e:
            self.queue.fail(item, f"invalid request: {e}")
            return
        except RuntimeError as e:
            # fleet closed / all replicas abandoned — release the
            # lease so a healthy host serves it
            self.queue.release(item)
            if not (self._stop.is_set() or self.fleet.closed):
                # not a shutdown: the fleet is permanently unable to
                # serve (e.g. every replica's restart budget is
                # exhausted). Claiming again would hot-spin the same
                # claim/release rename forever — retire instead
                self._retire(f"fleet cannot serve: {e}")
            return
        self._inflight[item["name"]] = item
        fut.add_done_callback(
            lambda f, item=item: self._done.put((item, f))
        )

    def _settle(self, item: Dict[str, Any], fut: Future) -> None:
        self._inflight.pop(item["name"], None)
        try:
            res = fut.result()
        except DeadlineExceeded:
            # expired inside the fleet/engine mid-ownership: the
            # durable resolution says deadline, not error — the
            # client can tell honesty from failure
            self.queue.expire(item)
            return
        except BaseException as e:
            if self._stop.is_set() or self.fleet.closed:
                # shutdown, not a verdict on the request: hand the
                # lease back for the survivors
                self.queue.release(item)
            else:
                self.n_failed += 1
                self.queue.fail(
                    item, f"{type(e).__name__}: {e}"
                )
            return
        delivered = self.queue.complete(
            item,
            res.recon,
            psnr=res.psnr,
            latency_ms=res.latency_s * 1e3,
            bucket=res.bucket,
            iters=int(res.trace.num_iters),
        )
        if delivered:
            self.served += 1
        if item.get("trace_id"):
            # this host's ownership, written retrospectively (one
            # emit, start + end): a SIGKILL mid-solve can never
            # orphan it — the reaper writes the dead ownership
            # instead when it requeues
            trace_util.emit_span(
                self._emit,
                trace_id=item["trace_id"],
                span="attempt",
                parent_span=item.get("root_span"),
                t_start=float(item.get("lease_t") or time.time()),
                t_end=time.time(),
                status="ok" if delivered else "suppressed",
                host=self.host,
                attempt=int(item.get("attempts", 0)),
            )

    # -- heartbeat + reaper --------------------------------------------
    def _beat_loop(self) -> None:
        # first beat IMMEDIATELY, not one cadence in: a host that
        # joins, drains a short queue, and leaves inside a single
        # heartbeat_s window must still be visible in the stream (and
        # to per-host liveness) as having been alive at all
        self._beat_once()
        while not self._stop_beat.wait(self.heartbeat_s):
            self._beat_once()

    def _beat_once(self) -> None:
        try:
            leased = len(self._inflight) + len(self._deferred)
            self.queue.heartbeat(leased=leased, served=self.served)
            self.queue.reap()
            self._emit(
                "fed_heartbeat",
                host=self.host,
                epoch=self.epoch,
                leased=leased,
                served=self.served,
            )
        except Exception as e:
            # the drain loop retries transient I/O errors; its
            # heartbeat must survive the same blip — a dead beat
            # thread under a live drain would let survivors reap and
            # re-solve everything this host is still serving
            self._run.console(
                f"federation: heartbeat error ({type(e).__name__}: "
                f"{e}) — retrying",
                tier="always",
            )

    # -- lifecycle -----------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def serve_until_sealed(
        self, timeout: Optional[float] = None
    ) -> bool:
        """Block until the queue is sealed AND fully drained (every
        item resolved somewhere in the pool) — or until this host
        retired itself because its fleet can no longer serve (check
        ``fatal``; the caller should close() either way). Returns
        False on timeout."""
        return self._drained_sealed.wait(timeout)

    @property
    def fatal(self) -> bool:
        """True when the host retired itself (dead fleet, broken
        queue I/O) rather than finishing the stream."""
        return self._fatal

    def close(self) -> None:
        """Leave the pool cleanly: stop draining, release every
        unserved lease back to the queue, close the fleet, announce
        the departure."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        self._drain_thread.join(timeout=60.0)
        # the fleet's close drains its queued work first — every
        # in-flight ownership this host can still finish is finished
        # and durably completed before any lease is handed back. The
        # beat thread keeps heartbeating THROUGH the drain: a drain
        # longer than the lease TTL must not let survivors reap and
        # re-solve work this host is about to complete.
        try:
            self.fleet.close()
        except Exception:
            pass
        self._settle_done()
        for item in list(self._inflight.values()):
            self.queue.release(item)
        self._inflight.clear()
        for _t, item in self._deferred:
            self.queue.release(item)
        self._deferred = []
        self._stop_beat.set()
        self._beat_thread.join(timeout=60.0)
        released = self.queue.leave()
        self._emit(
            "fed_leave",
            host=self.host,
            epoch=self.epoch,
            served=self.served,
            released=released,
        )
        if not self._run.closed:
            self._run.close(
                status="ok",
                served=self.served,
                n_failed=self.n_failed,
                released=released,
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FederatedHostPool:
    """The coarse-grain elasticity actuator: N in-process
    :class:`FederatedHost`\\ s draining ONE durable queue, grown and
    shrunk one host at a time (serve.controller's ``hosts`` actuator,
    ISSUE 17).

    ``grow()`` constructs a full host — its own fleet, its own obs
    stream under ``metrics_dir/host-NN`` — which joins the queue with
    a fresh epoch and starts draining immediately (warmed from the
    artifact store when ``serve_cfg.artifact_store`` is set, so a
    grown host fetches instead of compiling). ``shrink()`` retires
    the newest host through its clean ``close()``: unserved leases
    are RELEASED back to the queue for the survivors — scale-down
    never loses work, the same drain-then-retire contract as
    ``ServeFleet.set_replica_count``. All mutation is serialized
    under one lock; the pool holds no state a restarted controller
    could disagree with (``n_hosts`` IS the state)."""

    def __init__(
        self,
        queue_dir: str,
        d,
        prob,
        cfg,
        serve_cfg,
        fleet_cfg,
        blur_psf=None,
        metrics_dir: Optional[str] = None,
        verbose: str = "brief",
        host_prefix: Optional[str] = None,
        **host_kw,
    ):
        self.queue_dir = queue_dir
        self._factory_args = (d, prob, cfg, serve_cfg, fleet_cfg)
        self._blur_psf = blur_psf
        self._metrics_dir = metrics_dir
        self._verbose = verbose
        self._host_prefix = host_prefix or _default_host()
        self._host_kw = dict(host_kw)
        self._hosts: List[FederatedHost] = []
        self._next_id = 0
        self._lock = threading.Lock()
        self._closed = False

    @property
    def n_hosts(self) -> int:
        with self._lock:
            return len(self._hosts)

    @property
    def hosts(self) -> List[FederatedHost]:
        with self._lock:
            return list(self._hosts)

    def grow(self) -> str:
        """Spin one more host up against the queue; returns its host
        id. Raises on a closed pool — the controller's actuator
        ladder turns that into a failed invocation, never a crash."""
        with self._lock:
            if self._closed:
                raise RuntimeError("host pool is closed")
            hid = self._next_id
            self._next_id += 1
        name = f"{self._host_prefix}-{hid}"
        mdir = (
            os.path.join(self._metrics_dir, f"host-{hid:02d}")
            if self._metrics_dir is not None
            else None
        )
        d, prob, cfg, serve_cfg, fleet_cfg = self._factory_args
        host = FederatedHost(
            self.queue_dir, d, prob, cfg, serve_cfg, fleet_cfg,
            blur_psf=self._blur_psf, host=name, metrics_dir=mdir,
            verbose=self._verbose, **self._host_kw,
        )
        stillborn = False
        with self._lock:
            if self._closed:
                # lost the race with close(): retire immediately,
                # leases go straight back to the queue
                stillborn = True
            else:
                self._hosts.append(host)
        if stillborn:
            host.close()
            raise RuntimeError("host pool is closed")
        return name

    def shrink(self) -> str:
        """Retire the newest host (clean leave: finish in-flight,
        release unserved leases, ``fed_leave``); returns its host id.
        The caller owns the floor — the controller never calls this
        below its ``min_hosts`` bound."""
        with self._lock:
            if not self._hosts:
                raise RuntimeError("host pool is empty")
            host = self._hosts.pop()
        try:
            host.close()
        except Exception:
            # the host is out of the pool either way; its leases are
            # reaped by the survivors' heartbeat reaper
            pass
        return host.host

    def serve_until_sealed(
        self, timeout: Optional[float] = None
    ) -> bool:
        """Block until every current host drained the sealed queue
        (or the timeout elapsed). Hosts grown mid-wait are NOT
        awaited — the caller owns quiescence ordering."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        for host in self.hosts:
            left = (
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            if not host.serve_until_sealed(left):
                return False
        return True

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            hosts, self._hosts = self._hosts, []
        for host in reversed(hosts):
            try:
                host.close()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
