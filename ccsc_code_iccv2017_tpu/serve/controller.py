"""SLO-feedback capacity controller — the strictly-advisory control
plane over a :class:`~.fleet.ServeFleet` (ISSUE 17, ROADMAP item 3).

One daemon thread closes the loop the fleet left open: every tick it
takes ONE consistent sensor snapshot (``ServeFleet.control_snapshot``
— queue depth vs the derived admission ceiling, live/warm replicas vs
target, SLO p99 vs the declared target, warmup ETAs, plus an optional
measured HBM watermark from :class:`~..utils.memwatch.MemWatch`) and
drives the fleet's actuators inside configured bounds:

- ``set_replica_count`` — fine-grain grow/shrink. Grow spawns onto
  free device slices warmed from the artifact store; the new replica
  is admitted into the ceiling only once past ``BucketCold``. Shrink
  is drain-then-retire with requeue-to-front, never a kill.
- ``set_brownout`` — the degrade rung driven directly: trade solve
  quality for throughput BEFORE any shed.
- an optional :class:`~.federation.FederatedHostPool` — coarse-grain
  host spin-up/down against the durable queue, engaged only when the
  replica axis is already pinned at its bound.

Control-theory hygiene, because a flapping controller is worse than
none: hysteresis bands (``high_frac``/``low_frac`` and the brownout
pair) with ``sustain``-tick streaks, per-actuator cooldowns, sensor
staleness detection that FAILS SAFE (stale or missing telemetry →
hold state, emit ``ctrl_holdoff``, and never scale *down*), actuator
invocations under timeout/retry/exponential-backoff with a
stuck-actuator circuit breaker, and — the hard invariant the
``CCSC_FAULT_CTRL_*`` chaos points prove — the controller holds NO
durable state: every tick re-reads ``fleet.replica_target``, so a
controller that dies mid-scale leaves the fleet serving exactly as
configured and a restarted one reconciles from live state.

Every decision is a schema-declared event (``ctrl_decision`` /
``ctrl_scale`` / ``ctrl_brownout`` / ``ctrl_holdoff``) carrying the
sensor snapshot that justified it, so ``obs_report`` can replay why
capacity moved.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..config import ControllerConfig
from ..utils import env as _env
from ..utils import faults

__all__ = ["CapacityController", "ActuatorStuck", "BreakerOpen"]


class ActuatorStuck(RuntimeError):
    """An actuator invocation exhausted its timeout/retry budget."""


class BreakerOpen(RuntimeError):
    """The actuator's circuit breaker is open — invocation refused."""


def _resolve(value, knob: str):
    return value if value is not None else _env.env_float(knob)


class CapacityController:
    """The control loop. Construct over a running fleet and
    :meth:`start` it; :meth:`close` stops the loop without touching
    the data plane. ``host_pool`` (a
    :class:`~.federation.FederatedHostPool`) and ``memwatch`` (a
    :class:`~..utils.memwatch.MemWatch`) are optional sensors/
    actuators — absent, the controller simply never uses them."""

    #: actuator registry keys (cooldowns + breakers are per-actuator)
    _ACTUATORS = ("scale_up", "scale_down", "brownout", "hosts")

    def __init__(
        self,
        fleet,
        cfg: Optional[ControllerConfig] = None,
        *,
        host_pool=None,
        memwatch=None,
    ):
        cfg = cfg or ControllerConfig()
        self._fleet = fleet
        self._cfg = cfg
        self._pool = host_pool
        self._mem = memwatch
        # every None field resolves from its CCSC_CTRL_* knob once,
        # here — the loop never consults the environment again
        self.interval_s = float(
            _resolve(cfg.interval_s, "CCSC_CTRL_INTERVAL_S")
        )
        self.high_frac = float(
            _resolve(cfg.high_frac, "CCSC_CTRL_HIGH_FRAC")
        )
        self.low_frac = float(
            _resolve(cfg.low_frac, "CCSC_CTRL_LOW_FRAC")
        )
        self.sustain = int(
            cfg.sustain if cfg.sustain is not None
            else _env.env_int("CCSC_CTRL_SUSTAIN")
        )
        self.cooldown_s = float(
            _resolve(cfg.cooldown_s, "CCSC_CTRL_COOLDOWN_S")
        )
        self.stale_s = float(
            _resolve(cfg.stale_s, "CCSC_CTRL_STALE_S")
        )
        self.act_timeout_s = float(
            _resolve(cfg.act_timeout_s, "CCSC_CTRL_ACT_TIMEOUT_S")
        )
        self.act_retries = int(
            cfg.act_retries if cfg.act_retries is not None
            else _env.env_int("CCSC_CTRL_ACT_RETRIES")
        )
        self.act_backoff_s = float(
            _resolve(cfg.act_backoff_s, "CCSC_CTRL_ACT_BACKOFF_S")
        )
        self.breaker_after = int(
            cfg.breaker_after if cfg.breaker_after is not None
            else _env.env_int("CCSC_CTRL_BREAKER_AFTER")
        )
        self.breaker_reset_s = float(
            _resolve(cfg.breaker_reset_s, "CCSC_CTRL_BREAKER_RESET_S")
        )
        self.brownout_frac = float(
            _resolve(cfg.brownout_frac, "CCSC_CTRL_BROWNOUT_FRAC")
        )
        self.brownout_exit_frac = float(
            _resolve(
                cfg.brownout_exit_frac, "CCSC_CTRL_BROWNOUT_EXIT_FRAC"
            )
        )
        self.hbm_limit_mb = float(
            _resolve(cfg.hbm_limit_mb, "CCSC_CTRL_HBM_LIMIT_MB")
        )
        # loop state — streaks and bookkeeping only; NEVER the
        # capacity itself (that lives in fleet.replica_target)
        self._tick = 0
        self._up_streak = 0
        self._down_streak = 0
        self._stale_since: Optional[float] = None
        self._cool_until: Dict[str, float] = {}
        self._breaker_fails: Dict[str, int] = {}
        self._breaker_open_until: Dict[str, float] = {}
        self._last_holdoff: Optional[tuple] = None  # (reason, t_mono)
        self.died = False  # the loop thread crashed (chaos asserts)
        self.n_decisions = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- plumbing ------------------------------------------------------
    def _emit(self, type_: str, *, replica_id, **fields) -> None:
        """Controller records ride the fleet's obs stream (one
        merged timeline for obs_report); ``replica_id`` is always
        None — decisions are fleet-scope."""
        self._fleet._run.event(type_, replica_id=replica_id, **fields)

    def _console(self, msg: str) -> None:
        try:
            self._fleet._run.console(f"ctrl: {msg}", tier="brief")
        except Exception:
            pass

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "CapacityController":
        if self._thread is not None:
            raise RuntimeError("controller already started")
        self._thread = threading.Thread(
            target=self._loop, name="ccsc-capacity-ctrl", daemon=True
        )
        self._thread.start()
        return self

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def close(self) -> None:
        """Stop the control loop. Strictly advisory to the end: the
        fleet keeps serving at whatever capacity was last
        configured."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, 2 * self.interval_s))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except faults.InjectedFault:
                # chaos: the controller crashed mid-decision. The
                # invariant under test is that NOTHING else changes —
                # no cleanup, no last-gasp actuation.
                self.died = True
                return
            except Exception as e:  # noqa: BLE001 — advisory plane
                # a control-plane bug must never wedge the loop (and
                # can never touch the data plane)
                self._console(f"tick error ({type(e).__name__}: {e})")

    # -- sensors -------------------------------------------------------
    def _read_sensors(self) -> Optional[Dict[str, object]]:
        """One consistent snapshot, or None when telemetry is absent/
        stale — the caller must then FAIL SAFE (hold state, never
        scale down)."""
        if faults.ctrl_sensor_blackout(self._tick):
            return None
        try:
            snap = self._fleet.control_snapshot()
        except Exception:
            return None
        age = time.time() - float(snap.get("t", 0.0))
        if age > self.stale_s:
            return None
        if self._mem is not None:
            try:
                self._mem.sample()
                peak = self._mem.peak_bytes
                snap["hbm_peak_mb"] = (
                    None if peak is None
                    else round(peak / 2**20, 1)
                )
            except Exception:
                snap["hbm_peak_mb"] = None
        return snap

    # -- actuation ladder ---------------------------------------------
    def _breaker_is_open(self, name: str) -> bool:
        until = self._breaker_open_until.get(name)
        if until is None:
            return False
        if time.monotonic() >= until:
            # half-open: allow one probe invocation through
            del self._breaker_open_until[name]
            self._publish_breaker_gauge()
            return False
        return True

    def _publish_breaker_gauge(self) -> None:
        now = time.monotonic()
        n_open = sum(
            1 for u in self._breaker_open_until.values() if u > now
        )
        try:
            self._fleet.set_ctrl_gauge(
                "ctrl_breaker_open", float(n_open)
            )
        except Exception:
            pass

    def _actuate(self, name: str, fn: Callable[[], object]):
        """Run one actuator under the full robustness ladder:
        circuit-breaker gate, per-invocation timeout (the fn runs on
        a scratch thread — a wedged actuator can strand that thread
        but never this loop), retries with exponential backoff, and
        breaker accounting on exhaustion. The chaos hang fault lives
        INSIDE the guarded invocation, so the ladder itself is what
        gets exercised."""
        if self._breaker_is_open(name):
            raise BreakerOpen(name)
        last_err: Optional[BaseException] = None
        for attempt in range(1 + self.act_retries):
            box: Dict[str, object] = {}

            def _work():
                try:
                    dur = faults.ctrl_actuator_hang()
                    if dur > 0:
                        time.sleep(dur)
                    box["value"] = fn()
                except BaseException as e:  # noqa: BLE001
                    box["error"] = e

            t = threading.Thread(
                target=_work,
                name=f"ccsc-ctrl-act-{name}",
                daemon=True,
            )
            t.start()
            t.join(self.act_timeout_s)
            if not t.is_alive() and "value" in box:
                self._breaker_fails[name] = 0
                self._cool_until[name] = (
                    time.monotonic() + self.cooldown_s
                )
                return box["value"]
            last_err = box.get("error") or TimeoutError(
                f"actuator {name} exceeded {self.act_timeout_s}s"
            )
            if attempt < self.act_retries:
                time.sleep(self.act_backoff_s * (2 ** attempt))
        fails = self._breaker_fails.get(name, 0) + 1
        self._breaker_fails[name] = fails
        if fails >= self.breaker_after:
            self._breaker_open_until[name] = (
                time.monotonic() + self.breaker_reset_s
            )
            self._publish_breaker_gauge()
            self._console(
                f"breaker OPEN for {name} ({fails} consecutive "
                f"failures, reset in {self.breaker_reset_s}s)"
            )
        raise ActuatorStuck(f"{name}: {last_err!r}")

    def _holdoff(self, reason: str, snap=None) -> None:
        """Emit a wanted-but-suppressed decision — deduplicated (same
        reason re-emits at cooldown cadence at most) so a saturated
        suppression doesn't flood the stream."""
        now = time.monotonic()
        if self._last_holdoff is not None:
            last_reason, last_t = self._last_holdoff
            if (
                last_reason == reason
                and now - last_t < self.cooldown_s
            ):
                return
        self._last_holdoff = (reason, now)
        self._emit(
            "ctrl_holdoff", replica_id=None, reason=reason,
            tick=self._tick, snapshot=snap,
        )

    def _cooling(self, name: str) -> bool:
        return time.monotonic() < self._cool_until.get(name, 0.0)

    # -- one control tick ----------------------------------------------
    def step(self) -> None:
        """A single tick, callable directly by tests: sense, judge,
        actuate. All capacity state is re-read from the fleet — a
        restarted controller starts correct by construction."""
        self._tick += 1
        snap = self._read_sensors()
        if snap is None:
            # FAIL SAFE: no/stale telemetry. Hold everything, reset
            # streaks (resumed sensors must re-sustain pressure), and
            # say so — but never scale down blind.
            if self._stale_since is None:
                self._stale_since = time.monotonic()
                self._console(
                    "sensors stale/absent — holding state (no "
                    "scale-down on blind telemetry)"
                )
            self._up_streak = self._down_streak = 0
            self._holdoff("sensor_stale")
            return
        if self._stale_since is not None:
            self._stale_since = None
            self._last_holdoff = None
        target = int(self._fleet.replica_target)
        ceiling = snap.get("ceiling") or 0
        depth = int(snap.get("queue_depth") or 0)
        frac = depth / max(1, int(ceiling))
        p99 = snap.get("p99_ms")
        slo = snap.get("slo_p99_target_ms")
        breach = (
            p99 is not None and slo is not None and p99 > float(slo)
        )
        snap = dict(snap, frac=round(frac, 4), breach=breach)

        self._judge_brownout(snap, frac, breach)

        # pressure streaks (the flap guard): scale-down additionally
        # requires SLO green, ladder at rung 0, and no brownout —
        # shedding capacity while ANY overload signal is live would
        # fight the ladder
        up = frac >= self.high_frac or breach
        down = (
            frac <= self.low_frac
            and not breach
            and int(snap.get("rung") or 0) == 0
            and not bool(snap.get("brownout"))
        )
        if up:
            self._up_streak += 1
            self._down_streak = 0
        elif down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0

        cfg = self._cfg
        if self._up_streak >= self.sustain:
            if target < cfg.max_replicas:
                self._scale(target, target + 1, "queue_pressure"
                            if frac >= self.high_frac
                            else "slo_breach", snap)
            elif not self._grow_hosts(snap):
                self._holdoff("at_max_replicas", snap)
        elif self._down_streak >= self.sustain:
            if target > cfg.min_replicas:
                self._scale(target, target - 1, "idle_capacity", snap)
            elif not self._shrink_hosts(snap):
                self._holdoff("at_min_replicas", snap)
        elif target < cfg.min_replicas:
            # reconciliation: live state below the configured floor
            # (operator shrink, a previous controller's last act) is
            # corrected without waiting out a streak
            self._scale(target, target + 1, "reconcile_bounds", snap)
        elif target > cfg.max_replicas:
            self._scale(target, target - 1, "reconcile_bounds", snap)

    # -- judged actions ------------------------------------------------
    def _judge_brownout(self, snap, frac: float, breach: bool) -> None:
        on = bool(snap.get("brownout"))
        want_on = not on and frac >= self.brownout_frac
        want_off = (
            on and frac <= self.brownout_exit_frac and not breach
        )
        if not (want_on or want_off):
            return
        if self._cooling("brownout"):
            self._holdoff("cooldown:brownout", snap)
            return
        to = bool(want_on)
        reason = "queue_saturation" if to else "pressure_cleared"
        self.n_decisions += 1
        self._emit(
            "ctrl_decision", replica_id=None,
            action="brownout_on" if to else "brownout_off",
            reason=reason, tick=self._tick, snapshot=snap,
        )
        try:
            self._actuate(
                "brownout", lambda: self._fleet.set_brownout(
                    to, reason="controller"
                )
            )
        except BreakerOpen:
            self._holdoff("breaker_open:brownout", snap)
            return
        except ActuatorStuck:
            self._emit(
                "ctrl_brownout", replica_id=None, on=to,
                reason=reason, ok=False,
            )
            return
        self._emit(
            "ctrl_brownout", replica_id=None, on=to, reason=reason,
            ok=True,
        )

    def _scale(self, from_n: int, to_n: int, reason: str, snap) -> None:
        direction = "up" if to_n > from_n else "down"
        name = f"scale_{direction}"
        if self._cooling(name):
            self._holdoff(f"cooldown:{name}", snap)
            return
        if self._breaker_is_open(name):
            self._holdoff(f"breaker_open:{name}", snap)
            return
        if direction == "up" and self._hbm_veto(snap):
            self._holdoff("hbm_watermark", snap)
            return
        self.n_decisions += 1
        self._emit(
            "ctrl_decision", replica_id=None, action=name,
            reason=reason, tick=self._tick, snapshot=snap,
        )
        if faults.ctrl_crash_mid_scale():
            # chaos: die between commitment and actuation — the fleet
            # must keep serving exactly as configured
            raise faults.InjectedFault(
                "controller crash mid-scale (chaos)"
            )
        try:
            self._actuate(
                name,
                lambda: self._fleet.set_replica_count(
                    to_n, reason=f"controller:{reason}"
                ),
            )
        except BreakerOpen:
            self._holdoff(f"breaker_open:{name}", snap)
            return
        except ActuatorStuck:
            self._emit(
                "ctrl_scale", replica_id=None, direction=direction,
                from_n=from_n, to_n=to_n, ok=False,
            )
            return
        self._up_streak = self._down_streak = 0
        self._emit(
            "ctrl_scale", replica_id=None, direction=direction,
            from_n=from_n, to_n=to_n, ok=True,
        )
        self._console(
            f"scaled {direction} {from_n} -> {to_n} ({reason})"
        )

    def _hbm_veto(self, snap) -> bool:
        if self.hbm_limit_mb <= 0:
            return False
        peak = snap.get("hbm_peak_mb")
        return peak is not None and float(peak) >= self.hbm_limit_mb

    # -- coarse-grain host scaling -------------------------------------
    def _grow_hosts(self, snap) -> bool:
        cfg = self._cfg
        if self._pool is None or cfg.max_hosts is None:
            return False
        if self._pool.n_hosts >= cfg.max_hosts:
            return False
        if self._cooling("hosts"):
            self._holdoff("cooldown:hosts", snap)
            return True
        n = self._pool.n_hosts
        self.n_decisions += 1
        self._emit(
            "ctrl_decision", replica_id=None, action="host_up",
            reason="replicas_at_max", tick=self._tick, snapshot=snap,
        )
        try:
            self._actuate("hosts", self._pool.grow)
        except (BreakerOpen, ActuatorStuck):
            self._holdoff("breaker_open:hosts", snap)
            return True
        self._emit(
            "ctrl_scale", replica_id=None, direction="host_up",
            from_n=n, to_n=n + 1, ok=True,
        )
        return True

    def _shrink_hosts(self, snap) -> bool:
        cfg = self._cfg
        if self._pool is None or cfg.min_hosts is None:
            return False
        if self._pool.n_hosts <= cfg.min_hosts:
            return False
        if self._cooling("hosts"):
            self._holdoff("cooldown:hosts", snap)
            return True
        n = self._pool.n_hosts
        self.n_decisions += 1
        self._emit(
            "ctrl_decision", replica_id=None, action="host_down",
            reason="replicas_at_min", tick=self._tick, snapshot=snap,
        )
        try:
            self._actuate("hosts", self._pool.shrink)
        except (BreakerOpen, ActuatorStuck):
            self._holdoff("breaker_open:hosts", snap)
            return True
        self._emit(
            "ctrl_scale", replica_id=None, direction="host_down",
            from_n=n, to_n=n - 1, ok=True,
        )
        return True
