"""Tenancy runtime: per-tenant routing, quotas, and the weighted-fair
admission queue of the serving fleet.

Multi-tenant serving means one fleet carries MANY tenants' traffic —
each routing to its own bank (serve.registry), each with its own
declared latency band (serve.slo.TenantSlos) — and the failure mode
the layer exists for is noisy neighbors: one tenant's burst must get
its OWN explicit :class:`~.fleet.Overloaded` rejections while the
other tenants' latency bands hold. Two mechanisms, both declared per
tenant in :class:`~..config.TenantSpec`:

- **Quotas** — a per-tenant ceiling on QUEUED requests. Declared
  (``TenantSpec.quota``) or derived from the fleet's admission
  ceiling x the tenant's weight share x ``CCSC_TENANT_QUOTA_FRAC``
  (so quotas track a live serving_bound-derived ceiling without
  re-declaration). Enforced at fleet admission, before the global
  ceiling: a quota refusal is a ``tenant_reject`` event + Overloaded
  with the same retry-after contract, and it consumes NO shared queue
  capacity.
- **Weighted-fair dequeue** — :class:`WeightedFairScheduler` replaces
  the single FIFO with per-tenant deques drained by virtual-time fair
  queuing (each tenant's virtual clock advances by 1/weight per
  request taken; the lowest clock is served next). A tenant with
  nothing queued accrues no credit (its clock is brought up to the
  global floor on its next arrival — an idle tenant cannot bank a
  burst), FIFO order holds WITHIN a tenant, and requeued casualties
  go back to the front of their tenant's deque with their virtual
  cost refunded (they already paid for their turn).

The scheduler exposes the deque surface the fleet already speaks
(``append`` / ``appendleft`` / ``popleft`` / ``__len__`` /
``__iter__`` / ``clear``) so the queue swap is a data-structure
change, not a protocol change; it does NO locking of its own — every
method is called under the fleet's queue lock, same as the deque was.
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Dict, Iterator, List, Optional, Tuple

from ..config import TenantSpec
from ..utils import env as _env

__all__ = [
    "TenantSpec",
    "TenantTable",
    "WeightedFairScheduler",
    "parse_tenant_spec",
]


def parse_tenant_spec(spec: str) -> TenantSpec:
    """Parse a CLI/ops tenant spec string into a
    :class:`~..config.TenantSpec`:

        NAME[:key=value,...]   keys: bank, p50, p99, quota, weight,
                               deadline

    e.g. ``mobile:bank=bank-mobile,p99=250,quota=16,weight=2,
    deadline=2000``. Shared by ``apps/serve.py --tenant`` so the
    grammar cannot drift between surfaces."""
    name, _, rest = spec.partition(":")
    name = name.strip()
    kw: Dict[str, object] = {}
    keys = {
        "bank": ("bank_id", str),
        "p50": ("slo_p50_ms", float),
        "p99": ("slo_p99_ms", float),
        "quota": ("quota", int),
        "weight": ("weight", float),
        "deadline": ("deadline_ms", float),
    }
    for part in filter(None, (p.strip() for p in rest.split(","))):
        k, eq, v = part.partition("=")
        if not eq or k.strip() not in keys:
            raise ValueError(
                f"tenant spec {spec!r}: bad entry {part!r} (expected "
                f"key=value with key in {sorted(keys)})"
            )
        field, conv = keys[k.strip()]
        try:
            kw[field] = conv(v.strip())
        except ValueError:
            raise ValueError(
                f"tenant spec {spec!r}: {k.strip()}={v.strip()!r} is "
                f"not a valid {conv.__name__}"
            )
    return TenantSpec(tenant=name, **kw)  # type: ignore[arg-type]


class TenantTable:
    """The fleet's declared-tenant lookup: specs by name, bank
    routing, and quota resolution against a (possibly live-derived)
    admission ceiling. Immutable after construction; every method is
    cheap and lock-free (the fleet reads it under its own lock)."""

    def __init__(self, specs: Optional[Tuple[TenantSpec, ...]]):
        self.specs: Dict[str, TenantSpec] = {
            s.tenant: s for s in (specs or ())
        }
        self._total_weight = sum(
            s.weight for s in self.specs.values()
        ) or 1.0
        self._quota_frac = float(
            _env.env_float("CCSC_TENANT_QUOTA_FRAC")
        )

    def __bool__(self) -> bool:
        return bool(self.specs)

    def __contains__(self, tenant: Optional[str]) -> bool:
        return tenant in self.specs

    def get(self, tenant: Optional[str]) -> Optional[TenantSpec]:
        return self.specs.get(tenant) if tenant is not None else None

    def names(self) -> List[str]:
        return list(self.specs)

    def check(self, tenant: Optional[str]) -> None:
        """Refuse an UNKNOWN tenant name when tenants are declared —
        a typo'd tenant silently served untenanted would bypass its
        quota and SLO accounting. ``None`` (untenanted traffic) is
        always admitted."""
        from ..utils import validate

        if tenant is None or not self.specs:
            return
        if tenant not in self.specs:
            raise validate.CCSCInputError(
                f"unknown tenant {tenant!r} — declared tenants: "
                f"{sorted(self.specs)} (untenanted requests pass "
                "tenant=None)"
            )

    def route(
        self, tenant: Optional[str], bank_id: Optional[str]
    ) -> Optional[str]:
        """Effective bank id of one request: an explicit request
        ``bank_id`` wins, else the tenant's declared default, else
        None (the fleet's pinned default bank)."""
        if bank_id is not None:
            return bank_id
        spec = self.get(tenant)
        return spec.bank_id if spec is not None else None

    def weight(self, tenant: Optional[str]) -> float:
        spec = self.get(tenant)
        return spec.weight if spec is not None else 1.0

    def quota(
        self, tenant: Optional[str], ceiling: int
    ) -> Optional[int]:
        """The tenant's queued-request quota: declared, or derived as
        ``ceil(ceiling x weight_share x CCSC_TENANT_QUOTA_FRAC)``
        (floored at 1 so a declared tenant can always queue
        something). None for untenanted traffic — the global ceiling
        is its only bound."""
        spec = self.get(tenant)
        if spec is None:
            return None
        if spec.quota is not None:
            return spec.quota
        share = spec.weight / self._total_weight
        return max(1, int(ceiling * share * self._quota_frac + 0.999))


class WeightedFairScheduler:
    """Virtual-time weighted-fair queue over per-tenant deques.

    Drop-in for the fleet's ``deque`` front queue: ``append`` reads
    the item's ``tenant`` attribute, ``popleft`` returns the next
    item under weighted-fair order (min virtual time; FIFO within a
    tenant), ``appendleft`` is the requeue path (front of the
    tenant's deque, virtual cost refunded). NOT thread-safe by
    itself — every call happens under the fleet's queue lock, exactly
    like the deque it replaces."""

    def __init__(self, table: Optional[TenantTable] = None):
        self._table = table or TenantTable(None)
        self._queues: Dict[Optional[str], Deque] = {}
        self._vt: Dict[Optional[str], float] = {}
        self._vt_floor = 0.0
        self._n = 0

    def _cost(self, tenant: Optional[str]) -> float:
        return 1.0 / self._table.weight(tenant)

    def _lane(self, tenant: Optional[str]) -> Deque:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
        return q

    def append(self, item) -> None:
        tenant = getattr(item, "tenant", None)
        q = self._lane(tenant)
        if not q:
            # an idle tenant re-enters at the global floor: it cannot
            # have banked credit while absent (no burst head start),
            # and it is not penalized for having been idle either
            self._vt[tenant] = max(
                self._vt.get(tenant, 0.0), self._vt_floor
            )
        q.append(item)
        self._n += 1

    def appendleft(self, item) -> None:
        """Requeue path: front of the tenant's lane (the request
        already waited its turn once) with the virtual cost refunded
        so the retry is not billed as a second serving."""
        tenant = getattr(item, "tenant", None)
        q = self._lane(tenant)
        if not q:
            self._vt[tenant] = max(
                self._vt.get(tenant, 0.0), self._vt_floor
            )
        self._vt[tenant] = max(
            0.0, self._vt.get(tenant, 0.0) - self._cost(tenant)
        )
        q.appendleft(item)
        self._n += 1

    def popleft(self):
        """Next item under weighted-fair order; raises ``IndexError``
        when empty (deque contract)."""
        best: Optional[Tuple[float, Optional[str]]] = None
        for tenant, q in self._queues.items():
            if not q:
                continue
            vt = self._vt.get(tenant, 0.0)
            key = (vt, "" if tenant is None else tenant)
            if best is None or key < (
                best[0], "" if best[1] is None else best[1]
            ):
                best = (vt, tenant)
        if best is None:
            raise IndexError("pop from an empty scheduler")
        _vt, tenant = best
        item = self._queues[tenant].popleft()
        self._n -= 1
        self._vt[tenant] = self._vt.get(tenant, 0.0) + self._cost(
            tenant
        )
        self._vt_floor = max(self._vt_floor, _vt)
        return item

    def depth_of(self, tenant: Optional[str]) -> int:
        q = self._queues.get(tenant)
        return len(q) if q is not None else 0

    def depths(self) -> Dict[Optional[str], int]:
        return {
            t: len(q) for t, q in self._queues.items() if q
        }

    def clear(self) -> None:
        for q in self._queues.values():
            q.clear()
        self._n = 0

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def __iter__(self) -> Iterator:
        # tenant-grouped iteration order; consumers (close-time
        # failure sweep) treat the queue as a set, not an order
        for q in self._queues.values():
            yield from q
