"""Live metrics surface: a stdlib-only Prometheus-text HTTP endpoint
plus an atomic snapshot file, fed by a running fleet or engine.

The obs streams are the system of record, but they answer "what
happened" after a reader parses JSONL; a serving fleet also needs
"what is true RIGHT NOW" answerable by anything that can speak HTTP —
a Prometheus scraper, ``curl`` in an incident, a k8s liveness probe.
This module is that surface, with zero dependencies beyond the
standard library:

- :class:`MetricsD` serves ``GET /metrics`` in Prometheus text
  exposition format (counters, gauges, and the ``serve.slo``
  latency histograms as cumulative ``_bucket{le=...}`` series) from
  a ``source`` — any callable returning the metrics dict shape of
  ``ServeFleet.metrics()`` / ``CodecEngine.metrics()``, or a metrics
  DIR, in which case a :class:`StreamMetrics` tails the event stream
  incrementally (``utils.obs.EventTail`` — each scrape costs O(new
  records), never a full re-read) so the endpoint can run beside a
  process it does not share memory with.
- The same text is written ATOMICALLY (tmp + rename) to a snapshot
  file every ``CCSC_METRICSD_INTERVAL_S`` seconds for scrape-less
  environments: a sidecar, ``cat``, or a log shipper reads a
  complete, never-torn exposition.
- Every exposition carries a FRESHNESS STAMP:
  ``ccsc_snapshot_timestamp_seconds`` (write time — a reader
  comparing it to the wall clock detects a snapshot whose fleet died
  with it), ``ccsc_snapshot_age_seconds`` (seconds since the
  underlying metrics last CHANGED — a live sidecar over a dead
  source shows it growing), and ``ccsc_snapshot_info{run_id=...}``
  (the fleet run identity, so a stale file names the fleet that
  abandoned it). ``parse_snapshot_stamp`` reads it back;
  ``scripts/obs_report.py`` flags staleness past ``--stale-after``.

Wiring: ``FleetConfig.metricsd_port`` (or ``CCSC_METRICSD_PORT``;
0 = an ephemeral port, reported in the ``fleet_metricsd`` event and
``MetricsD.port``) starts one inside :class:`~.fleet.ServeFleet`;
``apps/serve.py --metricsd-port`` wires a standalone engine. The
server binds 127.0.0.1 — exposure beyond the host is a deployment
decision, not a default.
"""
from __future__ import annotations

import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple, Union

from ..utils import env as _env

__all__ = [
    "MetricsD",
    "StreamMetrics",
    "parse_snapshot_stamp",
    "render_prometheus",
    "resolve_endpoint",
    "tenant_labeled_counters",
]

_PREFIX = "ccsc"
# exposition format version, stamped into every snapshot/scrape:
# 2 = per-tenant labeled counter series (serve.tenancy) added
# 3 = quality plane series (serve.quality): ccsc_psnr_db histograms,
#     ccsc_probe_failures_total, ccsc_quality_breach. Purely
#     additive — parse_snapshot_stamp and every format-2 series are
#     byte-identical, so format-2 readers keep parsing format-3 files
SNAPSHOT_FORMAT = 3


def resolve_endpoint(
    port: Optional[int],
    snapshot: Optional[str],
    metrics_dir: Optional[str],
) -> Tuple[Optional[int], Optional[str]]:
    """The ONE resolution chain for the metrics surface, shared by
    the fleet and the standalone-engine CLI so the two can never
    diverge: port = explicit > CCSC_METRICSD_PORT > off (None);
    snapshot = explicit > CCSC_METRICSD_SNAPSHOT >
    metrics_dir/metrics.prom (only when the endpoint is on — a run
    that asked for nothing gets no surprise file). A snapshot
    REQUEST without a port is honored: scrape-less environments are
    the snapshot's whole point, so (None, path) means snapshot-only
    mode (:class:`MetricsD` skips the HTTP server)."""
    if port is None:
        port = _env.env_int("CCSC_METRICSD_PORT")
    snap = snapshot or _env.env_str("CCSC_METRICSD_SNAPSHOT")
    if port is None:
        return None, snap
    if snap is None and metrics_dir:
        snap = os.path.join(metrics_dir, "metrics.prom")
    return int(port), snap


def tenant_labeled_counters(
    delivered: Dict[str, int], rejected: Dict[str, int]
) -> List[Tuple[str, Dict[str, object], int]]:
    """The ONE construction of the per-tenant labeled counter series
    from {tenant: count} maps — shared by the fleet's live
    ``metrics()`` and the stream-derived :class:`StreamMetrics`, so
    the HTTP endpoint and a scrape-less snapshot can never render
    different series names or label shapes for the same state."""
    return [
        ("tenant_requests_total", {"tenant": t}, delivered[t])
        for t in sorted(delivered)
    ] + [
        ("tenant_rejected_total", {"tenant": t}, rejected[t])
        for t in sorted(rejected)
    ]


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    return repr(round(f, 6))


def _labels(labels: Optional[Dict[str, object]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{v}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prometheus(metrics: Dict, prefix: str = _PREFIX) -> str:
    """Render the shared metrics-dict shape:

    ``{"counters": {name: value}, "gauges": {name: value},
    "labeled_counters": [(name, labels_dict, value), ...],
    "histograms": [(name, labels_dict, slo-snapshot-dict), ...]}``

    as Prometheus text exposition (one stable, sorted rendering — the
    HTTP endpoint and the snapshot file emit identical bytes for
    identical state). ``labeled_counters`` is the per-tenant series
    surface (``tenant``/``bank_id`` labels, serve.tenancy): one TYPE
    line per metric name, one sample per label set."""
    lines: List[str] = []
    for kind in ("counters", "gauges"):
        ptype = "counter" if kind == "counters" else "gauge"
        for name in sorted(metrics.get(kind) or {}):
            full = f"{prefix}_{name}"
            lines.append(f"# TYPE {full} {ptype}")
            lines.append(f"{full} {_fmt(metrics[kind][name])}")
    seen_labeled = set()
    for name, labels, value in sorted(
        metrics.get("labeled_counters") or (),
        key=lambda row: (row[0], sorted((row[1] or {}).items())),
    ):
        full = f"{prefix}_{name}"
        if full not in seen_labeled:
            seen_labeled.add(full)
            lines.append(f"# TYPE {full} counter")
        lines.append(f"{full}{_labels(labels)} {_fmt(value)}")
    seen_types = set()
    for name, labels, snap in metrics.get("histograms") or ():
        full = f"{prefix}_{name}"
        if full not in seen_types:
            seen_types.add(full)
            lines.append(f"# TYPE {full} histogram")
        bounds = snap.get("bounds_ms") or []
        counts = snap.get("counts") or []
        cum = 0
        for i, b in enumerate(bounds):
            cum += counts[i] if i < len(counts) else 0
            lab = dict(labels or {})
            lab["le"] = _fmt(float(b))
            lines.append(f"{full}_bucket{_labels(lab)} {cum}")
        if len(counts) > len(bounds):
            cum += counts[len(bounds)]
        lab = dict(labels or {})
        lab["le"] = "+Inf"
        lines.append(f"{full}_bucket{_labels(lab)} {cum}")
        lines.append(
            f"{full}_sum{_labels(labels)} {_fmt(snap.get('sum_ms', 0.0))}"
        )
        lines.append(
            f"{full}_count{_labels(labels)} {snap.get('n', cum)}"
        )
    return "\n".join(lines) + "\n"


def parse_snapshot_stamp(path: str) -> Optional[Dict[str, object]]:
    """Read the freshness stamp back out of a snapshot file:
    ``{"timestamp": ..., "age_s": ..., "run_id": ...}`` — or None
    when the file is absent or predates the stamp. The staleness
    judgment belongs to the READER (``scripts/obs_report.py`` flags a
    snapshot whose timestamp lags the wall clock): a static file
    cannot know how long ago it was written."""
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError:
        return None
    out: Dict[str, object] = {}
    for line in text.splitlines():
        if line.startswith("ccsc_snapshot_timestamp_seconds "):
            try:
                out["timestamp"] = float(line.split()[-1])
            except ValueError:
                pass
        elif line.startswith("ccsc_snapshot_age_seconds "):
            try:
                out["age_s"] = float(line.split()[-1])
            except ValueError:
                pass
        elif line.startswith("ccsc_snapshot_info{"):
            lo = line.find('run_id="')
            if lo >= 0:
                hi = line.find('"', lo + 8)
                if hi > lo:
                    out["run_id"] = line[lo + 8:hi]
    return out if "timestamp" in out else None


class StreamMetrics:
    """Metrics source derived from an obs event stream on disk.

    Tails the stream INCREMENTALLY (``utils.obs.EventTail``,
    recursive so a fleet dir's ``replica-NN/`` streams merge): each
    call consumes only appended records, folds them into running
    counters, and keeps the newest ``slo_histogram`` snapshot per
    (phase, replica) — so a scrape of a day-old stream costs what the
    last few seconds wrote, not the whole file."""

    def __init__(self, metrics_dir: str):
        from ..utils import obs

        self._dir = metrics_dir
        self._tail = obs.EventTail(metrics_dir, recursive=True)
        # fleet mode is LATCHED (structurally from replica-NN subdirs,
        # or from the first fleet_request): a Prometheus counter must
        # never decrease, and flipping from the engine-side count to
        # the (briefly lower) fleet-side delivered count mid-stream
        # would read as a process restart to rate()/increase()
        self._fleet_mode = self._is_fleet_dir()
        self._counters: Dict[str, int] = {
            "dispatches_total": 0,
            "requeued_total": 0,
            "rejected_total": 0,
            "duplicates_suppressed_total": 0,
            "slo_breaches_total": 0,
            "probe_failures_total": 0,
            # request-lifecycle folds (serve.fleet hedging/deadlines):
            # same names the live fleet.metrics() surface exports, so
            # a stream-derived scrape and an in-process scrape render
            # identical ccsc_* series
            "hedges_total": 0,
            "hedge_wins_total": 0,
            "deadline_exceeded_total": 0,
            "cancelled_total": 0,
        }
        # quality plane folds (serve.quality): breached tenant floors
        # (gauge parity with the live fleet's n_breached — a floor
        # never un-breaches within a run) and the newest psnr_db
        # histogram per (bank, tenant, bucket, replica)
        self._breached_tenants: set = set()
        self._qhists: Dict[Tuple, Dict] = {}
        # a fleet dir carries BOTH record kinds for one delivery —
        # fleet_request at the top level, serve_request in the
        # replica's stream — so the two are counted separately and
        # the mode is picked at READ time: any fleet_request ever
        # seen means the fleet count is the request count (counting
        # serve_request until the first fleet_request arrives would
        # double-count every early delivery)
        self._n_fleet_req = 0
        self._n_serve_req = 0
        # per-tenant folds (serve.tenancy): delivered and
        # quota-rejected counts, rendered as labeled counter series
        self._tenant_req: Dict[str, int] = {}
        self._tenant_rej: Dict[str, int] = {}
        self._hists: Dict[Tuple[str, object, object], Dict] = {}
        self._lock = threading.Lock()

    def _is_fleet_dir(self) -> bool:
        try:
            return any(
                name.startswith("replica-")
                and os.path.isdir(os.path.join(self._dir, name))
                for name in os.listdir(self._dir)
            )
        except OSError:
            return False

    def __call__(self) -> Dict:
        with self._lock:
            if not self._fleet_mode:
                self._fleet_mode = self._is_fleet_dir()
            for rec in self._tail.poll():
                kind = rec.get("type")
                if kind == "fleet_request":
                    self._fleet_mode = True
                    self._n_fleet_req += 1
                    t = rec.get("tenant")
                    if t:
                        self._tenant_req[t] = (
                            self._tenant_req.get(t, 0) + 1
                        )
                elif kind == "serve_request":
                    self._n_serve_req += 1
                elif kind == "serve_dispatch":
                    self._counters["dispatches_total"] += 1
                elif kind == "fleet_requeue":
                    self._counters["requeued_total"] += int(
                        rec.get("n", 0)
                    )
                elif kind == "fleet_admission_reject":
                    self._counters["rejected_total"] += 1
                elif kind == "tenant_reject":
                    t = rec.get("tenant")
                    if t:
                        self._tenant_rej[t] = (
                            self._tenant_rej.get(t, 0) + 1
                        )
                elif kind == "fleet_duplicate_suppressed":
                    self._counters["duplicates_suppressed_total"] += 1
                elif kind == "slo_breach":
                    self._counters["slo_breaches_total"] += 1
                elif kind == "slo_histogram":
                    key = (
                        str(rec.get("phase", "total")),
                        rec.get("replica_id"),
                        rec.get("tenant"),
                    )
                    self._hists[key] = rec
                elif kind == "quality_probe_breach":
                    self._counters["probe_failures_total"] += 1
                elif kind == "hedge_spawn":
                    self._counters["hedges_total"] += 1
                elif kind == "hedge_win":
                    self._counters["hedge_wins_total"] += 1
                elif kind == "deadline_exceeded":
                    self._counters["deadline_exceeded_total"] += 1
                elif kind == "request_cancelled":
                    self._counters["cancelled_total"] += 1
                elif kind == "quality_breach":
                    t = rec.get("tenant")
                    if t:
                        self._breached_tenants.add(t)
                elif kind == "quality_histogram":
                    qkey = (
                        rec.get("bank_id"),
                        rec.get("tenant"),
                        rec.get("bucket"),
                        rec.get("replica_id"),
                    )
                    self._qhists[qkey] = rec
            hists = []
            for (phase, rid, tenant), rec in sorted(
                self._hists.items(), key=lambda kv: str(kv[0])
            ):
                labels = {"phase": phase}
                if rid is not None:
                    labels["replica"] = rid
                if tenant is not None:
                    labels["tenant"] = tenant
                hists.append(("latency_ms", labels, rec))
            # psnr_db series mirror the live metrics() label shape
            # ({bank_id, tenant, bucket}); a replica label is added
            # only for replica-scope rows so the fleet-scope series
            # renders identically to the in-memory source
            for (bank, tenant, bucket, rid), rec in sorted(
                self._qhists.items(), key=lambda kv: str(kv[0])
            ):
                labels = {
                    "bank_id": bank, "tenant": tenant,
                    "bucket": bucket,
                }
                if rid is not None:
                    labels["replica"] = rid
                hists.append(("psnr_db", labels, rec))
            counters = dict(self._counters)
            counters["requests_total"] = (
                self._n_fleet_req
                if self._fleet_mode
                else self._n_serve_req
            )
            labeled = tenant_labeled_counters(
                self._tenant_req, self._tenant_rej
            )
            return {
                "counters": counters,
                "gauges": {
                    "quality_breach": len(self._breached_tenants),
                },
                "labeled_counters": labeled,
                "histograms": hists,
            }


class _Handler(BaseHTTPRequestHandler):
    server_version = "ccsc-metricsd"

    def do_GET(self):  # noqa: N802 - http.server API
        try:
            body = self.server._render().encode("utf-8")  # type: ignore[attr-defined]
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except Exception:  # pragma: no cover - a broken scrape must
            # never take the server thread down
            try:
                self.send_error(500)
            except Exception:
                pass

    def log_message(self, *args):  # silence per-scrape stderr noise
        pass


class MetricsD:
    """The live surface: HTTP endpoint + atomic snapshot file.

    ``source`` is a callable returning the shared metrics-dict shape
    (``ServeFleet.metrics`` / ``CodecEngine.metrics``) or a metrics
    dir (wrapped in :class:`StreamMetrics`). ``port`` 0 binds an
    ephemeral port; the bound port is ``self.port`` after
    ``start()``; ``port=None`` is snapshot-only mode (no HTTP server
    — a scrape-less environment that only wants the atomic file).
    Both background threads are tracked and joined by ``stop()`` — a
    leaked daemon thread at interpreter exit is the failure class the
    thread-safety lint exists for."""

    def __init__(
        self,
        source: Union[Callable[[], Dict], str],
        port: Optional[int] = 0,
        host: str = "127.0.0.1",
        snapshot_path: Optional[str] = None,
        interval_s: Optional[float] = None,
        run_id: Optional[str] = None,
    ):
        if isinstance(source, str):
            source = StreamMetrics(source)
        self._source = source
        self._host = host
        self._req_port = None if port is None else int(port)
        self.snapshot_path = snapshot_path
        if interval_s is None:
            interval_s = _env.env_float("CCSC_METRICSD_INTERVAL_S")
        self.interval_s = max(0.05, float(interval_s))
        # run identity stamped into every exposition: a scrape-less
        # reader of metrics.prom can tell whether the file belongs to
        # the fleet it thinks is alive, or is the husk of a dead one
        self.run_id = run_id or f"pid-{os.getpid()}"
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._server_thread: Optional[threading.Thread] = None
        self._snap_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # freshness tracking: _last_change is the newest time the
        # UNSTAMPED body actually differed — a live metricsd sitting
        # on a dead source (a sidecar tailing a stream that stopped)
        # shows a growing ccsc_snapshot_age_seconds; a dead metricsd
        # shows a frozen ccsc_snapshot_timestamp_seconds readers
        # compare against the wall clock
        self._last_body: Optional[str] = None
        self._last_change = time.time()

    def render(self) -> str:
        body = render_prometheus(self._source())
        now = time.time()
        if body != self._last_body:
            self._last_body = body
            self._last_change = now
        stamp = [
            # snapshot-format version stamp: readers that care about
            # the exposition shape (format 2 added labeled per-tenant
            # counter series, format 3 the quality plane series) can
            # branch on it; parse_snapshot_stamp ignores it — the
            # freshness contract is unchanged
            "# TYPE ccsc_snapshot_format gauge",
            f"ccsc_snapshot_format {SNAPSHOT_FORMAT}",
            "# TYPE ccsc_snapshot_timestamp_seconds gauge",
            f"ccsc_snapshot_timestamp_seconds {_fmt(now)}",
            "# TYPE ccsc_snapshot_age_seconds gauge",
            "ccsc_snapshot_age_seconds "
            f"{_fmt(max(0.0, now - self._last_change))}",
            "# TYPE ccsc_snapshot_info gauge",
            f'ccsc_snapshot_info{{run_id="{self.run_id}"}} 1',
        ]
        return body + "\n".join(stamp) + "\n"

    def write_snapshot(self) -> None:
        """One atomic exposition write (tmp + rename): a reader can
        never observe a torn file."""
        if not self.snapshot_path:
            return
        body = self.render()
        d = os.path.dirname(os.path.abspath(self.snapshot_path))
        os.makedirs(d, exist_ok=True)
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(body)
        os.replace(tmp, self.snapshot_path)

    def _snap_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write_snapshot()
            except Exception:  # pragma: no cover - disk-full etc.;
                pass  # the endpoint stays up regardless

    def start(self) -> "MetricsD":
        if self._req_port is not None:
            srv = ThreadingHTTPServer(
                (self._host, self._req_port), _Handler
            )
            srv.daemon_threads = True
            srv._render = self.render  # type: ignore[attr-defined]
            self._server = srv
            self.port = srv.server_address[1]
            self._server_thread = threading.Thread(
                target=srv.serve_forever, name="ccsc-metricsd",
                daemon=True,
            )
            self._server_thread.start()
        if self.snapshot_path:
            try:
                self.write_snapshot()  # a snapshot exists from t=0
                self._snap_thread = threading.Thread(
                    target=self._snap_loop,
                    name="ccsc-metricsd-snap",
                    daemon=True,
                )
                self._snap_thread.start()
            except BaseException:
                # callers treat a start() failure as "no surface" and
                # drop the instance — the server started above must
                # not outlive that decision as an ownerless daemon
                # squatting the port
                self.stop()
                raise
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:  # pragma: no cover
                pass
        if self._server_thread is not None:
            self._server_thread.join(timeout=5.0)
        if self._snap_thread is not None:
            self._snap_thread.join(timeout=5.0)
            try:
                self.write_snapshot()  # final state on disk
            except Exception:  # pragma: no cover
                pass

    def __enter__(self) -> "MetricsD":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
