"""Fault-tolerant serving fleet: replicated engines behind one queue.

One :class:`~.engine.CodecEngine` on one device has no survival story:
an engine stall or crash loses every queued request, and overload has
no admission path short of OOM. :class:`ServeFleet` is the fleet
layer — N engine replicas that share NOTHING but a front queue (the
MPAX fleet of jit-cached solver instances over pinned problem
structure, PAPERS.md arXiv:2412.09734; the ``vmap``-of-independent-
n=1-solves batch shape means replicas need no coordination beyond
request ownership):

1. **Durable front queue + idempotency keys.** Durability is against
   REPLICA failure: every request carries an idempotency key, a
   replica owns the requests it has taken, and when a replica dies or
   stalls its undelivered requests are requeued (at the front — they
   already waited their turn) onto survivors. Delivery is
   at-most-once (a recovered straggler's late result for an
   already-delivered key is suppressed, counted as
   ``fleet_duplicate_suppressed``) and each request resolves
   exactly-once-or-error: after ``FleetConfig.max_attempts`` failed
   ownerships the future gets an explicit error instead of silent
   retry-forever.
2. **Health-driven drain.** Each replica worker arms a per-replica
   :class:`~..utils.watchdog.DispatchWatchdog` (event mode + the
   ``on_stall`` authority hook) around its dispatch fence — the same
   deadline rules as the learner drivers (MIN_S floor, first-fence
   compile allowance, self-calibration against observed clean
   fences). A stalled or dead replica is retired, its requests are
   requeued, and a replacement engine is rebuilt from the warm
   persistent compile cache (``ServeConfig.compile_cache``) under a
   per-replica restart budget with exponential backoff — the
   ``scripts/supervise.py`` discipline, in-process. Injected chaos
   (``CCSC_FAULT_ENGINE_KILL_REQ`` / ``CCSC_FAULT_ENGINE_HANG_REQ``,
   utils.faults, fire-once per replica) makes both paths provable on
   CPU (tests/test_fleet.py, scripts/chaos_smoke.py ``fleet_kill``).
3. **Admission control + predictable overload.** ``submit`` refuses
   work beyond a queue-depth ceiling — explicit
   (``FleetConfig.max_queue_depth``) or derived live from
   ``utils.perfmodel.serving_bound`` x live replicas x
   ``max_queue_s`` — raising :class:`Overloaded` with a retry-after
   hint instead of growing the queue to OOM. Below the ceiling a
   three-rung ladder keeps latency predictable: rung 1 sheds the
   ``max_wait_ms`` micro-batch waiting (``set_max_wait_ms(0)``),
   rung 2 rejects new requests, rung 3 (sustained rejection) recycles
   replicas onto a degraded solve budget (``max_it`` x
   ``degrade_max_it_factor`` — the serving face of the PR 4 degrade
   ladder, each transition a ``degrade`` obs event).

Telemetry: the fleet stream (``FleetConfig.metrics_dir``) carries
``fleet_heartbeat`` (per replica: state/served/inflight — the
liveness signal ``utils.watchdog.check_replicas`` and
``scripts/obs_report.py`` FLEET read with the ``--stale-after``
rule), ``fleet_request`` / ``fleet_requeue`` /
``fleet_duplicate_suppressed``, replica lifecycle
(``fleet_replica_dead`` / ``_restart`` / ``_ready`` /
``_abandoned``), ``fleet_admission_reject``, ``fleet_ceiling`` and
``fleet_overload`` rung transitions; every record carries a
``replica_id`` field (None for fleet-scope records — lint-enforced).
Each replica engine's own serve_* stream lands in a ``replica-NN/``
subdir (``obs.read_events(recursive=True)`` merges them).

Exactness: replicas are built from the same pinned
(bank, problem, SolveConfig, ServeConfig), so a request served by ANY
replica — including after a mid-stream handoff — is bit-identical to
a single unfaulted engine's serve of the same request (the chaos
parity contract of tests/test_fleet.py). Only rung 3 trades solve
budget for latency, and it announces itself in the stream.

Multi-tenancy (serve.registry / serve.tenancy): ``submit`` routes by
``bank_id`` (explicit, or the tenant's declared default) and binds
the bank's DIGEST at admission; ``publish_bank`` hot-swaps a bank id
to a new digest with zero downtime (staggered per-replica plan
builds, one atomic route flip, a ``bank_swap`` event with both
digests — in-flight requests finish on their admission-time plan).
With ``FleetConfig.tenants`` declared, the front queue becomes
weighted-fair per-tenant lanes, admission enforces per-tenant quotas
(``tenant_reject`` + :class:`Overloaded` for the bursting tenant
only), and each tenant's submit->result latency streams into its own
SLO histogram judged against its own declared targets
(serve.slo.TenantSlos).
"""
from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..config import FleetConfig, ServeConfig, SolveConfig
from ..utils import env as _env
from ..utils import trace as trace_util
from . import capture as _capture
from . import metricsd as _metricsd_mod
from . import quality as _quality
from . import registry as _registry
from . import slo as _slo
from . import tenancy as _tenancy
from .engine import (
    BucketCold,
    CodecEngine,
    DeadlineExceeded,
    ServedResult,
    _bucket_name,
    parse_mesh_shape,
    pick_bucket,
)

__all__ = [
    "ServeFleet", "Overloaded", "BucketCold", "DeadlineExceeded",
    "RUNGS",
]

# the overload ladder, least to most drastic
RUNGS = ("normal", "shed_batching", "reject", "degrade")


def _ms_to_s(v):
    return None if v is None else v / 1e3


class Overloaded(RuntimeError):
    """Admission refusal: the fleet's queue is at its ceiling. Carries
    ``retry_after_s`` — the caller should back off that long before
    resubmitting (explicit backpressure instead of silent queue growth
    and eventual OOM)."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass
class _FleetRequest:
    key: str
    b: np.ndarray
    mask: Optional[np.ndarray]
    smooth_init: Optional[np.ndarray]
    x_orig: Optional[np.ndarray]
    future: Future
    t_submit: float
    attempts: int = 0  # ownerships so far (incremented at take)
    # -- multi-tenant routing (serve.registry / serve.tenancy): the
    # tenant the request was admitted under (its weighted-fair lane,
    # quota and SLO accounting), the effective bank id, and the bank
    # DIGEST bound at admission — a hot-swap republishing the bank id
    # mid-queue must never retarget already-admitted requests, and a
    # requeued casualty re-serves against the SAME digest on any
    # replica (every replica retains every published bank's plans)
    tenant: Optional[str] = None
    bank_id: Optional[str] = None
    digest: str = ""
    # -- request-level tracing (utils.trace). The span context RIDES
    # the request through every requeue, so one trace survives
    # replica kills/restarts: root_span covers submit->resolution,
    # queue_span the open queue episode (re-opened per requeue),
    # attempt_span the open replica ownership. Ids are assigned under
    # the fleet lock; emission always happens OUTSIDE it. trace_id
    # None (white-box-constructed requests) disables span emission.
    trace_id: Optional[str] = None
    root_span: Optional[str] = None  # assigned once, never cleared
    # claim-to-emit pointers: a path that will emit the span_end
    # first CLAIMS the id under the lock (reads it and clears the
    # field / sets root_done), so racing paths can never double-end
    queue_span: Optional[str] = None
    attempt_span: Optional[str] = None
    # owning replica of the OPEN attempt span: a straggler that wins
    # the delivery race after a requeue would otherwise end the NEW
    # owner's span as its own ok (misattributing the solve in the
    # reassembled story)
    attempt_rep: Optional[int] = None
    root_done: bool = False
    t_wall: float = 0.0  # wall-clock submit time (span timestamps)
    queue_t: float = 0.0  # wall-clock start of the open queue episode
    attempt_t: float = 0.0  # wall-clock start of the open ownership
    # -- request lifecycle (ISSUE 19). deadline is the ABSOLUTE
    # end-to-end budget (wall-clock epoch seconds) stamped at
    # admission; None = unbounded. A hedged request exists as TWO
    # _FleetRequest instances sharing key/future/trace_id/root_span:
    # the original (hedged=True once its clone is queued) and the
    # clone (hedge_of=True), each with its own queue/attempt span
    # slots so both attempts are visible in the reassembled trace.
    # `primary` points the clone at the original — the shared
    # root-span claim (root_done) lives on ONE instance so the two
    # delivery races can never double-end the root. `not_replica`
    # excludes the clone from the replica whose slow attempt it
    # hedges against (first result wins through the _delivered
    # fencing; the loser ends its attempt span `hedge_lost`).
    deadline: Optional[float] = None
    hedged: bool = False
    hedge_of: bool = False
    not_replica: Optional[int] = None
    primary: Optional["_FleetRequest"] = None


class _Replica:
    """One engine replica: identity, worker thread, health state.

    ``state``: 'live' -> ('dead' | 'stalled' | 'recycling') ->
    replaced by a fresh _Replica of the same id (generation + 1).
    ``retired`` flags the worker to stop taking work; a wedged worker
    that later wakes finds it set and exits after its (suppressed)
    deliveries."""

    def __init__(self, rid: int, generation: int, engine: CodecEngine,
                 watchdog, degraded: bool = False) -> None:
        self.id = rid
        self.generation = generation
        self.engine = engine
        self.watchdog = watchdog
        self.degraded = degraded  # built on the reduced solve budget?
        self.state = "live"
        self.retired = False
        # the casualty handoff (requeue + replacement scheduling) has
        # run for this replica — exactly one of the stall handler, the
        # death handler, or the worker's clean recycle exit performs
        # it (a recycle marks `retired` without handing off, so the
        # handoff is still owed if the worker then crashes or stalls)
        self.reaped = False
        self.req_seq = 0  # requests taken, lifetime of this generation
        self.served = 0
        self.assigned: List[_FleetRequest] = []
        self.thread: Optional[threading.Thread] = None


class ServeFleet:
    """N replicated CodecEngines behind one durable front queue.

    API mirrors :class:`~.engine.CodecEngine` — ``submit`` returns a
    Future of :class:`~.engine.ServedResult`, plus ``reconstruct`` /
    ``serve_many`` / ``stats`` / ``close`` / context manager — with
    two additions: ``submit`` takes an optional idempotency ``key``
    and may raise :class:`Overloaded`.
    """

    def __init__(self, d, prob, cfg: SolveConfig,
                 serve_cfg: ServeConfig, fleet_cfg: FleetConfig,
                 blur_psf=None):
        from ..utils import obs, validate

        self._close_lock = threading.Lock()
        self._close_started = False
        self._close_done = threading.Event()
        # set by close(): wakes restart threads out of their backoff
        # sleep so they can be joined instead of left running engine
        # construction (XLA teardown from a live daemon thread at
        # interpreter exit aborts the process)
        self._closing = threading.Event()
        self._restart_threads: List[threading.Thread] = []
        self._recycle_thread: Optional[threading.Thread] = None

        # fail on a garbage bank/config ONCE, before N engines build
        validate.check_solve_config(cfg)
        validate.check_filters(d, prob.geom)
        self.geom = prob.geom
        self.cfg = cfg
        self.serve_cfg = serve_cfg
        self.fleet_cfg = fleet_cfg
        self._d = d
        self._prob = prob
        self._blur_psf = blur_psf
        # already normalized + volume-sorted by ServeConfig.__post_init__
        self.buckets = serve_cfg.buckets
        self._total_slots = sum(s for s, _ in self.buckets)
        self._take_cap = max(s for s, _ in self.buckets)

        # heterogeneous replica shapes (FleetConfig.replica_meshes:
        # per-replica mesh shape or None; default = every replica
        # inherits ServeConfig.mesh_shape, resolving the
        # CCSC_SERVE_MESH env fallback HERE — N engines each
        # resolving the knob themselves would all land on the same
        # default device prefix while the capacity math counted them
        # as distinct hardware). Entries are normalized to a concrete
        # shape or () (the explicit single-device pin), so replica
        # topology is frozen at fleet construction and restarts
        # rebuild exactly it. Mesh replicas get DISJOINT device
        # slices — a pool that cannot supply them is refused up
        # front (CCSC_SERVE_MESH_STRICT, default on): overlapping
        # slices would let capacity_hint / the derived admission
        # ceiling credit devices that do not exist.
        import math as _math

        default_mesh = serve_cfg.mesh_shape
        env_malformed = False
        if default_mesh is None:
            spec = _env.env_str("CCSC_SERVE_MESH")
            if spec:
                try:
                    default_mesh = parse_mesh_shape(spec)
                except ValueError:
                    # keep the entries None (NOT the () pin) so each
                    # engine's own resolution re-parses the malformed
                    # spec and refuses with the named CCSCInputError
                    # — a typo'd knob must error, never silently
                    # serve at 1/prod(mesh) capacity
                    env_malformed = True
        if fleet_cfg.replica_meshes is not None:
            self._replica_mesh = [
                tuple(m) if m else () for m in fleet_cfg.replica_meshes
            ]
        elif env_malformed:
            self._replica_mesh = [None] * fleet_cfg.replicas
        else:
            self._replica_mesh = [
                tuple(default_mesh) if default_mesh else ()
            ] * fleet_cfg.replicas
        # the shape a replica GROWN past the startup set inherits
        # (set_replica_count): the same default every startup replica
        # would get — None propagates the malformed-spec refusal
        self._default_mesh_entry = (
            None if env_malformed
            else (tuple(default_mesh) if default_mesh else ())
        )
        self._replica_devices: List[Optional[tuple]] = (
            [None] * fleet_cfg.replicas
        )
        # device-slice allocation survives growth: the pool and the
        # high-water offset persist so a replica grown later still
        # gets a DISJOINT slice (or the strict refusal)
        self._mesh_pool: Optional[List[int]] = None
        self._mesh_off = 0
        if any(m for m in self._replica_mesh):
            import jax

            # the allocation POOL: an operator-pinned
            # ServeConfig.mesh_devices (e.g. steering the fleet off
            # devices a colocated learner owns) is honored as the
            # pool the slices are cut from — a standalone engine
            # honors the pin, so moving to a fleet must not silently
            # change which silicon serves
            if serve_cfg.mesh_devices is not None:
                pool = list(serve_cfg.mesh_devices)
            else:
                pool = list(range(len(jax.devices())))
            self._mesh_pool = pool
            off = 0
            short: List[int] = []
            for rid, shape in enumerate(self._replica_mesh):
                if not shape:
                    continue
                need = _math.prod(shape)
                if off + need <= len(pool):
                    self._replica_devices[rid] = tuple(
                        pool[off:off + need]
                    )
                    off += need
                else:
                    short.append(rid)
            self._mesh_off = off
            if short and _env.env_flag("CCSC_SERVE_MESH_STRICT"):
                from ..utils import validate

                total_need = sum(
                    _math.prod(m)
                    for m in self._replica_mesh
                    if m
                )
                pool_desc = (
                    f"the pinned mesh_devices pool {tuple(pool)}"
                    if serve_cfg.mesh_devices is not None
                    else f"the {len(pool)} visible device(s)"
                )
                raise validate.CCSCInputError(
                    f"replica meshes "
                    f"{[m or None for m in self._replica_mesh]} need "
                    f"{total_need} device(s) for disjoint slices but "
                    f"{pool_desc} cannot supply them (replica(s) "
                    f"{short} left without a slice) — shrink the "
                    "meshes or replica count, force more host "
                    "devices (XLA_FLAGS=--xla_force_host_platform_"
                    f"device_count={total_need} on CPU), or set "
                    "CCSC_SERVE_MESH_STRICT=0 to let slices overlap "
                    "(the admission ceiling then over-credits the "
                    "shared devices)"
                )
            # non-strict: the short replicas fall back to the engine's
            # default device prefix (overlapping a sibling)

        self._cv = threading.Condition()
        # multi-tenant admission (serve.tenancy): declared tenants
        # get their own weighted-fair lanes, quotas, and SLO
        # monitors; with no tenants declared the scheduler degrades
        # to the historical single FIFO exactly
        self._tenants = _tenancy.TenantTable(fleet_cfg.tenants)
        self._queue = _tenancy.WeightedFairScheduler(self._tenants)
        self._tenant_slos = _slo.TenantSlos(fleet_cfg.tenants)
        self._tenant_delivered: Dict[str, int] = {}
        self._tenant_rejects: Dict[str, int] = {}
        # bank routing (serve.registry): bank_id -> digest, flipped
        # atomically by publish_bank (the fleet-wide hot-swap);
        # retained bank bytes let a restarted replica republish every
        # bank before it takes work
        default_digest = _registry.bank_digest(d)
        self._bank_routes: Dict[Optional[str], str] = {
            None: default_digest
        }
        self._bank_arrays: Dict[str, np.ndarray] = {
            default_digest: np.asarray(d)
        }
        self._index: Dict[str, _FleetRequest] = {}  # queued/assigned
        # served / failed idempotency keys, BOUNDED to the newest
        # FleetConfig.key_window each (insertion order = eviction
        # order): a long-lived fleet must not grow per-request state
        # forever — suppression and resubmit refusal hold within the
        # window, which only a straggler delayed by key_window
        # requests can outlive
        self._delivered: "OrderedDict[str, None]" = OrderedDict()
        # keys whose future got an error (max_attempts / no capacity):
        # a late straggler result for one is suppressed, and the key is
        # spent — exactly-once-OR-error, never both
        self._failed_keys: "OrderedDict[str, None]" = OrderedDict()
        # replica ids whose restart budget is exhausted — these never
        # come back; every OTHER retired replica has a restart pending
        self._abandoned: set = set()
        # latency sample for the stats percentiles, newest
        # latency_window deliveries (the delivered COUNT is
        # _n_delivered, which never truncates)
        self._latencies: Deque[float] = deque(
            maxlen=fleet_cfg.latency_window
        )
        self._n_delivered = 0
        self._seq = 0
        self._n_requeued = 0
        self._n_duplicates = 0
        self._n_rejected = 0
        self._n_failed = 0
        # -- request lifecycle (ISSUE 19): deadline/cancel/hedge
        # counters; per-replica recent-latency histograms (engine-
        # side solve latency, so fleet queueing noise — identical
        # across replicas — can't mask a gray one) feeding the
        # adaptive hedge_after quantile and the gray-failure scores
        self._n_admitted = 0
        self._n_deadline = 0
        self._n_cancelled = 0
        self._n_hedges = 0
        self._n_hedge_wins = 0
        self._lat_hist = _slo.Histogram()
        self._rep_hist: Dict[int, _slo.Histogram] = {}
        # replica ids currently judged gray (sustained latency
        # outlier vs the fleet median — slow-but-alive, DISTINCT from
        # the watchdog's stall detector) + their latest factor; the
        # fleet_gray_replica advisory fires once per excursion
        self._gray_now: set = set()
        self._gray_score: Dict[int, float] = {}
        self._restarts: Dict[int, int] = {}
        self._replicas: List[Optional[_Replica]] = [None] * (
            fleet_cfg.replicas
        )
        # -- elasticity (serve.controller / set_replica_count): the
        # fleet's replica count is a TARGET, not a constant. The list
        # above only ever grows; a slot retired by scale-down lands in
        # _scaled_down (excluded from capacity math and the dead-fleet
        # checks) until a later grow resurrects it. _slot_gen remembers
        # the last generation a drained slot served at, so a
        # resurrection keeps the per-slot generation monotonic (the
        # recycle walker's replacement test relies on it).
        self._replica_target = fleet_cfg.replicas
        self._scaled_down: set = set()
        self._slot_gen: Dict[int, int] = {}
        # gauges a CapacityController publishes through the fleet's
        # metrics surface (metricsd renders ccsc_ctrl_*); the breaker
        # gauge exists (closed) even with no controller attached
        self._ctrl_gauges: Dict[str, float] = {"ctrl_breaker_open": 0}
        self._degraded = False
        # controller-driven brownout (set_brownout): holds the
        # degraded solve budget independent of the overload ladder —
        # a rung-0 restore must not undo it
        self._brownout = False
        self._recycling = False
        self._rung = 0
        self._rung2_since: Optional[float] = None
        self._bound_rps = 0.0
        self._ceiling_derived = False
        self._ceiling = fleet_cfg.max_queue_depth or max(
            fleet_cfg.min_queue_depth,
            2 * self._total_slots * fleet_cfg.replicas,
        )
        # fleet-wide SLO layer (serve.slo): submit->result latency —
        # the path a CLIENT sees, including fleet queueing and requeue
        # retries a replica-local histogram cannot observe. Checked on
        # the monitor thread; breaches are fleet-scope events.
        self._slo = _slo.SloMonitor(
            _slo.resolve_targets(
                fleet_cfg.slo_p50_ms, fleet_cfg.slo_p99_ms
            )
        )
        # quality plane (serve.quality): per-(bank, tenant, bucket)
        # dB histograms, declared tenant floors
        # (TenantSpec.min_psnr_db), and the per-bank drift watch
        # judged against kind=quality ledger history. Checked on the
        # monitor thread beside the SLO tick; golden probes (below)
        # run on their own thread at probe_interval_s.
        self._quality = _quality.QualityMonitor(
            specs=fleet_cfg.tenants,
            drift_band_for=self._quality_drift_band,
        )
        # advisory demotion signals (quality_demote_advice): appended
        # on probe regression / drift, deduped per (bank, digest,
        # reason) excursion; a registry/controller — or the chaos
        # harness — consumes them via quality_advice()
        self._quality_advice: List[Dict] = []
        self._advice_seen: set = set()
        # bank_id -> the digest it routed to BEFORE the latest swap
        # (the advisory's to_digest — what a demotion restores)
        self._bank_prev: Dict[Optional[str], str] = {}
        self._n_probe_failures = 0
        self._probe_set: Optional[_quality.ProbeSet] = None
        self._probe_seq = 0
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_dir = _quality.resolve_probe_dir(
            fleet_cfg.probe_dir
        )
        _pi = fleet_cfg.probe_interval_s
        if _pi is None:
            _pi = _env.env_float("CCSC_PROBE_INTERVAL_S")
        self._probe_interval_s = float(_pi) if _pi else 0.0
        self._metricsd = None
        self._capture: Optional[_capture.WorkloadRecorder] = None
        self._t_start = time.time()
        # fleet run identity: stamped into the metricsd snapshot so a
        # stale metrics.prom left by a DEAD fleet is distinguishable
        # from this one's
        self.run_id = f"fleet-{os.getpid()}-{int(self._t_start)}"

        self._run = obs.start_run(
            fleet_cfg.metrics_dir,
            algorithm="serve_fleet",
            verbose=fleet_cfg.verbose,
            geom=prob.geom,
            cfg=cfg,
            replicas=fleet_cfg.replicas,
            buckets=[
                {"slots": s, "spatial": list(sp)}
                for s, sp in self.buckets
            ],
            max_queue_depth=fleet_cfg.max_queue_depth,
        )
        try:
            for rid in range(fleet_cfg.replicas):
                self._replicas[rid] = self._spawn_replica(
                    rid, generation=0, degraded=False
                )
            self._emit(
                "fleet_start",
                replica_id=None,
                replicas=fleet_cfg.replicas,
                queue_ceiling=self._ceiling,
                # per-replica device topology: a mixed mesh /
                # single-device fleet is readable from this one record
                replica_devices=[
                    rep.engine.devices if rep is not None else None
                    for rep in self._replicas
                ],
                total_devices=self.total_devices,
                ceiling_source=(
                    "explicit" if fleet_cfg.max_queue_depth
                    else "static_floor"
                ),
            )
            cap_dir = _capture.resolve_capture_dir(
                fleet_cfg.capture_dir
            )
            if cap_dir:
                # admission-level capture: ONE recorder at the fleet
                # boundary (replica engines never capture — N copies
                # of the same stream would not be a workload record)
                self._capture = _capture.WorkloadRecorder(
                    cap_dir,
                    sample=fleet_cfg.capture_sample,
                    emit=lambda type_, **f: self._emit(
                        type_, replica_id=None, **f
                    ),
                    meta={
                        "source": "serve_fleet",
                        "run_id": self.run_id,
                        "replicas": fleet_cfg.replicas,
                        "buckets": [
                            {"slots": s, "spatial": list(sp)}
                            for s, sp in self.buckets
                        ],
                        "geom": {
                            "spatial_support": list(
                                self.geom.spatial_support
                            ),
                            "num_filters": self.geom.num_filters,
                        },
                        "solve": {
                            "max_it": cfg.max_it,
                            "tol": cfg.tol,
                            "lambda_residual": cfg.lambda_residual,
                            "lambda_prior": cfg.lambda_prior,
                        },
                        # replicas resolve tuning themselves, so the
                        # solve dict above is the PRE-tune config; a
                        # replay must re-resolve under the same mode
                        # (same chip + store reproduces the arm) for
                        # bit parity to hold
                        "tune": serve_cfg.tune,
                    },
                )
            self._stop_monitor = threading.Event()
            self._hb_last = 0.0
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="ccsc-fleet-monitor",
                daemon=True,
            )
            self._monitor.start()
            if self._probe_interval_s > 0 and self._probe_dir:
                self._probe_thread = threading.Thread(
                    target=self._probe_loop,
                    name="ccsc-fleet-probes",
                    daemon=True,
                )
                self._probe_thread.start()
            self._start_metricsd()
        except BaseException:
            with self._close_lock:
                self._close_started = True
            self._closing.set()
            self._close_done.set()
            if self._metricsd is not None:
                try:
                    self._metricsd.stop()
                except Exception:
                    pass
            if self._capture is not None:
                try:
                    self._capture.close(status_note="init_failed")
                except Exception:
                    pass
            for rep in self._replicas:
                if rep is not None:
                    try:
                        rep.watchdog.stop()
                    except Exception:
                        pass
                    try:
                        rep.engine.close()
                    except Exception:
                        pass
            self._run.close(status="error")
            raise
        self._run.console(
            f"fleet: {fleet_cfg.replicas} replica(s) live, queue "
            f"ceiling {self._ceiling}",
            tier="brief",
        )

    # -- telemetry -----------------------------------------------------
    def _emit(self, type_: str, *, replica_id, **fields) -> None:
        """Single emission point for fleet records: ``replica_id`` is
        a REQUIRED argument (None only for fleet-scope records like
        admission/ceiling) so per-replica attribution can never be
        forgotten silently — the companion of the engine's ``_emit``,
        both lint-enforced."""
        self._run.event(type_, replica_id=replica_id, **fields)

    # -- live metrics surface ------------------------------------------
    def _start_metricsd(self) -> None:
        """Start the stdlib Prometheus endpoint + snapshot file
        (serve.metricsd) when FleetConfig.metricsd_port or
        CCSC_METRICSD_PORT asks for one. Best-effort: a port conflict
        must not take the fleet down with it."""
        from . import metricsd as metricsd_mod

        port, snap = metricsd_mod.resolve_endpoint(
            self.fleet_cfg.metricsd_port,
            self.fleet_cfg.metricsd_snapshot,
            self.fleet_cfg.metrics_dir,
        )
        if port is None and snap is None:
            return
        try:
            self._metricsd = metricsd_mod.MetricsD(
                self.metrics, port=port, snapshot_path=snap,
                run_id=self.run_id,
            ).start()
        except Exception as e:
            self._metricsd = None
            self._run.console(
                f"fleet: metrics endpoint failed to start "
                f"({type(e).__name__}: {e}) — serving without it",
                tier="always",
            )
            return
        self._emit(
            "fleet_metricsd", replica_id=None,
            port=self._metricsd.port, snapshot=snap,
        )
        self._run.console(
            "fleet: metrics "
            + (
                f"endpoint http://127.0.0.1:{self._metricsd.port}"
                "/metrics"
                if self._metricsd.port is not None
                else "snapshot-only"
            )
            + (f", snapshot {snap}" if snap else ""),
            tier="brief",
        )

    def metrics(self) -> Dict[str, object]:
        """Live counters/gauges/histograms in the shared shape
        ``serve.metricsd.render_prometheus`` renders. The request
        counter is ``_n_delivered`` — the never-truncating delivered
        count, so a scrape equals the number of served requests
        EXACTLY (the metricsd acceptance contract)."""
        with self._cv:
            counters = {
                "requests_total": self._n_delivered,
                "rejected_total": self._n_rejected,
                "requeued_total": self._n_requeued,
                "duplicates_suppressed_total": self._n_duplicates,
                "failed_total": self._n_failed,
                "probe_failures_total": self._n_probe_failures,
                # request lifecycle (ISSUE 19): rendered as
                # ccsc_hedges_total / ccsc_hedge_wins_total /
                # ccsc_deadline_exceeded_total / ccsc_cancelled_total
                "hedges_total": self._n_hedges,
                "hedge_wins_total": self._n_hedge_wins,
                "deadline_exceeded_total": self._n_deadline,
                "cancelled_total": self._n_cancelled,
            }
            n_live = sum(
                1 for r in self._replicas
                if r is not None and r.state == "live"
            )
            gauges = {
                "queue_depth": len(self._queue),
                "queue_ceiling": self._ceiling,
                "live_replicas": n_live,
                # controller-facing names: ccsc_replicas_live is the
                # autoscaling dashboard's canonical series (the
                # legacy live_replicas key is kept for old scrapes)
                "replicas_live": n_live,
                "replica_target": self._replica_target,
                "overload_rung": self._rung,
                "banks": len(self._bank_routes),
                # tenants currently judged below their declared dB
                # floor (ccsc_quality_breach — 0 is healthy)
                "quality_breach": self._quality.n_breached,
                # replicas currently judged gray (slow-but-alive
                # latency outliers — 0 is healthy)
                "gray_replicas": len(self._gray_now),
            }
            gauges.update(self._ctrl_gauges)
            # per-tenant labeled series: the shared constructor
            # (serve.metricsd.tenant_labeled_counters) keeps this
            # live surface and the stream-derived snapshot identical
            labeled = _metricsd_mod.tenant_labeled_counters(
                self._tenant_delivered, self._tenant_rejects
            )
        hists = [
            ("latency_ms", {"phase": sn["phase"]}, sn)
            for sn in self._slo.raw_snapshots()
        ] + [
            (
                "latency_ms",
                {"phase": sn["phase"], "tenant": sn["tenant"]},
                sn,
            )
            for sn in self._tenant_slos.raw_snapshots()
        ] + [
            (
                "psnr_db",
                {
                    "bank_id": sn["bank_id"],
                    "tenant": sn["tenant"],
                    "bucket": sn["bucket"],
                },
                sn,
            )
            for sn in self._quality.raw_snapshots()
        ]
        return {
            "counters": counters,
            "gauges": gauges,
            "labeled_counters": labeled,
            "histograms": hists,
        }

    # -- replica lifecycle ---------------------------------------------
    def _engine_cfg(self, degraded: bool) -> SolveConfig:
        if not degraded:
            return self.cfg
        f = self.fleet_cfg.degrade_max_it_factor
        return dataclasses.replace(
            self.cfg, max_it=max(1, int(self.cfg.max_it * f))
        )

    def _spawn_replica(
        self, rid: int, generation: int, degraded: bool
    ) -> _Replica:
        from ..utils import watchdog as wd_mod

        scfg = dataclasses.replace(
            self.serve_cfg,
            replica_id=rid,
            # replica engines never capture: the fleet records the
            # workload once at admission
            capture_dir=None,
            # this replica's device topology (heterogeneous fleets:
            # FleetConfig.replica_meshes; restarts reuse the same
            # disjoint device slice)
            mesh_shape=self._replica_mesh[rid],
            mesh_devices=(
                self._replica_devices[rid]
                if self._replica_mesh[rid]
                else None
            ),
            metrics_dir=(
                None if self.fleet_cfg.metrics_dir is None
                else os.path.join(
                    self.fleet_cfg.metrics_dir, f"replica-{rid:02d}"
                )
            ),
        )
        engine = CodecEngine(
            self._d, self._prob, self._engine_cfg(degraded), scfg,
            blur_psf=self._blur_psf,
        )
        # republish every known bank onto the fresh engine: a
        # restarted replica must be able to serve a requeued request
        # bound to ANY published digest (add_bank is idempotent for
        # the engine's own default bank, and the extra plan builds
        # ride the jitted build_plan cache — no XLA recompiles)
        with self._cv:
            extra_banks = list(self._bank_arrays.values())
        for arr in extra_banks:
            engine.add_bank(arr)
        if self._rung >= 1:
            # a replica (re)built while the ladder is shedding must
            # inherit the shed micro-batch deadline, not wait out the
            # configured one under exactly the pressure rung 1 exists
            # for
            try:
                engine.set_max_wait_ms(0.0)
            except Exception:
                pass
        watchdog = wd_mod.DispatchWatchdog(
            0.0,  # no analytic cost model: MIN_S floor + self-calibration
            action="event",
            algorithm="serve_fleet",
            replica_id=rid,
            run=self._run,  # stall records land in the FLEET stream,
            # not whichever replica's run happens to be newest
        )
        rep = _Replica(rid, generation, engine, watchdog, degraded)
        # the hook closes over the replica GENERATION: a stale
        # watchdog can never retire its successor
        watchdog.on_stall = (
            lambda label, rep=rep: self._on_replica_stall(rep, label)
        )
        rep.thread = threading.Thread(
            target=self._worker_loop, args=(rep,),
            name=f"ccsc-fleet-r{rid}", daemon=True,
        )
        rep.thread.start()
        return rep

    def _on_replica_stall(self, rep: _Replica, label: str) -> None:
        with self._cv:
            if rep.reaped or (
                rep.retired and rep.state != "recycling"
            ):
                # someone already handed this replica off (or a death
                # handler is about to — reaped gates exactly one)
                return
            rep.reaped = True
            rep.retired = True
            rep.state = "stalled"
        self._emit(
            "fleet_replica_dead", replica_id=rep.id, reason="stall",
            label=label,
        )
        self._run.console(
            f"fleet: replica {rep.id} stalled ({label}) — draining "
            "and restarting",
            tier="always",
        )
        self._requeue_from(rep, reason="stall")
        # cancel work still sitting in the stalled engine's micro-batch
        # queue: the fleet just requeued its own copies, and a
        # cancelled engine future unwedges the abandoned worker's
        # result() wait if it ever wakes
        try:
            rep.engine.drain_pending()
        except Exception:
            pass
        # the wedged worker thread is abandoned (daemon); if it ever
        # wakes it finds `retired` set, its late deliveries are
        # suppressed by the idempotency set, and it closes its engine
        # on the way out
        self._schedule_restart(rep)
        # satellite fix (ISSUE 17): the stall just removed live
        # capacity — recompute the derived admission ceiling at the
        # transition instead of waiting out the monitor's hysteresis
        self._refresh_ceiling(force=True)

    def _on_replica_death(self, rep: _Replica, exc: BaseException) -> None:
        with self._cv:
            # `reaped` is the handoff gate, not `retired`: a replica
            # retired for a rung-3 recycle still OWES its handoff — if
            # its worker crashes mid-dispatch before the clean recycle
            # exit, this handler must requeue its in-flight requests
            # and respawn the slot, or they are lost and the slot
            # stays a dead husk
            already = rep.reaped
            if not already:
                rep.reaped = True
                rep.retired = True
                rep.state = "dead"
        if already:
            # stall handler already drained + restarted this replica;
            # we are its abandoned worker waking up (often via the
            # drain's cancelled engine futures) — release the old
            # engine on the way out, nobody else holds it anymore
            try:
                rep.engine.close()
            except Exception:
                pass
            return
        self._emit(
            "fleet_replica_dead", replica_id=rep.id, reason="crash",
            error=f"{type(exc).__name__}: {exc}"[:300],
        )
        self._run.console(
            f"fleet: replica {rep.id} died ({type(exc).__name__}) — "
            "requeueing its requests and restarting",
            tier="always",
        )
        self._requeue_from(rep, reason="crash")
        try:
            # the fleet just requeued its own copies of everything the
            # engine still holds — drain them so close() below doesn't
            # spend a dispatch serving results nobody will read
            rep.engine.drain_pending()
            rep.engine.close()
        except Exception:
            pass
        self._schedule_restart(rep)
        # satellite fix (ISSUE 17): a dead replica stops contributing
        # capacity right now — the ceiling must follow at the
        # transition, not at the next hysteresis crossing
        self._refresh_ceiling(force=True)

    def _schedule_restart(self, rep: _Replica, charge: bool = True) -> None:
        """``charge=False`` for ladder recycles: a rung transition is
        maintenance, not a failure — it must neither consume the
        crash-restart budget nor escalate the backoff."""
        exhausted = False
        with self._cv:
            if self._close_started:
                return
            if rep.id in self._scaled_down:
                # the slot was retired by scale-down while this
                # casualty was in flight — drop it instead of
                # respawning capacity the controller just removed
                self._slot_gen[rep.id] = rep.generation
                if self._replicas[rep.id] is rep:
                    self._replicas[rep.id] = None
                scaled = True
            else:
                scaled = False
                n = self._restarts.get(rep.id, 0)
                if not charge:
                    attempt = 1
                elif n >= self.fleet_cfg.max_restarts:
                    self._abandoned.add(rep.id)
                    exhausted = True
                else:
                    self._restarts[rep.id] = n + 1
                    attempt = n + 1
        if scaled:
            self._emit(
                "fleet_replica_retired", replica_id=rep.id,
                reason="scale_down",
            )
            self._refresh_ceiling(force=True)
            return
        if exhausted:
            self._emit(
                "fleet_replica_abandoned", replica_id=rep.id,
                restarts=n,
            )
            self._run.console(
                f"fleet: replica {rep.id} restart budget "
                f"({self.fleet_cfg.max_restarts}) exhausted — "
                "serving on survivors",
                tier="always",
            )
            self._fail_if_no_capacity()
            # satellite fix (ISSUE 17): a half-dead fleet must stop
            # over-admitting NOW, not at the next monitor hysteresis
            # crossing — recompute the derived ceiling on the
            # abandon transition and emit on any change
            self._refresh_ceiling(force=True)
            return
        t = threading.Thread(
            target=self._restart, args=(rep, attempt),
            name=f"ccsc-fleet-restart-r{rep.id}", daemon=True,
        )
        with self._cv:
            self._restart_threads = [
                x for x in self._restart_threads if x.is_alive()
            ]
            self._restart_threads.append(t)
        t.start()

    def _restart(self, old: _Replica, attempt: int) -> None:
        try:
            old.watchdog.stop()
        except Exception:
            pass
        delay = min(
            self.fleet_cfg.restart_backoff_s * (2 ** (attempt - 1)),
            30.0,
        )
        if delay > 0 and self._closing.wait(delay):
            return
        if self._close_started:
            return
        with self._cv:
            if old.id in self._scaled_down:
                # scale-down landed during the backoff: the slot is
                # retired, do not rebuild capacity for it
                self._slot_gen[old.id] = old.generation
                if self._replicas[old.id] is old:
                    self._replicas[old.id] = None
                scaled = True
            else:
                scaled = False
        if scaled:
            self._emit(
                "fleet_replica_retired", replica_id=old.id,
                reason="scale_down",
            )
            self._refresh_ceiling(force=True)
            return
        self._emit(
            "fleet_replica_restart", replica_id=old.id,
            attempt=attempt, degraded=self._degraded,
        )
        try:
            rep = self._spawn_replica(
                old.id, old.generation + 1, degraded=self._degraded
            )
        except Exception as e:
            self._emit(
                "fleet_replica_dead", replica_id=old.id,
                reason="restart_failed",
                error=f"{type(e).__name__}: {e}"[:300],
            )
            self._schedule_restart(old)
            return
        with self._cv:
            closing = (
                self._close_started or old.id in self._scaled_down
            )
            if not closing:
                self._replicas[old.id] = rep
                self._cv.notify_all()
            elif old.id in self._scaled_down:
                self._slot_gen[old.id] = rep.generation
                if self._replicas[old.id] is old:
                    self._replicas[old.id] = None
        if closing:
            # close() (or a scale-down) raced the rebuild and will
            # never see this replica — release it here instead of
            # leaking the engine
            rep.retired = True
            try:
                rep.watchdog.stop()
            except Exception:
                pass
            rep.engine.close()
            return
        self._emit(
            "fleet_replica_ready", replica_id=old.id,
            generation=rep.generation,
            warm=bool(rep.engine.cache_dir),
            degraded=self._degraded,
        )
        # satellite fix (ISSUE 17): a rejoin changes live capacity —
        # recompute the derived ceiling at the transition
        self._refresh_ceiling(force=True)

    def _fail_if_no_capacity(self) -> None:
        """Called (NOT under self._cv) when a replica is abandoned: if
        NO replica is live or coming back, pending futures can never
        resolve — fail them explicitly (exactly-once-or-error). A
        replica that is merely retired (restart backoff / rebuild in
        flight) counts as coming back — only budget exhaustion
        (``_abandoned``) is terminal, so a transient all-retired
        window must not error recoverable requests. The exceptions are
        set AFTER the lock is released (same discipline as
        ``_requeue_from`` / ``close``): ``Future.set_exception`` runs
        done-callbacks synchronously, and a client callback that
        re-enters the fleet — e.g. resubmitting under a fresh key —
        would deadlock on the non-reentrant Condition."""
        doom_spans: List = []  # (req, queue_span, root_owed)
        with self._cv:
            alive = any(
                rid not in self._abandoned
                and rid not in self._scaled_down
                for rid in range(len(self._replicas))
            )
            if alive:
                return
            doomed = list(self._queue)
            self._queue.clear()
            for r in doomed:
                self._index.pop(r.key, None)
                self._remember(self._failed_keys, r.key)
                if r.trace_id is not None:
                    qs, r.queue_span = r.queue_span, None
                    owed = not r.root_done
                    r.root_done = True
                    doom_spans.append((r, qs, owed))
            self._n_failed += len(doomed)
        wall = time.time()
        for r, qs, root_owed in doom_spans:
            if qs:
                trace_util.end_span(
                    self._emit, trace_id=r.trace_id, span="queue",
                    span_id=qs, parent_span=r.root_span,
                    status="error", ts=wall,
                )
            if root_owed:
                trace_util.end_span(
                    self._emit, trace_id=r.trace_id,
                    span=trace_util.ROOT_SPAN, span_id=r.root_span,
                    status="error", ts=wall, t_start=r.t_wall,
                )
        for r in doomed:
            try:
                r.future.set_exception(
                    RuntimeError(
                        "fleet has no live replicas left (restart "
                        "budgets exhausted)"
                    )
                )
            except InvalidStateError:
                pass

    # -- requeue / delivery --------------------------------------------
    def _remember(self, store: "OrderedDict[str, None]", key: str) -> None:
        """Record a spent key (served or failed) under self._cv,
        evicting the oldest beyond FleetConfig.key_window."""
        store[key] = None
        while len(store) > self.fleet_cfg.key_window:
            store.popitem(last=False)

    def _requeue_from(self, rep: _Replica, reason: str) -> None:
        failed: List[_FleetRequest] = []
        wall = time.time()
        # span actions, emitted after the lock: the casualty's open
        # ownership span ends ('requeued' or 'error') and each
        # requeued request re-opens a queue span — the trace carries
        # the handoff, so a killed replica's request still reassembles
        # as ONE story
        requeue_spans: List = []  # (req, old_attempt_span, att_t, new_queue_span)
        fail_spans: List = []  # (req, old_attempt_span, att_t, root_owed)
        with self._cv:
            lost = [
                r for r in rep.assigned
                if r.key not in self._delivered
                and not r.future.cancelled()
            ]
            rep.assigned = []
            requeued = []
            for r in lost:
                if r.attempts >= self.fleet_cfg.max_attempts:
                    failed.append(r)
                    self._index.pop(r.key, None)
                    self._remember(self._failed_keys, r.key)
                    if r.trace_id is not None:
                        att, r.attempt_span = r.attempt_span, None
                        pr = r.primary or r
                        owed = not pr.root_done
                        pr.root_done = True
                        r.root_done = True
                        fail_spans.append((r, att, r.attempt_t, owed))
                else:
                    requeued.append(r)
                    if r.trace_id is not None:
                        att, r.attempt_span = r.attempt_span, None
                        att_t = r.attempt_t
                        r.queue_span = trace_util.new_span_id()
                        r.queue_t = wall
                        requeue_spans.append(
                            (r, att, att_t, r.queue_span)
                        )
            # hand-offs go to the FRONT of the queue: they already
            # waited their turn once
            for r in reversed(requeued):
                self._queue.appendleft(r)
            self._n_requeued += len(requeued)
            self._n_failed += len(failed)
            self._cv.notify_all()
        for r, att, att_t, new_q in requeue_spans:
            if att:
                trace_util.end_span(
                    self._emit, trace_id=r.trace_id, span="attempt",
                    span_id=att, parent_span=r.root_span,
                    replica_id=rep.id, status="requeued", ts=wall,
                    t_start=att_t, reason=reason,
                )
            trace_util.start_span(
                self._emit, trace_id=r.trace_id, span="queue",
                span_id=new_q, parent_span=r.root_span, ts=wall,
                attempt=r.attempts + 1,
            )
        for r, att, att_t, root_owed in fail_spans:
            if att:
                trace_util.end_span(
                    self._emit, trace_id=r.trace_id, span="attempt",
                    span_id=att, parent_span=r.root_span,
                    replica_id=rep.id, status="error", ts=wall,
                    t_start=att_t, reason=reason,
                )
            if root_owed:
                trace_util.end_span(
                    self._emit, trace_id=r.trace_id,
                    span=trace_util.ROOT_SPAN, span_id=r.root_span,
                    status="error", ts=wall, t_start=r.t_wall,
                    attempts=r.attempts,
                )
        for r in failed:
            try:
                r.future.set_exception(
                    RuntimeError(
                        f"request {r.key!r} failed after "
                        f"{r.attempts} delivery attempts "
                        "(exactly-once-or-error: no result was "
                        "delivered)"
                    )
                )
            except InvalidStateError:
                pass
        if requeued or failed:
            # a casualty that had already delivered everything it took
            # is not a hand-off — emitting n=0 records here would
            # inflate the FLEET report's drain count on every clean
            # restart
            self._emit(
                "fleet_requeue", replica_id=rep.id, reason=reason,
                n=len(requeued), n_failed=len(failed),
                keys=[r.key for r in requeued][:16],
            )

    def _deliver(
        self, rep: _Replica, req: _FleetRequest, res: ServedResult
    ) -> None:
        lat = time.perf_counter() - req.t_submit
        att_span = None
        att_t = 0.0
        root_owed = False
        hedge_won = False
        lost_span = None
        lost_rep = None
        lost_t = 0.0
        with self._cv:
            # a key whose future already carries an error (max_attempts
            # exhausted) is as spent as a served one: recording a late
            # straggler result for it would report a request the client
            # saw FAIL as served in the stats and obs stream
            dup = (
                req.key in self._delivered
                or req.key in self._failed_keys
            )
            if not dup:
                self._remember(self._delivered, req.key)
                self._index.pop(req.key, None)
                self._latencies.append(lat)
                self._n_delivered += 1
                if req.tenant is not None:
                    self._tenant_delivered[req.tenant] = (
                        self._tenant_delivered.get(req.tenant, 0) + 1
                    )
                rep.served += 1
                # per-replica recent-latency histograms (engine-side
                # solve time): the gray-failure scores and the
                # adaptive hedge_after quantile read these
                self._lat_hist.observe(res.latency_s * 1e3)
                self._rep_hist.setdefault(
                    rep.id, _slo.Histogram()
                ).observe(res.latency_s * 1e3)
                if req.hedge_of:
                    # the hedged duplicate beat the original attempt
                    self._n_hedge_wins += 1
                    hedge_won = True
                # claim the open spans under the lock: a racing
                # requeue/close path can then never double-end them.
                # The root claim goes through the PRIMARY instance so
                # a hedge pair's two delivery paths can never
                # double-end the shared root span.
                if req.trace_id is not None:
                    att_span, req.attempt_span = req.attempt_span, None
                    att_rep = req.attempt_rep
                    att_t = req.attempt_t
                    pr = req.primary or req
                    root_owed = not pr.root_done
                    pr.root_done = True
                    req.root_done = True
            else:
                self._n_duplicates += 1
                # a hedge loser's attempt span is still OPEN (neither
                # requeue nor delivery claimed it): close it as the
                # suppressed half of the race
                if (req.hedged or req.hedge_of) and req.attempt_span:
                    lost_span, req.attempt_span = req.attempt_span, None
                    lost_rep = req.attempt_rep
                    lost_t = req.attempt_t
            try:
                rep.assigned.remove(req)
            except ValueError:
                pass  # requeued from under us (stall handoff)
        if dup:
            # at-most-once delivery: a recovered straggler's late
            # result for a key a survivor already served (or the fleet
            # already failed) is dropped
            self._emit(
                "fleet_duplicate_suppressed", replica_id=rep.id,
                trace_id=req.trace_id, key=req.key,
                failed_key=req.key in self._failed_keys,
            )
            if lost_span is not None:
                owner = rep.id if lost_rep is None else lost_rep
                trace_util.end_span(
                    self._emit, trace_id=req.trace_id, span="attempt",
                    span_id=lost_span, parent_span=req.root_span,
                    replica_id=owner, status="hedge_lost",
                    ts=time.time(), t_start=lost_t,
                )
                self._emit(
                    "hedge_lost", replica_id=owner,
                    trace_id=req.trace_id, key=req.key,
                )
            return
        self._slo.observe("total", lat * 1e3)
        # the tenant's OWN histogram: per-tenant p50/p99 vs declared
        # targets, untouched by other tenants' bursts
        self._tenant_slos.observe(req.tenant, lat * 1e3)
        # quality plane: fold the delivered valid-region dB (None on
        # requests without ground truth — a no-op) into the
        # per-(bank, tenant, bucket) histograms and the bank's drift
        # watch; a drift excursion fires here (the monitor returns
        # the records, nothing is emitted under its lock) and also
        # raises a demotion advisory
        if res.psnr is not None:
            with self._cv:
                q_digest = self._bank_routes.get(req.bank_id)
            for fire in self._quality.observe(
                res.psnr,
                bank_id=req.bank_id,
                tenant=req.tenant,
                bucket=res.bucket,
                digest=q_digest,
            ):
                self._emit(
                    "quality_drift", replica_id=None, **fire
                )
                self._advise_demotion(
                    req.bank_id, fire.get("digest"), "drift"
                )
        try:
            req.future.set_result(res)
        except InvalidStateError:
            pass  # client cancelled between checks
        wall = time.time()
        if att_span is not None:
            # the claimed span keeps ITS owner's identity: when a
            # recovered straggler wins the delivery race after a
            # requeue, the new owner's open span ends as
            # 'superseded' (its solve was not the delivered result —
            # the fleet_request record names the actual deliverer)
            owner = rep.id if att_rep is None else att_rep
            trace_util.end_span(
                self._emit, trace_id=req.trace_id, span="attempt",
                span_id=att_span, parent_span=req.root_span,
                replica_id=owner,
                status="ok" if owner == rep.id else "superseded",
                ts=wall, t_start=att_t, bucket=res.bucket,
            )
        if root_owed:
            trace_util.end_span(
                self._emit, trace_id=req.trace_id,
                span=trace_util.ROOT_SPAN, span_id=req.root_span,
                status="ok", ts=wall, t_start=req.t_wall,
                attempts=req.attempts,
            )
        if hedge_won:
            self._emit(
                "hedge_win", replica_id=rep.id,
                trace_id=req.trace_id, key=req.key,
            )
        self._emit(
            "fleet_request", replica_id=rep.id, trace_id=req.trace_id,
            key=req.key, attempts=req.attempts, bucket=res.bucket,
            latency_ms=round(lat * 1e3, 3),
            requeued=req.attempts > 1,
            tenant=req.tenant, bank_id=req.bank_id,
        )
        if self._capture is not None and not req.key.startswith(
            _quality.PROBE_KEY_PREFIX
        ):
            # outcome digest pairs the delivered bytes with the
            # captured request — the bit-parity oracle replay checks
            # (probe keys skipped, mirroring the submit-side guard)
            self._capture.record_outcome(
                req.key, res.recon, res.psnr, lat * 1e3, res.bucket,
                iters=int(res.trace.num_iters),
            )

    # -- the replica worker --------------------------------------------
    def _take(self, rep: _Replica) -> Optional[List[_FleetRequest]]:
        # span actions collected under the lock, EMITTED after release
        # (no stream I/O under the queue mutex): (queue_span_id, req,
        # status, root_end_owed) for drops, (queue_span_id,
        # attempt_span_id, req, attempt_no, t_queue) for takes
        dropped: List = []
        taken: List = []
        expired: List[_FleetRequest] = []
        cancelled: List[_FleetRequest] = []
        with self._cv:
            while True:
                if rep.retired:
                    return None
                if self._queue:
                    break
                if self._close_started:
                    return None
                self._cv.wait(timeout=0.1)
            # span clock AFTER the wait: this is when the take happens
            wall = time.time()
            batch: List[_FleetRequest] = []
            skipped: List[_FleetRequest] = []
            while self._queue and len(batch) < self._take_cap:
                req = self._queue.popleft()
                if (
                    req.key in self._delivered
                    or req.key in self._failed_keys
                ):
                    # requeued copy of a key a straggler already
                    # resolved — solving it again would only be
                    # suppressed at delivery; drop it for free here
                    self._index.pop(req.key, None)
                    if req.trace_id is not None and req.queue_span:
                        qs, req.queue_span = req.queue_span, None
                        dropped.append((qs, req, "dropped", False))
                    continue
                if req.deadline is not None and wall >= req.deadline:
                    # already dead: refusing here costs a queue pop,
                    # solving it would waste a full solve slot. Marked
                    # failed so a late hedge-twin delivery suppresses
                    # as a duplicate.
                    self._index.pop(req.key, None)
                    self._remember(self._failed_keys, req.key)
                    self._n_deadline += 1
                    expired.append(req)
                    if req.trace_id is not None and req.queue_span:
                        qs, req.queue_span = req.queue_span, None
                        pr = req.primary or req
                        owed = not pr.root_done
                        pr.root_done = True
                        req.root_done = True
                        dropped.append((qs, req, "deadline", owed))
                    continue
                if req.attempts == 0 and not req.hedge_of:
                    if not req.future.set_running_or_notify_cancel():
                        self._index.pop(req.key, None)
                        self._n_cancelled += 1
                        cancelled.append(req)
                        if req.trace_id is not None and req.queue_span:
                            qs, req.queue_span = req.queue_span, None
                            pr = req.primary or req
                            owed = not pr.root_done
                            pr.root_done = True
                            req.root_done = True
                            dropped.append(
                                (qs, req, "cancelled", owed)
                            )
                        continue  # client cancelled while queued
                elif req.future.cancelled():
                    # hedge clones share the primary's (already
                    # running) future, so they always land here; count
                    # the cancellation once, on the primary instance
                    self._index.pop(req.key, None)
                    if not req.hedge_of:
                        self._n_cancelled += 1
                        cancelled.append(req)
                    if req.trace_id is not None and req.queue_span:
                        qs, req.queue_span = req.queue_span, None
                        pr = req.primary or req
                        owed = not pr.root_done
                        pr.root_done = True
                        req.root_done = True
                        dropped.append((qs, req, "cancelled", owed))
                    continue
                if req.not_replica == rep.id or (
                    req.hedge_of and rep.id in self._gray_now
                ):
                    # a hedge clone must land on a DIFFERENT replica
                    # than its primary's attempt, and not on one
                    # currently scored gray — a hedge onto the slow
                    # replica would be no hedge at all
                    skipped.append(req)
                    continue
                req.attempts += 1
                if req.trace_id is not None:
                    qs, req.queue_span = req.queue_span, None
                    req.attempt_span = trace_util.new_span_id()
                    req.attempt_rep = rep.id
                    req.attempt_t = wall
                    taken.append(
                        (qs, req.attempt_span, req, req.attempts,
                         req.queue_t)
                    )
                rep.assigned.append(req)
                batch.append(req)
            for r in reversed(skipped):
                self._queue.appendleft(r)
            if skipped and not batch:
                # everything queued was a hedge this replica may not
                # take — yield briefly instead of busy-spinning
                self._cv.wait(timeout=0.05)
            rep.req_seq += len(batch)
        for qs, req, status, root_owed in dropped:
            trace_util.end_span(
                self._emit, trace_id=req.trace_id, span="queue",
                span_id=qs, parent_span=req.root_span, status=status,
                ts=wall,
            )
            if root_owed:
                trace_util.end_span(
                    self._emit, trace_id=req.trace_id,
                    span=trace_util.ROOT_SPAN, span_id=req.root_span,
                    status=status, ts=wall, t_start=req.t_wall,
                )
        for req in expired:
            # fail the future OUTSIDE the lock (done-callbacks run
            # inline). A hedge twin may have resolved it already —
            # the spent-key record above is the authoritative fence.
            try:
                if req.attempts == 0 and not req.hedge_of:
                    if not req.future.set_running_or_notify_cancel():
                        continue  # cancelled first: nothing to fail
                req.future.set_exception(
                    DeadlineExceeded("queue", req.deadline)
                )
            except InvalidStateError:
                pass
            self._emit(
                "deadline_exceeded", replica_id=rep.id,
                where="queue", deadline=round(req.deadline, 3),
                key=req.key, trace_id=req.trace_id,
            )
        for req in cancelled:
            self._emit(
                "request_cancelled", replica_id=rep.id,
                where="queue", key=req.key, trace_id=req.trace_id,
            )
        for qs, att, req, attempt_no, t_queue in taken:
            if qs:
                trace_util.end_span(
                    self._emit, trace_id=req.trace_id, span="queue",
                    span_id=qs, parent_span=req.root_span,
                    status="ok", ts=wall, t_start=t_queue,
                )
            trace_util.start_span(
                self._emit, trace_id=req.trace_id, span="attempt",
                span_id=att, parent_span=req.root_span,
                replica_id=rep.id, ts=wall, attempt=attempt_no,
            )
        return batch

    def _process(self, rep: _Replica, batch: List[_FleetRequest]) -> None:
        from ..utils import faults, validate

        seq0 = rep.req_seq - len(batch)
        stalls_before = rep.watchdog.stalls
        t0 = time.monotonic()
        # the health fence covers the injected faults too: a hang
        # sleeping here is indistinguishable from a wedged dispatch,
        # which is the point
        rep.watchdog.arm(len(batch), label=f"replica{rep.id}-dispatch")
        try:
            for i in range(len(batch)):
                s = seq0 + i + 1
                dur = faults.engine_hang_request(rep.id, s)
                if dur > 0:
                    time.sleep(dur)
                # gray-replica fault: SLOW, not hung — the sleep stays
                # far under the watchdog floor, so only the hedging /
                # gray-score plane may react, never the stall plane
                dur = faults.engine_slow_request(rep.id, s)
                if dur > 0:
                    time.sleep(dur)
                if faults.engine_kill_request(rep.id, s):
                    raise faults.InjectedFault(
                        f"injected engine kill on replica {rep.id} "
                        f"(request #{s})"
                    )
            def _submit_to_engine(r):
                # _validated: admission already ran the full request
                # checks and canonicalized the arrays — no second
                # finiteness scan per ownership. _trace threads the
                # span context: the engine's dispatch/solve spans
                # nest under THIS ownership span, in the replica's
                # own stream
                return rep.engine.submit(
                    r.b, mask=r.mask, smooth_init=r.smooth_init,
                    x_orig=r.x_orig,
                    bank_id=r.bank_id, tenant=r.tenant,
                    _validated=True,
                    _trace=(
                        (r.trace_id, r.attempt_span)
                        if r.trace_id is not None
                        else None
                    ),
                    # the ADMISSION-TIME digest, not the engine's
                    # current route: a hot-swap between admission and
                    # ownership must not retarget this request
                    _digest=r.digest or None,
                    # the ABSOLUTE deadline rides along: the engine
                    # refuses/expires it pre-dispatch instead of
                    # burning a solve slot on a request nobody waits for
                    _deadline=r.deadline,
                )

            futs = []
            for r in batch:
                try:
                    futs.append(_submit_to_engine(r))
                except DeadlineExceeded as e:
                    # engine-side admission expiry: terminal for THIS
                    # request only, never a replica fault
                    futs.append(e)
                except validate.CCSCInputError:
                    # a replica registered concurrently with a
                    # publish_bank rollout can miss the new bank
                    # (spawned after the rollout's replica snapshot,
                    # snapshot of _bank_arrays taken before the
                    # publish landed): heal from the fleet's
                    # retained bytes and retry — a routing gap must
                    # never read as a replica death
                    with self._cv:
                        arr = self._bank_arrays.get(r.digest)
                    if arr is None:
                        raise
                    rep.engine.add_bank(arr)
                    futs.append(_submit_to_engine(r))
            results = []
            for f in futs:
                if isinstance(f, DeadlineExceeded):
                    results.append(f)
                    continue
                try:
                    results.append(f.result(timeout=600.0))
                except DeadlineExceeded as e:
                    # the engine's pre-dispatch sweep expired it while
                    # queued for a micro-batch — same terminal contract
                    results.append(e)
        finally:
            rep.watchdog.disarm()
        if rep.watchdog.stalls == stalls_before:
            # teach the watchdog this replica's real measured pace
            # (same role as LearnConfig.watchdog_slack: deadline =
            # observed per-request time x stall_slack). A fence the
            # watchdog fired on is NOT representative — it may include
            # an injected hang's sleep.
            per = (time.monotonic() - t0) / len(batch)
            rep.watchdog.per_iter_s = max(
                rep.watchdog.per_iter_s,
                self.fleet_cfg.stall_slack * per,
            )
        for req, res in zip(batch, results):
            if isinstance(res, DeadlineExceeded):
                self._fail_request(rep, req, res)
            else:
                self._deliver(rep, req, res)

    def _fail_request(
        self, rep: _Replica, req: _FleetRequest, exc: DeadlineExceeded
    ) -> None:
        """Terminal per-request failure (deadline expiry inside the
        engine): fail the client future and close the spans WITHOUT
        burning a fleet retry — the request is dead by contract, not
        by replica fault, so it must never reach _requeue_from."""
        att_span = None
        att_t = 0.0
        root_owed = False
        with self._cv:
            dup = (
                req.key in self._delivered
                or req.key in self._failed_keys
            )
            if not dup:
                self._remember(self._failed_keys, req.key)
                self._index.pop(req.key, None)
                self._n_deadline += 1
            if req.trace_id is not None and req.attempt_span:
                att_span, req.attempt_span = req.attempt_span, None
                att_t = req.attempt_t
                pr = req.primary or req
                root_owed = not pr.root_done
                pr.root_done = True
                req.root_done = True
            try:
                rep.assigned.remove(req)
            except ValueError:
                pass  # requeued from under us (stall handoff)
        if not dup:
            try:
                req.future.set_exception(exc)
            except InvalidStateError:
                pass  # client cancelled between checks
        wall = time.time()
        if att_span is not None:
            trace_util.end_span(
                self._emit, trace_id=req.trace_id, span="attempt",
                span_id=att_span, parent_span=req.root_span,
                replica_id=rep.id, status="deadline", ts=wall,
                t_start=att_t,
            )
        if root_owed:
            trace_util.end_span(
                self._emit, trace_id=req.trace_id,
                span=trace_util.ROOT_SPAN, span_id=req.root_span,
                status="deadline", ts=wall, t_start=req.t_wall,
                attempts=req.attempts,
            )
        if not dup:
            self._emit(
                "deadline_exceeded", replica_id=rep.id,
                where=exc.where, deadline=round(exc.deadline, 3),
                key=req.key, trace_id=req.trace_id,
            )

    def _worker_loop(self, rep: _Replica) -> None:
        while True:
            batch = self._take(rep)
            if batch is None:
                break
            if not batch:
                continue
            try:
                self._process(rep, batch)
            except BaseException as e:
                self._on_replica_death(rep, e)
                return
        # clean exit: fleet close, or a retire (stall handoff /
        # recycle). The stall path already scheduled the replacement;
        # a clean recycle claims the handoff here (reaped gates
        # exactly one of us) and schedules it after the engine is
        # released — nothing to requeue, _take stopped before this
        # batch was taken.
        with self._cv:
            recycle = rep.state == "recycling" and not rep.reaped
            draining = rep.state == "draining" and not rep.reaped
            if recycle or draining:
                rep.reaped = True
        if recycle:
            # normally nothing is in flight here (_take stopped before
            # another batch was taken, _process delivered the last
            # one), but the handoff contract is uniform: whoever
            # claims `reaped` requeues whatever is left
            self._requeue_from(rep, reason="recycle")
        elif draining:
            # scale-down: drain-then-retire, never a kill — leftovers
            # (normally none; _take stopped before another batch) go
            # back to the FRONT of the queue for the survivors
            self._requeue_from(rep, reason="scale_down")
        if rep.retired:
            try:
                rep.engine.close()
            except Exception:
                pass
        if recycle:
            self._schedule_restart(rep, charge=False)
        elif draining:
            # no replacement is scheduled: the slot empties and the
            # capacity math (ceiling, dead-fleet checks, devices)
            # follows the new target immediately
            with self._cv:
                rep.state = "stopped"
                self._slot_gen[rep.id] = rep.generation
                if self._replicas[rep.id] is rep:
                    self._replicas[rep.id] = None
            self._emit(
                "fleet_replica_retired", replica_id=rep.id,
                reason="scale_down",
            )
            self._refresh_ceiling(force=True)

    # -- monitor: heartbeats, ceiling, overload ladder ------------------
    def _monitor_loop(self) -> None:
        from ..utils import perfmodel

        hb_every = self.fleet_cfg.heartbeat_s
        while not self._stop_monitor.wait(
            self.fleet_cfg.health_interval_s
        ):
            now = time.monotonic()
            with self._cv:
                depth = len(self._queue)
                reps = list(self._replicas)
            if self.fleet_cfg.max_queue_depth is None:
                self._update_ceiling(perfmodel, reps)
            self._eval_rungs(depth, now)
            if now - self._hb_last >= hb_every:
                self._hb_last = now
                for rep in reps:
                    if rep is None:
                        continue
                    self._emit(
                        "fleet_heartbeat", replica_id=rep.id,
                        state=rep.state, generation=rep.generation,
                        served=rep.served, inflight=len(rep.assigned),
                        queue_depth=depth,
                        restarts=self._restarts.get(rep.id, 0),
                        devices=rep.engine.devices,
                    )
            # fleet-wide SLO check (serve.slo): submit->result
            # latency vs the declared targets, plus the periodic
            # histogram snapshot any stream reader can recompute
            # percentiles from
            breaches, snaps = self._slo.tick(now)
            for br in breaches:
                self._emit("slo_breach", replica_id=None, **br)
            for sn in snaps:
                self._emit("slo_histogram", replica_id=None, **sn)
            # per-TENANT SLO checks: each declared tenant's own
            # histogram vs its own declared band — the records carry
            # the tenant name (obs_report TENANTS)
            t_breaches, t_snaps = self._tenant_slos.tick(now)
            for br in t_breaches:
                self._emit("slo_breach", replica_id=None, **br)
            for sn in t_snaps:
                self._emit("slo_histogram", replica_id=None, **sn)
            # quality plane: tenant dB floors vs declared
            # min_psnr_db (quality_breach, the slo_breach
            # discipline), periodic per-(bank, tenant, bucket) dB
            # snapshots, and the per-bucket solve diagnostics
            q_breaches, q_snaps, q_diags = self._quality.tick(now)
            for br in q_breaches:
                self._emit("quality_breach", replica_id=None, **br)
            for sn in q_snaps:
                self._emit(
                    "quality_histogram", replica_id=None, **sn
                )
            for dg in q_diags:
                self._emit(
                    "quality_solve_diag", replica_id=None, **dg
                )
            # request lifecycle: gray-failure scores from the
            # per-replica latency histograms, then hedge any attempt
            # that has outwaited the hedge threshold
            self._hedge_and_gray_tick()

    def _hedge_after_ms(self) -> Optional[float]:
        """The hedge trigger threshold: a stuck attempt older than
        this gets a second attempt on another replica. Resolution:
        ``FleetConfig.hedge_after_ms`` > ``CCSC_HEDGE_AFTER_MS`` >
        the ``hedge_quantile`` (default p95) of the fleet-wide
        engine-side latency histogram — adaptive, so 'slow' means
        slow RELATIVE to what this fleet actually serves. None while
        the histogram is too thin to judge (no hedging yet)."""
        if self.fleet_cfg.hedge_after_ms is not None:
            return self.fleet_cfg.hedge_after_ms
        env_ms = _env.env_float("CCSC_HEDGE_AFTER_MS")
        if env_ms is not None:
            return float(env_ms)
        q = self.fleet_cfg.hedge_quantile
        if q is None:
            q = float(_env.env_float("CCSC_HEDGE_QUANTILE"))
        with self._cv:
            if self._lat_hist.n < 5:
                return None
            return self._lat_hist.percentile(q)

    def _hedge_and_gray_tick(self) -> None:
        """One monitor-tick pass of the gray-failure plane.

        Gray scoring: a replica whose engine-side latency p50 is
        ``CCSC_GRAY_FACTOR``x the median of the replica p50s is
        scored gray — a sustained latency OUTLIER, a weaker (and
        earlier) signal than the watchdog's hard stall. Gray is
        advisory: the replica keeps serving, but hedges avoid it and
        a deduped ``fleet_gray_replica`` event (the recycle hint)
        marks the excursion.

        Hedging: any in-flight attempt older than the hedge
        threshold gets ONE duplicate attempt enqueued for a
        different, non-gray replica — first result wins through the
        delivery fence, the loser is suppressed-and-counted. Total
        hedges are capped at ``hedge_max_frac`` of admitted requests
        so a fleet-wide slowdown cannot double its own load."""
        gray_factor = float(_env.env_float("CCSC_GRAY_FACTOR"))
        frac = self.fleet_cfg.hedge_max_frac
        if frac is None:
            frac = float(_env.env_float("CCSC_HEDGE_MAX_FRAC"))
        hedge_ms = self._hedge_after_ms()
        wall = time.time()
        gray_events: List[Dict[str, object]] = []
        spawned: List[Tuple[_FleetRequest, int, float]] = []
        with self._cv:
            live = [
                rep for rep in self._replicas
                if rep is not None and rep.state == "live"
            ]
            # -- gray scores (needs >= 2 replicas for a median) -----
            p50s = {}
            for rep in live:
                h = self._rep_hist.get(rep.id)
                if h is not None and h.n >= 5:
                    p = h.percentile(0.5)
                    if p is not None:
                        p50s[rep.id] = p
            if len(p50s) >= 2:
                med = sorted(p50s.values())[len(p50s) // 2]
                for rid, p in p50s.items():
                    factor = p / max(med, 1e-9)
                    self._gray_score[rid] = round(factor, 3)
                    if factor >= gray_factor and med > 0:
                        if rid not in self._gray_now:
                            # one event per excursion, not per tick
                            self._gray_now.add(rid)
                            gray_events.append({
                                "replica_id": rid,
                                "p50_ms": round(p, 3),
                                "fleet_p50_ms": round(med, 3),
                                "factor": round(factor, 3),
                            })
                    else:
                        self._gray_now.discard(rid)
            # -- hedge spawns ---------------------------------------
            if hedge_ms is not None and len(live) >= 2 and frac > 0:
                budget = frac * max(self._n_admitted, 1)
                for rep in live:
                    for req in list(rep.assigned):
                        if self._n_hedges >= budget:
                            break
                        if req.hedged or req.hedge_of:
                            continue  # one hedge per request, ever
                        if req.attempt_t <= 0:
                            continue
                        waited = (wall - req.attempt_t) * 1e3
                        if waited < hedge_ms:
                            continue
                        if (
                            req.key in self._delivered
                            or req.key in self._failed_keys
                        ):
                            continue
                        if req.deadline is not None and (
                            wall >= req.deadline
                        ):
                            continue  # expiry owns it, not hedging
                        if req.future.cancelled():
                            continue
                        clone = _FleetRequest(
                            key=req.key, b=req.b, mask=req.mask,
                            smooth_init=req.smooth_init,
                            x_orig=req.x_orig,
                            future=req.future,
                            t_submit=req.t_submit,
                            tenant=req.tenant, bank_id=req.bank_id,
                            digest=req.digest,
                            deadline=req.deadline,
                            trace_id=req.trace_id,
                            root_span=req.root_span,
                            queue_span=trace_util.new_span_id(),
                            t_wall=req.t_wall, queue_t=wall,
                            hedged=True, hedge_of=True,
                            not_replica=rep.id, primary=req,
                        )
                        req.hedged = True
                        # NOT in _index: the key's index entry stays
                        # the primary's; the clone is reachable only
                        # through the queue and the shared future
                        self._queue.append(clone)
                        self._n_hedges += 1
                        spawned.append((clone, rep.id, waited))
                if spawned:
                    self._cv.notify_all()
        for ev in gray_events:
            self._emit("fleet_gray_replica", **ev)
        for clone, owner, waited in spawned:
            self._emit(
                "hedge_spawn", replica_id=owner,
                trace_id=clone.trace_id, key=clone.key,
                waited_ms=round(waited, 3),
                hedge_after_ms=round(hedge_ms, 3),
            )
            if clone.trace_id is not None:
                trace_util.start_span(
                    self._emit, trace_id=clone.trace_id,
                    span="queue", span_id=clone.queue_span,
                    parent_span=clone.root_span, ts=wall,
                    attempt=2, hedge=True,
                )

    # -- quality plane (serve.quality) ---------------------------------
    def _quality_drift_band(
        self, bank_id: Optional[str], digest: str
    ) -> Optional[Dict[str, float]]:
        """The drift watch's historical band for one bank: the
        quality band over EVERY kind=quality ledger record of this
        bank id and workload — deliberately across digests, so a
        freshly-swapped rotten bank is judged against the good
        history it replaced, not its own. None (no ledger / thin
        history) leaves that bank unwatched."""
        try:
            from ..analysis import ledger as _ledger
            from ..tune import store as tune_store

            if not _ledger.enabled():
                return None
            workload = tune_store.solve_workload(self.geom)
            bank_key = bank_id or "default"
            vals = [
                float(r["value"])
                for r in _ledger.Ledger().read()
                if r.get("kind") == "quality"
                and r.get("workload") == workload
                and (r.get("knobs") or {}).get("bank") == bank_key
            ]
            min_history = _env.env_int("CCSC_PERF_GATE_MIN_HISTORY")
            if len(vals) < min_history:
                return None
            return _quality.quality_band(vals)
        except Exception:  # pragma: no cover - defensive
            return None

    def _advise_demotion(
        self,
        bank_id: Optional[str],
        from_digest: Optional[str],
        reason: str,
    ) -> None:
        """Record + emit one advisory demotion signal: the bank's
        served quality regressed (probe or drift evidence) and the
        previously-routed digest — if the fleet saw one — is the
        restoration candidate. ADVISORY by design: the fleet never
        swaps a bank on its own (a flapping probe must not flap
        production routing); a registry/controller or operator
        consumes quality_advice() and decides. Deduped per
        (bank, digest, reason)."""
        key = (bank_id, from_digest, reason)
        with self._cv:
            if key in self._advice_seen:
                return
            self._advice_seen.add(key)
            advice = {
                "bank_id": bank_id,
                "from_digest": from_digest,
                "to_digest": self._bank_prev.get(bank_id),
                "reason": reason,
                "t": time.time(),
            }
            self._quality_advice.append(advice)
        self._emit(
            "quality_demote_advice",
            replica_id=None,
            bank_id=bank_id,
            from_digest=from_digest,
            to_digest=advice["to_digest"],
            reason=reason,
        )

    def quality_advice(self) -> List[Dict]:
        """Advisory demotion signals accumulated so far (newest
        last) — each carries bank_id, the regressing from_digest,
        the restoration to_digest (the digest the bank routed to
        before its last swap, None if never swapped), and the
        evidence reason ('probe' | 'drift')."""
        with self._cv:
            return list(self._quality_advice)

    def _probe_loop(self) -> None:
        """Golden probes through idle capacity: every
        probe_interval_s, serve the deterministic probe set against
        every routed bank id and judge each result bit-exact + in dB
        against the stored reference for the bank's CURRENT digest
        (serve.quality.ProbeSet). Skipped while the queue has real
        work — probes ride idle replicas only. A regression emits
        quality_probe_breach and raises a demotion advisory."""
        while not self._stop_monitor.wait(self._probe_interval_s):
            with self._cv:
                busy = len(self._queue) > 0
                bank_ids = list(self._bank_routes)
            if busy or self._close_started:
                continue
            try:
                self._run_probes(bank_ids)
            except Exception:
                # a probe failure (draining fleet, bucket rebuild)
                # must never take the probe thread down — the next
                # interval retries
                continue

    def _run_probes(self, bank_ids) -> None:
        if self._probe_set is None:
            # auto-generate on first use: deterministic payloads per
            # configured bucket, idempotent on an existing store.
            # Content is synthesized through the PINNED bank — the
            # only content whose served dB ranks banks (synth_probe)
            self._probe_set = _quality.ProbeSet.generate(
                self._probe_dir, self.geom, self.buckets,
                d=self._d,
            )
        for bank_id in bank_ids:
            self._probe_seq += 1
            verdicts = self._probe_set.run(
                self,
                bank_id=bank_id,
                key_seq=self._probe_seq,
                timeout=600.0,
            )
            for v in verdicts:
                self._emit(
                    "quality_probe",
                    replica_id=None,
                    probe=v["probe"],
                    bank_id=v["bank_id"],
                    digest=v["digest"],
                    status=v["status"],
                    db=v["db"],
                    ref_db=v["ref_db"],
                )
                if v["status"] == "regressed":
                    with self._cv:
                        self._n_probe_failures += 1
                    self._emit(
                        "quality_probe_breach",
                        replica_id=None,
                        probe=v["probe"],
                        bank_id=v["bank_id"],
                        digest=v["digest"],
                        db=v["db"],
                        ref_db=v["ref_db"],
                    )
                    self._advise_demotion(
                        bank_id, v["digest"], "probe"
                    )

    def _refresh_ceiling(self, force: bool = False) -> None:
        """Recompute the derived admission ceiling NOW (satellite fix,
        ISSUE 17): called at every replica lifecycle transition —
        retire, rejoin, abandon, scale — so a half-dead fleet stops
        over-admitting at the transition instead of at the monitor's
        next 1.5x hysteresis crossing. ``force`` emits
        ``fleet_ceiling`` on ANY change, bypassing the hysteresis
        band (which exists to quiet steady-state jitter, not to
        delay capacity news)."""
        if (
            self.fleet_cfg.max_queue_depth is not None
            or self._close_started
        ):
            return
        from ..utils import perfmodel

        with self._cv:
            reps = list(self._replicas)
        self._update_ceiling(perfmodel, reps, force=force)

    def _replica_warm(self, rep: _Replica) -> bool:
        """Every declared bucket's program installed and serveable on
        this replica's engine. A replica staging its warmup
        (ServeConfig.staged_warmup) is LIVE for the buckets it has,
        but the capacity math must not credit it at full rate until
        it is past BucketCold everywhere — the scale-up admission
        gate of serve.controller."""
        try:
            return all(
                rep.engine.bucket_warm((s, sp))
                for s, sp in self.buckets
            )
        except Exception:
            return False

    def _update_ceiling(self, perfmodel, reps, force=False) -> None:
        live = [
            r for r in reps
            if r is not None and r.state == "live"
            and self._replica_warm(r)
        ]
        # per-replica bounds, device-count aware: each live replica
        # contributes its OWN measured rate; an unmeasured one is
        # credited at the best measured per-device rate times its
        # device count (perfmodel.fleet_serving_bound) — a mesh
        # replica is a multiple of a single-device replica's
        # capacity, and a ceiling that counted replicas instead of
        # devices would reject exactly the load the mesh bought.
        # The EFFECTIVE solve budget still applies: rung 3 recycles
        # replicas onto max_it x degrade_max_it_factor, which raises
        # real request throughput.
        bound = perfmodel.fleet_serving_bound(
            [
                (r.engine.last_it_rate, r.engine.devices)
                for r in live
            ],
            max(1, self._engine_cfg(self._degraded).max_it),
            self._total_slots,
            occupancy=1.0,
        )
        if bound["measured"] == 0:
            return
        self._bound_rps = bound["requests_per_sec"]
        derived = max(
            self.fleet_cfg.min_queue_depth,
            int(self._bound_rps * self.fleet_cfg.max_queue_s),
        )
        old = self._ceiling
        hysteresis = (
            not self._ceiling_derived or derived > 1.5 * old
            or derived < old / 1.5
        )
        if hysteresis or (force and derived != old):
            self._ceiling = derived
            self._ceiling_derived = True
            self._emit(
                "fleet_ceiling", replica_id=None, ceiling=derived,
                bound_requests_per_sec=round(self._bound_rps, 3),
                live_replicas=len(live),
                live_devices=sum(r.engine.devices for r in live),
                source="serving_bound",
            )

    def _set_rung(self, rung: int, depth: int) -> None:
        old = self._rung
        if rung == old:
            return
        self._rung = rung
        self._rung2_since = (
            time.monotonic() if rung == 2 else None
        )
        self._emit(
            "fleet_overload", replica_id=None,
            rung_from=RUNGS[old], rung_to=RUNGS[rung],
            queue_depth=depth, ceiling=self._ceiling,
        )
        self._run.console(
            f"fleet: overload ladder {RUNGS[old]} -> {RUNGS[rung]} "
            f"(queue {depth}/{self._ceiling})",
            tier="brief",
        )
        # rung effects on live engines (best-effort: a replica mid-
        # restart picks up the current rung when it next matters)
        shed = rung >= 1
        for rep in self._replicas:
            if rep is None or rep.retired:
                continue
            try:
                rep.engine.set_max_wait_ms(
                    0.0 if shed else self.serve_cfg.max_wait_ms
                )
            except Exception:
                pass
        if rung == 3 and not self._degraded:
            self._degraded = True
            self._emit(
                "degrade", replica_id=None, rung="serve_max_it",
                stage="overload",
                max_it=self._engine_cfg(True).max_it,
            )
            self._start_recycle()
        elif rung == 0 and self._degraded and not self._brownout:
            self._degraded = False
            self._emit(
                "degrade", replica_id=None, rung="serve_restore",
                stage="overload", max_it=self.cfg.max_it,
            )
            self._start_recycle()

    def _eval_rungs(self, depth: int, now: float) -> None:
        c = max(1, self._ceiling)
        frac = depth / c
        f = self.fleet_cfg
        r = self._rung
        if r == 3:
            if frac < f.shed_exit:
                self._set_rung(0, depth)
        elif r == 2:
            if frac < f.shed_exit:
                self._set_rung(0, depth)
            elif frac < f.reject_exit:
                self._set_rung(1, depth)
            elif (
                f.degrade_after_s > 0
                and self._rung2_since is not None
                and now - self._rung2_since > f.degrade_after_s
            ):
                self._set_rung(3, depth)
        elif r == 1:
            if frac >= 1.0:
                self._set_rung(2, depth)
            elif frac < f.shed_exit:
                self._set_rung(0, depth)
        else:
            if frac >= 1.0:
                self._set_rung(2, depth)
            elif frac >= f.shed_at:
                self._set_rung(1, depth)

    def _start_recycle(self) -> None:
        """Staggered replica recycle onto the current degrade state:
        one replica at a time, so capacity never drops below N-1."""
        with self._cv:
            if self._recycling or self._close_started:
                return
            self._recycling = True
            # tracked, not fire-and-forget: close() joins it so an
            # interpreter exit can never catch it mid-work (lint:
            # thread-safety; _recycling gates at most one alive).
            # Started INSIDE the lock: publishing an unstarted thread
            # and starting it after release would let a racing
            # close() join() a never-started Thread (RuntimeError
            # mid-cleanup). The new thread's first act is to take
            # this same lock, so it simply blocks until we release.
            self._recycle_thread = threading.Thread(
                target=self._recycle_loop, name="ccsc-fleet-recycle",
                daemon=True,
            )
            self._recycle_thread.start()

    def _recycle_loop(self) -> None:
        try:
            # loop until every live replica matches the CURRENT target
            # — capturing a fixed target and bailing when the ladder
            # moves would strand already-recycled replicas on the old
            # budget (the rung flip's own _start_recycle no-ops while
            # this thread holds _recycling)
            while not self._close_started:
                target = self._degraded
                with self._cv:
                    todo = [
                        rep for rep in self._replicas
                        if rep is not None and not rep.retired
                        and rep.degraded != target
                    ]
                    if not todo:
                        if self._degraded == target:
                            return
                        continue  # target moved during the scan
                    rep = todo[0]
                    rep.retired = True
                    rep.state = "recycling"
                    self._cv.notify_all()
                # wait for the replacement (engine rebuild rides the
                # warm compile cache) before touching the next one
                deadline = time.monotonic() + 120.0
                while time.monotonic() < deadline:
                    if self._close_started:
                        return
                    if rep.id in self._abandoned:
                        # the recycling replica crashed under us and
                        # exhausted its restart budget — no replacement
                        # is coming, move on
                        break
                    cur = self._replicas[rep.id]
                    if (
                        cur is not None
                        and cur.generation > rep.generation
                        and cur.state == "live"
                    ):
                        break
                    time.sleep(0.05)
        finally:
            with self._cv:
                self._recycling = False
            # a rung flip that raced our exit had its _start_recycle
            # no-oped against the flag we just cleared — re-check and
            # reschedule so no replica is stranded on a stale budget
            if not self._close_started:
                with self._cv:
                    stranded = any(
                        rep is not None and not rep.retired
                        and rep.degraded != self._degraded
                        for rep in self._replicas
                    )
                if stranded:
                    self._start_recycle()

    # -- public API ----------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._close_started

    @property
    def total_devices(self) -> int:
        """Devices across all replica engines (a single-device
        replica counts 1, a mesh replica prod(mesh_shape))."""
        return sum(
            rep.engine.devices
            for rep in self._replicas
            if rep is not None
        ) or max(1, self._replica_target)

    @property
    def capacity_hint(self) -> int:
        """Total concurrent request capacity across replicas — the
        natural claim-batch bound for a drain worker feeding this
        fleet from an external queue (serve.federation). Counts MESH
        slots: a replica sharded over D devices turns a bucket
        dispatch around ~D times faster, so it sustains ~D
        single-device replicas' worth of claimed work — an
        all-single-device fleet keeps the historical
        slots x replicas value exactly."""
        return self._total_slots * self.total_devices

    @property
    def queue_ceiling(self) -> int:
        """The current admission ceiling (explicit or
        serving_bound-derived)."""
        return self._ceiling

    @property
    def overload_rung(self) -> str:
        return RUNGS[self._rung]

    # -- elasticity: the control plane's actuators ----------------------
    @property
    def replica_target(self) -> int:
        """The replica count the fleet is currently converging to —
        the single source of truth a (re)started CapacityController
        reconciles from: the controller holds NO durable state of its
        own, so its death or restart can never disagree with the
        data plane about how much capacity exists."""
        return self._replica_target

    def set_replica_count(self, n: int, reason: str = "manual") -> Dict[str, int]:
        """Live grow/shrink to ``n`` replicas (the fine-grain
        elasticity actuator, ISSUE 17). Strictly a data-plane
        operation: callers (serve.controller, an operator REPL) are
        advisory.

        Grow spawns fresh replicas onto the next free device slices,
        warmed from the artifact store when one is configured
        (``ServeConfig.artifact_store`` — fetch instead of compile);
        a grown replica is admitted into the derived ceiling only
        once every bucket is past ``BucketCold``
        (``_replica_warm`` gates ``_update_ceiling``). Shrink is
        drain-then-retire, never a kill: the highest-id replicas stop
        taking work, finish their in-flight batch, requeue any
        leftovers to the FRONT of the queue, and release their
        engines — zero lost requests by construction. Returns
        ``{"from_n", "to_n"}``; raises ``CCSCInputError`` for n < 1
        and ``RuntimeError`` on a closed fleet (or a strict device
        pool that cannot supply another disjoint slice)."""
        import math as _math

        from ..utils import validate

        n = int(n)
        if n < 1:
            raise validate.CCSCInputError(
                f"replica count must be >= 1, got {n}"
            )
        if self._close_started:
            raise RuntimeError("fleet is closed")
        spawn: List[int] = []
        with self._cv:
            if self._close_started:
                raise RuntimeError("fleet is closed")
            cur = self._replica_target
            if n == cur:
                return {"from_n": cur, "to_n": n}
            if n > cur:
                add = n - cur
                # resurrect drained slots first (their device slice
                # is already reserved), then append fresh ones
                for rid in sorted(self._scaled_down):
                    if add == 0:
                        break
                    if self._replicas[rid] is None:
                        self._scaled_down.discard(rid)
                        self._restarts.pop(rid, None)
                        self._abandoned.discard(rid)
                        spawn.append(rid)
                        add -= 1
                while add > 0:
                    rid = len(self._replicas)
                    entry = self._default_mesh_entry
                    devices = None
                    if entry:
                        if self._mesh_pool is None:
                            import jax

                            self._mesh_pool = (
                                list(self.serve_cfg.mesh_devices)
                                if self.serve_cfg.mesh_devices
                                is not None
                                else list(range(len(jax.devices())))
                            )
                        need = _math.prod(entry)
                        pool = self._mesh_pool
                        if self._mesh_off + need <= len(pool):
                            devices = tuple(
                                pool[self._mesh_off:
                                     self._mesh_off + need]
                            )
                            self._mesh_off += need
                        elif _env.env_flag("CCSC_SERVE_MESH_STRICT"):
                            # roll back: nothing spawned yet, so the
                            # resurrected slots return to the drained
                            # set and the target stays where it was
                            for r2 in spawn:
                                self._scaled_down.add(r2)
                            raise RuntimeError(
                                f"cannot grow to {n} replicas: the "
                                f"device pool ({len(pool)} device(s),"
                                f" {self._mesh_off} allocated) has no"
                                f" disjoint {entry} slice left — "
                                "shrink the mesh, free devices, or "
                                "set CCSC_SERVE_MESH_STRICT=0"
                            )
                    self._replicas.append(None)
                    self._replica_mesh.append(entry)
                    self._replica_devices.append(devices)
                    spawn.append(rid)
                    add -= 1
                self._replica_target = n
            else:
                shed = cur - n
                for rid in range(len(self._replicas) - 1, -1, -1):
                    if shed == 0:
                        break
                    if rid in self._scaled_down:
                        continue
                    self._scaled_down.add(rid)
                    shed -= 1
                    rep = self._replicas[rid]
                    if rep is not None and not rep.retired:
                        # drain-then-retire: _take stops handing this
                        # worker batches; its clean exit requeues
                        # leftovers and empties the slot. An already-
                        # retired slot (recycle/restart in flight)
                        # is dropped by the _scaled_down guards in
                        # _schedule_restart/_restart instead.
                        rep.retired = True
                        rep.state = "draining"
                self._replica_target = n
                self._cv.notify_all()
        self._emit(
            "fleet_scale", replica_id=None, from_n=cur, to_n=n,
            reason=reason,
        )
        self._run.console(
            f"fleet: scaling {cur} -> {n} replica(s) ({reason})",
            tier="brief",
        )
        for rid in spawn:
            gen = self._slot_gen.get(rid, -1) + 1
            try:
                rep = self._spawn_replica(
                    rid, generation=gen, degraded=self._degraded
                )
            except BaseException:
                # a failed grow must not leave a husk slot the
                # dead-fleet checks count as coming back
                with self._cv:
                    self._scaled_down.add(rid)
                    self._replica_target -= 1
                raise
            with self._cv:
                closing = (
                    self._close_started or rid in self._scaled_down
                )
                if not closing:
                    self._replicas[rid] = rep
                    self._cv.notify_all()
            if closing:
                rep.retired = True
                try:
                    rep.watchdog.stop()
                except Exception:
                    pass
                rep.engine.close()
                continue
            self._emit(
                "fleet_replica_ready", replica_id=rid,
                generation=gen,
                warm=bool(rep.engine.cache_dir),
                degraded=self._degraded,
            )
        self._refresh_ceiling(force=True)
        return {"from_n": cur, "to_n": n}

    def set_brownout(self, on: bool, reason: str = "controller") -> bool:
        """Drive the degrade rung directly (the controller's brownout
        actuator): ``on`` recycles replicas onto the reduced
        ``max_it x degrade_max_it_factor`` solve budget WITHOUT
        waiting for the overload ladder's rung-3 escalation — trade
        solve quality for throughput BEFORE any shed. ``off``
        restores the full budget unless the ladder itself holds
        rung 3. Idempotent; returns whether the call changed
        state."""
        with self._cv:
            if self._close_started:
                raise RuntimeError("fleet is closed")
            if on == self._brownout:
                return False
            self._brownout = on
            if on:
                changed = not self._degraded
                self._degraded = True
            else:
                # the ladder still demands degrade at rung 3 — the
                # brownout flag releases, the budget stays down
                changed = self._degraded and self._rung < 3
                if changed:
                    self._degraded = False
        if on and changed:
            self._emit(
                "degrade", replica_id=None, rung="serve_max_it",
                stage="brownout",
                max_it=self._engine_cfg(True).max_it,
            )
            self._start_recycle()
        elif not on and changed:
            self._emit(
                "degrade", replica_id=None, rung="serve_restore",
                stage="brownout", max_it=self.cfg.max_it,
            )
            self._start_recycle()
        return True

    @property
    def brownout(self) -> bool:
        return self._brownout

    def set_ctrl_gauge(self, name: str, value: float) -> None:
        """Publish a controller gauge through the fleet's metrics
        surface (rendered as ``ccsc_<name>`` by serve.metricsd)."""
        with self._cv:
            self._ctrl_gauges[name] = value

    def control_snapshot(self) -> Dict[str, object]:
        """One consistent sensor read for the control plane
        (serve.controller): queue depth vs ceiling, rung, live/warm
        replica counts vs target, SLO percentiles vs declared
        targets, serving bound, and the fleet-wide warmup ETA.
        Carries its own wall-clock ``t`` — the controller's
        staleness detector compares against it and fails safe."""
        with self._cv:
            depth = len(self._queue)
            live = [
                r for r in self._replicas
                if r is not None and r.state == "live"
            ]
            snap = {
                "t": time.time(),
                "queue_depth": depth,
                "ceiling": self._ceiling,
                "rung": self._rung,
                "live_replicas": len(live),
                "replica_target": self._replica_target,
                "abandoned": len(self._abandoned),
                "bound_rps": round(self._bound_rps, 3),
                "brownout": self._brownout,
                # request-lifecycle plane: gray excursions and the
                # hedge/deadline/cancel tallies — the controller and
                # ops surfaces read recycle hints from here
                "gray_replicas": sorted(self._gray_now),
                "gray_scores": dict(self._gray_score),
                "hedges": self._n_hedges,
                "hedge_wins": self._n_hedge_wins,
                "deadline_exceeded": self._n_deadline,
                "cancelled": self._n_cancelled,
            }
        snap["warm_replicas"] = sum(
            1 for r in live if self._replica_warm(r)
        )
        etas = []
        for s, sp in self.buckets:
            eta = self._cold_eta((s, sp))
            if eta is not None:
                etas.append(eta)
        snap["warmup_eta_s"] = round(max(etas), 3) if etas else 0.0
        p99 = self._slo.percentile("total", 0.99)
        snap["p99_ms"] = None if p99 is None else round(p99, 3)
        snap["slo_p99_target_ms"] = self.fleet_cfg.slo_p99_ms
        return snap

    def _cold_eta(self, bkey) -> Optional[float]:
        """None when some LIVE replica already serves ``bkey``'s
        program — or no live replica exists to ask (the dead-fleet
        refusals own that path) — else the smallest warmup ETA across
        the staging replicas: the bucket is cold fleet-wide and the
        caller should back off that long."""
        with self._cv:
            engines = [
                rep.engine
                for rep in self._replicas
                if rep is not None
                and rep.state == "live"
                and rep.engine is not None
            ]
        etas = []
        for eng in engines:
            try:
                if eng.bucket_warm(bkey):
                    return None
                etas.append(eng.warmup_eta_s())
            except Exception:
                # a replica mid-death answers nothing — its casualty
                # handling is the watchdog's job, not admission's
                continue
        return min(etas) if etas else None

    def _resolve_deadline(
        self,
        tenant: Optional[str],
        deadline_ms: Optional[float],
        _deadline: Optional[float],
    ) -> Optional[float]:
        """Absolute wall-clock deadline of one submission. An
        internal absolute hand-off wins unconditionally (a cross-host
        budget must SHRINK through each hop, never reset); else the
        explicit relative budget, else the tenant's declared default,
        else the fleet config, else ``CCSC_REQ_DEADLINE_MS``, else
        None (unbounded — the pre-deadline contract)."""
        if _deadline is not None:
            return float(_deadline)
        if deadline_ms is None:
            spec = self._tenants.get(tenant)
            if spec is not None and spec.deadline_ms is not None:
                deadline_ms = spec.deadline_ms
            elif self.fleet_cfg.deadline_ms is not None:
                deadline_ms = self.fleet_cfg.deadline_ms
            else:
                deadline_ms = _env.env_float("CCSC_REQ_DEADLINE_MS")
        if deadline_ms is None:
            return None
        return time.time() + float(deadline_ms) / 1e3

    def submit(
        self, b, mask=None, smooth_init=None, x_orig=None,
        key: Optional[str] = None,
        bank_id: Optional[str] = None,
        tenant: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        _deadline: Optional[float] = None,
    ) -> "Future[ServedResult]":
        """Enqueue one observation; returns a Future of
        :class:`~.engine.ServedResult`.

        ``key`` is the request's idempotency key (auto-assigned when
        None): resubmitting a key that is still queued/in-flight
        returns the SAME future; a key that was already delivered —
        or already failed — is refused (at-most-once delivery and
        exactly-once-or-error: a key resolves once, ever; the fleet
        does not cache results). ``tenant`` names a declared
        :class:`~..config.TenantSpec` (admission then rides that
        tenant's weighted-fair lane, quota, and SLO histogram; an
        unknown name is refused — a typo must not silently bypass its
        quota). ``bank_id`` routes to a published bank (explicit id >
        the tenant's declared default > the fleet's pinned bank); the
        request binds that bank's DIGEST here, so a concurrent
        hot-swap never retargets admitted work. ``deadline_ms`` is the
        request's END-TO-END budget, relative to now (resolution:
        explicit > ``TenantSpec.deadline_ms`` >
        ``FleetConfig.deadline_ms`` > ``CCSC_REQ_DEADLINE_MS`` > no
        deadline); once it expires, the request is refused/failed
        with :class:`~.engine.DeadlineExceeded` at whatever stage it
        has reached — it never occupies a solve slot past expiry.
        ``_deadline`` (internal) is an ABSOLUTE ``time.time()``
        deadline passed through by cross-host hand-offs so queueing
        upstream shrinks the remaining budget instead of resetting
        it. Raises :class:`Overloaded` at the admission ceiling OR
        the tenant's quota (a ``tenant_reject`` — other tenants keep
        being admitted), :class:`~.engine.BucketCold` while no live
        replica has warmed the request's bucket yet (staged warmup —
        carries the same ``retry_after_s`` backoff contract),
        :class:`~.engine.DeadlineExceeded` when the budget is already
        spent at admission, and ``CCSCInputError`` for malformed
        requests."""
        from ..utils import validate

        if self._close_started:
            raise RuntimeError("fleet is closed")
        deadline = self._resolve_deadline(
            tenant, deadline_ms, _deadline
        )
        if deadline is not None and time.time() >= deadline:
            # stamped-dead on arrival: refuse before ANY admission
            # work — the client's budget is spent, honesty beats a
            # wasted solve
            with self._cv:
                self._n_deadline += 1
            self._emit(
                "deadline_exceeded", replica_id=None,
                where="admission", deadline=round(deadline, 3),
            )
            raise DeadlineExceeded("admission", deadline)
        validate.check_serve_request(
            b, self.geom, mask=mask, smooth_init=smooth_init,
            x_orig=x_orig,
        )
        self._tenants.check(tenant)
        eff_bank = self._tenants.route(tenant, bank_id)
        spatial = tuple(
            int(s) for s in np.shape(b)[self.geom.ndim_reduce:]
        )
        # oversize refusal, pre-queue (the picked bucket also names
        # the capture record's expected program)
        bslots, bsp = pick_bucket(self.buckets, spatial)
        # staged-warmup admission (serve.engine BucketCold): when NO
        # live replica has this bucket's program installed yet, refuse
        # just this bucket with a retry hint — the fleet keeps serving
        # its warm buckets while replicas stage. Checked BEFORE the
        # canonicalizing copies: a refused request must stay cheap.
        cold_eta = self._cold_eta((bslots, bsp))
        if cold_eta is not None:
            jitter = _env.env_float("CCSC_FED_RETRY_JITTER") or 0.0
            if jitter > 0:
                cold_eta *= 1.0 + random.random() * jitter
            self._emit(
                "bucket_cold", replica_id=None,
                bucket=_bucket_name(bslots, bsp),
                retry_after_s=round(cold_eta, 3),
            )
            raise BucketCold(_bucket_name(bslots, bsp), cold_eta)
        # canonicalize OUTSIDE the fleet lock: four potentially-large
        # array copies per request must not serialize every submitter
        # against the workers' _take/_deliver — nothing here reads
        # guarded state
        to32 = lambda a: None if a is None else np.asarray(a, np.float32)
        b32 = np.asarray(b, np.float32)
        mask32 = to32(mask)
        smooth32 = to32(smooth_init)
        xorig32 = to32(x_orig)
        wall0 = time.time()  # span clock: admission starts here
        reject = None
        treject = None
        with self._cv:
            if self._close_started:
                raise RuntimeError("fleet is closed")
            if not any(
                rid not in self._abandoned
                and rid not in self._scaled_down
                for rid in range(len(self._replicas))
            ):
                # every non-scaled-down replica's restart budget is
                # exhausted — no worker will ever take this request,
                # so an accepted future could never resolve
                raise RuntimeError(
                    "fleet has no live replicas left (restart budgets "
                    "exhausted)"
                )
            if key is not None:
                if key in self._index:
                    return self._index[key].future
                if key in self._delivered:
                    raise validate.CCSCInputError(
                        f"idempotency key {key!r} was already served "
                        "(at-most-once delivery: the fleet does not "
                        "cache results)"
                    )
                if key in self._failed_keys:
                    raise validate.CCSCInputError(
                        f"idempotency key {key!r} already failed "
                        "(exactly-once-or-error: the key is spent; "
                        "retry under a fresh key)"
                    )
            # bank digest binds UNDER the lock: publish_bank flips
            # the route under the same lock, so an admission can
            # never observe a torn route table
            digest = self._bank_routes.get(eff_bank)
            if digest is None:
                raise validate.CCSCInputError(
                    f"unknown bank id {eff_bank!r} — published: "
                    f"{sorted(k for k in self._bank_routes if k)} "
                    "(the fleet's pinned bank routes as "
                    "bank_id=None; publish_bank adds more)"
                )
            depth = len(self._queue)
            # per-tenant quota FIRST (the more specific refusal): a
            # bursting tenant gets its own Overloaded while other
            # tenants' admissions — and the shared queue capacity —
            # are untouched
            tq = self._tenants.quota(tenant, self._ceiling)
            if tq is not None and self._queue.depth_of(tenant) >= tq:
                self._tenant_rejects[tenant] = (
                    self._tenant_rejects.get(tenant, 0) + 1
                )
                retry = (
                    max(self._queue.depth_of(tenant), 1)
                    / self._bound_rps
                    if self._bound_rps > 0
                    else 1.0
                )
                retry = min(max(retry, 0.05), 60.0)
                treject = (
                    tenant, self._queue.depth_of(tenant), tq, retry
                )
            # rung 2 IS the reject rung: admission stays shut while
            # the ladder holds it, even once the queue dips back under
            # the hard ceiling — FleetConfig.reject_exit (the monitor's
            # exit fraction) is the hysteresis that reopens the door,
            # not the ceiling itself. Rung 3 reopens admission: the
            # degraded (faster) solve budget is what the fleet trades
            # for serving under sustained pressure, so only the hard
            # ceiling gates it there.
            elif depth >= self._ceiling or self._rung == 2:
                self._n_rejected += 1
                retry = (
                    max(depth, 1) / self._bound_rps
                    if self._bound_rps > 0
                    else 1.0
                )
                retry = min(max(retry, 0.05), 60.0)
                # emit + raise AFTER releasing the lock (the reject
                # event write can block on the stream file)
                reject = (depth, self._ceiling, RUNGS[self._rung], retry)
            else:
                if key is None:
                    # auto-assigned keys must not collide with a
                    # user-supplied key of the same shape: a collision
                    # would cross-wire two requests' delivery
                    # bookkeeping
                    while True:
                        self._seq += 1
                        key = f"req-{self._seq:08d}"
                        if (
                            key not in self._index
                            and key not in self._delivered
                            and key not in self._failed_keys
                        ):
                            break
                req = _FleetRequest(
                    key=key,
                    b=b32,
                    mask=mask32,
                    smooth_init=smooth32,
                    x_orig=xorig32,
                    future=Future(),
                    t_submit=time.perf_counter(),
                    tenant=tenant,
                    bank_id=eff_bank,
                    digest=digest,
                    deadline=deadline,
                    # span ids are assigned UNDER the lock (cheap id
                    # generation, no I/O) so a worker that takes this
                    # request immediately already sees them; the
                    # span events themselves are emitted after release
                    trace_id=trace_util.new_trace_id(),
                    root_span=trace_util.new_span_id(),
                    queue_span=trace_util.new_span_id(),
                    t_wall=wall0,
                    queue_t=time.time(),
                )
                self._index[req.key] = req
                self._queue.append(req)
                self._n_admitted += 1  # the hedge-rate denominator
                # snapshot the span ids before releasing the lock: a
                # worker can take the request (claiming queue_span)
                # the instant we release
                qspan = req.queue_span
                self._cv.notify_all()
        if treject is not None:
            t_name, t_depth, t_quota, retry = treject
            jitter = _env.env_float("CCSC_FED_RETRY_JITTER") or 0.0
            if jitter > 0:
                retry *= 1.0 + random.random() * jitter
            self._emit(
                "tenant_reject", replica_id=None,
                tenant=t_name, queue_depth=t_depth, quota=t_quota,
                retry_after_s=round(retry, 3),
            )
            raise Overloaded(
                f"tenant {t_name!r} is at its admission quota "
                f"({t_depth}/{t_quota} queued); retry after "
                f"~{retry:.2f}s (other tenants are unaffected)",
                retry_after_s=retry,
            )
        if reject is not None:
            depth, ceiling, rung, retry = reject
            # jitter the retry hint (CCSC_FED_RETRY_JITTER): N
            # federated frontends refused on the same tick would
            # otherwise all resubmit on the same tick too, arriving
            # as the very thundering herd the ceiling just rejected.
            # Applied outside the lock — the hint is advice, not
            # shared state.
            jitter = _env.env_float("CCSC_FED_RETRY_JITTER") or 0.0
            if jitter > 0:
                retry *= 1.0 + random.random() * jitter
            self._emit(
                "fleet_admission_reject", replica_id=None,
                queue_depth=depth, ceiling=ceiling, rung=rung,
                retry_after_s=round(retry, 3),
            )
            raise Overloaded(
                f"queue at its admission ceiling ({depth}/"
                f"{ceiling}, overload ladder at {rung}); retry "
                f"after ~{retry:.2f}s",
                retry_after_s=retry,
            )
        # trace spans for the accepted request (emitted OUTSIDE the
        # lock; a worker may already have taken — even delivered — it,
        # which is fine: spans match by id, not by stream order)
        trace_util.start_span(
            self._emit, trace_id=req.trace_id,
            span=trace_util.ROOT_SPAN, span_id=req.root_span,
            ts=req.t_wall, key=req.key,
            # the stamped absolute deadline travels on the root span:
            # every later deadline_exceeded/cancel/hedge decision is
            # auditable against it from the event stream alone
            deadline=(
                None if req.deadline is None
                else round(req.deadline, 3)
            ),
        )
        trace_util.emit_span(
            self._emit, trace_id=req.trace_id, span="admission",
            parent_span=req.root_span, t_start=req.t_wall,
            t_end=req.queue_t,
        )
        trace_util.start_span(
            self._emit, trace_id=req.trace_id, span="queue",
            span_id=qspan, parent_span=req.root_span,
            ts=req.queue_t, attempt=1,
        )
        if self._capture is not None and not req.key.startswith(
            _quality.PROBE_KEY_PREFIX
        ):
            # durable workload record of the ADMITTED request —
            # outside the fleet lock (sha256 + file append must not
            # serialize submitters against the workers). Golden
            # probes are excluded: synthetic quality traffic must
            # not pollute the replayable workload.
            self._capture.record_submit(
                req.key, req.trace_id, b32, mask=mask32,
                smooth_init=smooth32, x_orig=xorig32,
                bucket=_bucket_name(bslots, bsp),
                bank_id=eff_bank, tenant=tenant,
            )
        return req.future

    def reconstruct(
        self, b, mask=None, smooth_init=None, x_orig=None,
        key: Optional[str] = None,
        bank_id: Optional[str] = None,
        tenant: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> ServedResult:
        """Synchronous submit-and-wait."""
        return self.submit(
            b, mask=mask, smooth_init=smooth_init, x_orig=x_orig,
            key=key, bank_id=bank_id, tenant=tenant,
        ).result(timeout=timeout)

    def serve_many(self, requests, timeout=None) -> List[ServedResult]:
        """Submit an iterable of request dicts (keys b/mask/
        smooth_init/x_orig/key/bank_id/tenant) and wait for all
        results, in order."""
        futs = [self.submit(**req) for req in requests]
        return [f.result(timeout=timeout) for f in futs]

    # -- multi-tenant bank publication (serve.registry) ----------------
    def publish_bank(
        self, bank_id: Optional[str], d,
        tenant: Optional[str] = None,
        quality_check: Optional[bool] = None,
    ) -> Tuple[Optional[str], str]:
        """Fleet-wide zero-downtime hot-swap: make ``d`` servable on
        EVERY replica, then atomically route ``bank_id`` (None = the
        fleet's pinned default bank) to the new digest.

        The rollout is STAGGERED — one replica's plans build at a
        time (``CCSC_BANK_SWAP_STAGGER_S`` spacing), the rung-3
        staggered-recycle discipline applied to publication — so the
        plan-build burst is bounded and serving capacity never dips:
        plan builds are jitted (no XLA recompile; the compiled bucket
        programs are digest-canonical and shared) and traffic keeps
        flowing on the old digest throughout. Requests admitted
        before the flip bound the OLD digest and finish on it; the
        first admission after the flip serves the new one. The
        cutover is one ``bank_swap`` event carrying both digests.

        A replica that dies mid-rollout is fine: its restart
        republishes every retained bank before taking work
        (``_spawn_replica``), and requeued requests re-serve against
        their admission-time digest on any survivor. Returns
        ``(old_digest, new_digest)``.

        ``quality_check`` (None = the ``CCSC_QUALITY_GATE`` flag)
        arms the publish-time quality gate: the candidate digest's
        ``kind=quality`` ledger history (shadow scores from
        ``serve.quality.score_bank``) is judged against the live
        history's quality band and a regression raises
        :class:`~.quality.QualityGateError` BEFORE any replica sees
        the bank — the held-out-parity publish guard online
        dictionary learning rides on."""
        from ..utils import validate

        if self._close_started:
            raise RuntimeError("fleet is closed")
        validate.check_filters(d, self.geom)
        digest = _registry.bank_digest(d)
        if quality_check is None:
            quality_check = _env.env_flag("CCSC_QUALITY_GATE")
        if quality_check:
            _quality.gate_publish(digest, bank_id=bank_id)
        arr = np.asarray(d)
        with self._cv:
            if self._close_started:
                raise RuntimeError("fleet is closed")
            # retained bytes FIRST: any replica restarting from here
            # on republishes the new bank before taking work
            self._bank_arrays[digest] = arr
            old = self._bank_routes.get(bank_id)
            reps = [
                rep for rep in self._replicas
                if rep is not None and not rep.retired
            ]
        stagger = _env.env_float("CCSC_BANK_SWAP_STAGGER_S") or 0.0
        for i, rep in enumerate(reps):
            if i and stagger > 0 and self._closing.wait(stagger):
                raise RuntimeError("fleet closed mid-publish")
            try:
                rep.engine.add_bank(arr)
            except RuntimeError:
                # a replica that closed under us (crash handoff in
                # flight): its replacement republishes from
                # _bank_arrays, so the rollout still completes
                continue
        with self._cv:
            if self._close_started:
                raise RuntimeError("fleet is closed")
            self._bank_routes[bank_id] = digest
            # the demotion advisory's restoration target: what this
            # bank served BEFORE this flip (no-op on a republish of
            # the same digest — a refresh must not make a bank its
            # own rollback)
            if old is not None and old != digest:
                self._bank_prev[bank_id] = old
        self._emit(
            "bank_swap", replica_id=None,
            bank_id=bank_id, old_digest=old, new_digest=digest,
            tenant=tenant, replicas=len(reps),
        )
        self._run.console(
            f"fleet: bank {bank_id if bank_id else '<default>'} "
            f"hot-swapped {old} -> {digest} across {len(reps)} "
            "replica(s)",
            tier="brief",
        )
        self._retire_stale_banks()
        return old, digest

    def _retire_stale_banks(self) -> None:
        """Memory-bounding sweep after a route flip: drop superseded
        digests NOTHING references anymore — not routed by any bank
        id, not bound by any queued or assigned request (those finish
        on their admission-time plan; the next publish retries the
        sweep). A fleet republishing a refreshed bank continuously
        must not accumulate every superseded copy forever."""
        with self._cv:
            routed = set(self._bank_routes.values())
            bound = {r.digest for r in self._queue if r.digest}
            for rep in self._replicas:
                if rep is not None:
                    bound.update(
                        r.digest for r in rep.assigned if r.digest
                    )
            stale = [
                dg for dg in self._bank_arrays
                if dg not in routed and dg not in bound
            ]
            for dg in stale:
                del self._bank_arrays[dg]
            reps = [
                rep for rep in self._replicas
                if rep is not None and not rep.retired
            ]
        for dg in stale:
            for rep in reps:
                # best-effort: an engine still referencing the digest
                # locally refuses and keeps its copy; nothing can
                # bind the digest again, so that copy is the last
                try:
                    rep.engine.retire_bank(dg)
                except Exception:
                    pass

    @property
    def bank_ids(self) -> List[str]:
        """Published bank ids (the pinned default bank routes as
        None and is not listed)."""
        with self._cv:
            return sorted(
                k for k in self._bank_routes if k is not None
            )

    def bank_digest(self, bank_id: Optional[str] = None) -> str:
        """The digest ``bank_id`` currently routes to (None = the
        fleet's pinned default bank)."""
        from ..utils import validate

        with self._cv:
            digest = self._bank_routes.get(bank_id)
        if digest is None:
            raise validate.CCSCInputError(
                f"unknown bank id {bank_id!r}"
            )
        return digest

    def stats(self) -> Dict[str, object]:
        """Fleet aggregates: delivery counts, latency percentiles,
        admission/requeue/duplicate counters, per-replica liveness.
        Percentiles come from the fleet-wide streaming histogram
        (serve.slo) — the same numbers the slo_histogram events and
        the metricsd scrape quote; ``_latencies`` keeps the exact
        newest-window sample for cross-checks and debugging."""
        with self._cv:
            reps = [
                None if r is None else {
                    "replica": r.id,
                    "state": r.state,
                    "generation": r.generation,
                    "served": r.served,
                    "restarts": self._restarts.get(r.id, 0),
                    "devices": r.engine.devices,
                    "mesh": (
                        list(r.engine.mesh_shape)
                        if r.engine.mesh_shape
                        else None
                    ),
                }
                for r in self._replicas
            ]
            depth = len(self._queue)
            n_delivered = self._n_delivered
        return {
            "n_requests": n_delivered,
            "n_rejected": self._n_rejected,
            "n_requeued": self._n_requeued,
            "n_duplicates_suppressed": self._n_duplicates,
            "n_failed": self._n_failed,
            "queue_depth": depth,
            "queue_ceiling": self._ceiling,
            "overload_rung": RUNGS[self._rung],
            "p50_latency_s": _ms_to_s(
                self._slo.percentile("total", 0.50)
            ),
            "p99_latency_s": _ms_to_s(
                self._slo.percentile("total", 0.99)
            ),
            "replicas": reps,
            "tenants": {
                t: {
                    "delivered": self._tenant_delivered.get(t, 0),
                    "rejected": self._tenant_rejects.get(t, 0),
                    "p50_latency_s": _ms_to_s(
                        self._tenant_slos.percentile(t, 0.50)
                    ),
                    "p99_latency_s": _ms_to_s(
                        self._tenant_slos.percentile(t, 0.99)
                    ),
                }
                for t in self._tenants.names()
            },
            "banks": {
                (bid if bid is not None else "<default>"): dg
                for bid, dg in self._bank_routes.items()
            },
        }

    def _ledger_append(self, st: Dict[str, object]) -> None:
        """Append this serving session's normalized record to the
        durable perf ledger (analysis.ledger; no-op unless
        CCSC_PERF_LEDGER is set): achieved fleet requests/sec over
        the session lifetime, keyed by chip + solve-shape bucket +
        the replicas' resolved knob dict. Never raises — the ledger
        must not fail a fleet close."""
        try:
            from ..analysis import ledger as _ledger

            if not _ledger.enabled():
                return
            n = int(st.get("n_requests") or 0)
            elapsed = time.time() - self._t_start
            chip = self._run.chip
            if n <= 0 or elapsed <= 0 or not chip:
                return
            from ..tune import store as tune_store
            from ..utils import obs

            knobs = next(
                (
                    dict(rep.engine._knob_dict)
                    for rep in self._replicas
                    if rep is not None
                    and getattr(rep.engine, "_knob_dict", None)
                ),
                {},
            )
            n_reps = sum(
                1 for rep in self._replicas if rep is not None
            ) or self._replica_target
            knobs["replicas"] = n_reps
            if self.total_devices > n_reps:
                # only a meshed fleet carries the topology key: an
                # all-single-device fleet's knob digest (its ledger
                # history key) stays exactly the pre-mesh one
                knobs["total_devices"] = self.total_devices
            _spatial = max(
                (sp for _s_, sp in self.buckets),
                key=lambda sp: tuple(sp),
            )
            workload = tune_store.solve_workload(self.geom)
            rec = _ledger.maybe_append(
                chip=chip,  # normalize_record canonicalizes
                kind="serve",
                workload=workload,
                shape_key=tune_store.solve_shape_key(
                    workload,
                    k=self.geom.num_filters,
                    support=tuple(self.geom.spatial_support),
                    spatial=tuple(_spatial),
                ),
                knobs=knobs,
                value=n / elapsed,
                unit="requests/sec",
                git_sha=obs.git_sha(),
                n_compiles=(
                    self._run.compile_monitor.summary()["n_compiles"]
                    if self._run.compile_monitor is not None
                    else None
                ),
                source="serve.fleet",
            )
            if rec is not None:
                self._emit(
                    "ledger_append",
                    replica_id=None,
                    key=_ledger.record_key(rec),
                    value=rec["value"],
                    unit=rec["unit"],
                    path=_ledger.default_ledger_path(),
                )
        except Exception:  # pragma: no cover - defensive
            pass

    def close(self, drain_timeout_s: float = 600.0):
        """Serve every queued request, retire the replicas, and close
        the telemetry run with the fleet summary. Re-entrant and
        race-safe (same contract as ``CodecEngine.close``). Requests
        still undelivered after ``drain_timeout_s`` get an explicit
        error."""
        with self._close_lock:
            owner = not self._close_started
            self._close_started = True
        if not owner:
            self._close_done.wait()
            return
        self._closing.set()
        try:
            with self._cv:
                self._cv.notify_all()
            deadline = time.monotonic() + drain_timeout_s
            while time.monotonic() < deadline:
                with self._cv:
                    busy = bool(self._queue) or any(
                        rep is not None and rep.assigned
                        and not rep.retired
                        for rep in self._replicas
                    )
                    any_live = any(
                        rep is not None and not rep.retired
                        for rep in self._replicas
                    )
                if not busy or not any_live:
                    break
                time.sleep(0.02)
            self._stop_monitor.set()
            self._monitor.join(timeout=5.0)
            # the probe thread shares _stop_monitor but a sweep in
            # flight holds result futures — give it the same drain
            # grace as a worker before engines close under it
            if self._probe_thread is not None:
                self._probe_thread.join(timeout=60.0)
            # the recycle walker polls _close_started at 50ms — join
            # it so it cannot be alive at interpreter exit
            if self._recycle_thread is not None:
                self._recycle_thread.join(timeout=10.0)
            # a restart thread caught mid-engine-build must finish and
            # release its engine (the `closing` branch in _restart)
            # before the interpreter can safely exit
            with self._cv:
                pending_restarts = list(self._restart_threads)
            for t in pending_restarts:
                t.join(timeout=120.0)
            # workers exit once the queue is dry; join briefly, then
            # close engines (re-entrant — a straggler's own close on
            # exit is a no-op)
            for rep in self._replicas:
                if rep is None:
                    continue
                if rep.thread is not None:
                    rep.thread.join(timeout=60.0)
                try:
                    rep.watchdog.stop()
                except Exception:
                    pass
                try:
                    rep.engine.close()
                except Exception:
                    pass
                if rep.state == "live":
                    rep.state = "stopped"
            # final per-replica heartbeat: a short run may never reach
            # a monitor tick, and the FLEET report's liveness column
            # reads heartbeats — every replica gets a terminal one.
            # Snapshot under the lock, emit OUTSIDE it: the stream
            # write can block on file I/O and must not hold the queue
            # mutex (lint: thread-safety)
            with self._cv:
                depth = len(self._queue)
                final_rows = [
                    dict(
                        replica_id=rep.id, state=rep.state,
                        generation=rep.generation, served=rep.served,
                        inflight=len(rep.assigned), queue_depth=depth,
                        restarts=self._restarts.get(rep.id, 0),
                        devices=rep.engine.devices,
                        final=True,
                    )
                    for rep in self._replicas
                    if rep is not None
                ]
            for row in final_rows:
                self._emit("fleet_heartbeat", **row)
            undelivered: List[_FleetRequest] = []
            shutdown_spans: List = []  # (req, queue_span, attempt_span, root_owed)
            with self._cv:
                undelivered.extend(
                    # a queued hedge clone whose primary already
                    # delivered is not a casualty — its story closed
                    r for r in self._queue
                    if r.key not in self._delivered
                )
                self._queue.clear()
                for rep in self._replicas:
                    if rep is None:
                        continue
                    undelivered.extend(
                        r for r in rep.assigned
                        if r.key not in self._delivered
                    )
                    rep.assigned = []
                for r in undelivered:
                    self._index.pop(r.key, None)
                    if r.trace_id is not None:
                        qs, r.queue_span = r.queue_span, None
                        att, r.attempt_span = r.attempt_span, None
                        pr = r.primary or r
                        owed = not pr.root_done
                        pr.root_done = True
                        r.root_done = True
                        if qs or att or owed:
                            shutdown_spans.append((r, qs, att, owed))
                # hedge clones share their primary's key: one request,
                # one failure — don't count the pair twice
                self._n_failed += sum(
                    1 for r in undelivered if not r.hedge_of
                )
            # a shut-down fleet still closes every story: whatever
            # span the request had open ends 'shutdown', so the trace
            # reassembles gap-free even for requests the close failed
            wall = time.time()
            for r, qs, att, root_owed in shutdown_spans:
                if qs:
                    trace_util.end_span(
                        self._emit, trace_id=r.trace_id, span="queue",
                        span_id=qs, parent_span=r.root_span,
                        status="shutdown", ts=wall,
                    )
                if att:
                    trace_util.end_span(
                        self._emit, trace_id=r.trace_id,
                        span="attempt", span_id=att,
                        parent_span=r.root_span, status="shutdown",
                        ts=wall, t_start=r.attempt_t,
                    )
                if root_owed:
                    trace_util.end_span(
                        self._emit, trace_id=r.trace_id,
                        span=trace_util.ROOT_SPAN,
                        span_id=r.root_span, status="shutdown",
                        ts=wall, t_start=r.t_wall,
                    )
            for r in undelivered:
                try:
                    r.future.set_exception(
                        RuntimeError(
                            "fleet closed before this request could "
                            "be served"
                        )
                    )
                except InvalidStateError:
                    pass
            if self._metricsd is not None:
                # final snapshot rides stop(); the endpoint dies with
                # the fleet it describes
                try:
                    self._metricsd.stop()
                except Exception:
                    pass
            if self._capture is not None:
                # seal the capture with the fleet's final admission
                # counters: replay diffs its own admission behavior
                # against these (the recorded-vs-replayed story)
                with self._cv:
                    cap_final = dict(
                        n_delivered=self._n_delivered,
                        n_rejected=self._n_rejected,
                        n_requeued=self._n_requeued,
                        n_failed=self._n_failed,
                    )
                try:
                    self._capture.close(**cap_final)
                except Exception:
                    pass
            if not self._run.closed:
                # closing histogram flush: the stream always ends
                # with one complete fleet-wide slo_histogram per
                # phase (offline percentile recomputation — the
                # acceptance contract of the SLO layer), plus one
                # per declared tenant (the TENANTS report's source)
                _breaches, snaps = self._slo.final()
                for sn in snaps:
                    self._emit("slo_histogram", replica_id=None, **sn)
                _t_breaches, t_snaps = self._tenant_slos.final()
                for sn in t_snaps:
                    self._emit("slo_histogram", replica_id=None, **sn)
                # ... and the quality plane's closing flush: one
                # complete quality_histogram per (bank, tenant,
                # bucket) plus the accumulated solve diagnostics
                _qb, q_snaps, q_diags = self._quality.final()
                for sn in q_snaps:
                    self._emit(
                        "quality_histogram", replica_id=None, **sn
                    )
                for dg in q_diags:
                    self._emit(
                        "quality_solve_diag", replica_id=None, **dg
                    )
            if not self._run.closed:
                st = self.stats()
                self._ledger_append(st)
                self._run.close(
                    status="ok",
                    n_requests=st["n_requests"],
                    n_rejected=st["n_rejected"],
                    n_requeued=st["n_requeued"],
                    n_duplicates_suppressed=st[
                        "n_duplicates_suppressed"
                    ],
                    n_failed=st["n_failed"],
                    p50_latency_s=st["p50_latency_s"],
                    p99_latency_s=st["p99_latency_s"],
                )
        finally:
            self._close_done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
