"""Durable shared-filesystem work queue with lease-based ownership.

``ServeFleet`` survives replica death because its front queue outlives
any one replica — but that queue lives in ONE process, so a host kill
still loses the entire serving surface. This module is the queue one
level up: a directory on a shared filesystem is the only thing hosts
have in common (the parallel multi-block independence of the solves,
PAPERS.md arXiv:1312.3040 — work items share no state), and every
operation is a single atomic filesystem primitive, so whole-host death
is just an expired lease:

- **submit** writes payload arrays content-addressed into the capture
  payload store layout (``payloads/<sha>.npy``, sha256 over
  dtype/shape/bytes — :func:`~.capture.payload_sha`) and then the item
  record via tmp + ``os.replace`` into ``queue/``: a reader can never
  observe a torn request file, only absent-then-present.
- **claim** is one ``os.rename`` of the item file into the claiming
  host's ``leases/<host>/`` dir. POSIX rename has exactly one winner —
  concurrent claimers of the same item race on the rename and every
  loser gets ENOENT, no lock file, no coordinator. The winner then
  rewrites the record (atomically, inside its own lease dir) with the
  ownership stamp: host, the host's join **epoch**, claim time, and
  the incremented cross-host ``attempts`` count.
- **heartbeat** atomically rewrites ``hosts/<host>.json`` with the
  host's epoch and wall clock. A lease's TTL is judged against its
  owner's newest heartbeat — a live host mid-long-solve keeps its
  leases by heartbeating, without touching every lease file.
- **reap** (any host may run it) requeues items whose lease expired:
  the owner's heartbeat is older than ``ttl_s`` plus a clock-skew
  allowance (``skew_s`` — hosts share a filesystem, not a clock), or
  the owner rejoined under a newer epoch (its previous incarnation is
  dead no matter what the clock says), or the owner announced
  ``left``. Requeue is the same single rename back into ``queue/``
  under the item's ORIGINAL sequence name, so a handed-off item drains
  at the front — it already waited its turn. An item whose
  ``attempts`` already reached the budget is failed instead: an
  explicit error result, never a silent retry-forever
  (exactly-once-or-error, the PR 7 contract made cross-host).
- **complete / fail** write the result durably (reconstruction bytes
  content-addressed, digest + PSNR + latency in an atomically-written
  ``results/<key>.json``) and then mark the key **spent** with an
  ``O_EXCL`` marker create — the one decision point of the delivery
  race. A late straggler (a host that stalled mid-solve, lost its
  lease to the reaper, and woke after a survivor served the item) is
  fenced twice: its lease file is gone / its epoch is stale (checked
  before any result write), and the spent marker already exists (the
  atomic tiebreak if it raced the reaper). Spent keys STAY spent:
  ``submit`` of a spent key is refused, and claimers drop requeued
  copies of spent keys on the floor.

Durability stance = ``analysis/ledger.py``: every multi-byte write is
tmp + atomic replace, every read of a JSON record tolerates torn or
truncated bytes by treating the file as absent, and a reader of the
queue dirs never throws on concurrent renames happening under it.

This module is deliberately jax-free: frontends and reapers import it
without initializing a backend. :mod:`serve.federation` builds the
serving layer on top — each host drains this queue into its in-process
:class:`~.fleet.ServeFleet`.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import env as _env
from ..utils import trace as trace_util
from .capture import load_payload, payload_sha

__all__ = ["DurableQueue", "safe_key"]

_SCHEMA = 1
_QUEUE = "queue"
_LEASES = "leases"
_RESULTS = "results"
_SPENT = "spent"
_HOSTS = "hosts"
_PAYLOADS = "payloads"
_CORRUPT = "corrupt"
_SEALED = "SEALED"


def safe_key(key: str) -> str:
    """Filesystem name of one idempotency key: keys are
    client-provided strings and must not be trusted as path
    components, so result/spent files are named by digest."""
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:24]


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    """One JSON record, or None when absent / torn / truncated /
    not-a-dict — a file a crashed writer (or a racing rename) left
    unreadable is treated as absent, never as an error."""
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


def _write_json(path: str, rec: Dict[str, Any]) -> None:
    """Atomic record write (tmp + replace, same dir so the rename
    never crosses filesystems): readers see the old bytes or the new
    bytes, never a tear."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tmp-", dir=d)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(rec, f, default=str)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _publish_json(path: str, rec: Dict[str, Any]) -> bool:
    """Atomic FIRST-WINS record write: full bytes land under a tmp
    name, then ``os.link`` publishes them — which fails if the path
    already exists, so a racing loser can never overwrite the
    winner's record with a contradictory one (the result-file
    contract: whoever durably records an outcome first defines the
    client-visible one). Falls back to plain atomic replace on
    filesystems without hard links."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tmp-", dir=d)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(rec, f, default=str)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        except OSError:
            os.replace(tmp, path)
            tmp = None
            return True
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


class DurableQueue:
    """One handle on the shared queue directory, scoped to one host
    identity (``host``; frontends pass their client id — they submit
    and read results but never claim).

    Not thread-safe per handle by design EXCEPT for the read side:
    the federation layer drives claim/complete from one drain thread
    and heartbeat/reap from one beat thread, each through its own
    method set, and every mutation is a single atomic filesystem op —
    cross-PROCESS safety is the point, and it comes from rename/
    O_EXCL semantics, not Python locks.

    ``emit`` is an optional obs-event callable (``run.event``-shaped)
    announcing queue traffic (``dqueue_*`` events, declared in
    ``analysis/obs_schema.py``).
    """

    def __init__(
        self,
        path: str,
        host: str,
        emit=None,
        ttl_s: Optional[float] = None,
        skew_s: Optional[float] = None,
        max_attempts: Optional[int] = None,
    ):
        self.path = path
        self.host = host
        self.epoch = 0  # assigned by join()
        self.ttl_s = (
            float(ttl_s)
            if ttl_s is not None
            else float(_env.env_float("CCSC_DQUEUE_TTL_S"))
        )
        self.skew_s = (
            float(skew_s)
            if skew_s is not None
            else float(_env.env_float("CCSC_DQUEUE_SKEW_S"))
        )
        self.max_attempts = (
            int(max_attempts)
            if max_attempts is not None
            else int(_env.env_int("CCSC_DQUEUE_ATTEMPTS"))
        )
        self._emit = emit or (lambda type_, **fields: None)
        self._seq = 0
        self.n_claimed = 0
        self.n_completed = 0
        self.n_suppressed = 0
        for sub in (
            _QUEUE, _RESULTS, _SPENT, _HOSTS, _PAYLOADS, _CORRUPT,
        ):
            os.makedirs(os.path.join(path, sub), exist_ok=True)
        os.makedirs(self._lease_dir(host), exist_ok=True)

    # -- paths ---------------------------------------------------------
    def _lease_dir(self, host: str) -> str:
        return os.path.join(self.path, _LEASES, host)

    def _host_path(self, host: str) -> str:
        return os.path.join(self.path, _HOSTS, host + ".json")

    def _result_path(self, key: str) -> str:
        return os.path.join(self.path, _RESULTS, safe_key(key) + ".json")

    def _spent_path(self, key: str) -> str:
        return os.path.join(self.path, _SPENT, safe_key(key) + ".json")

    # -- membership ----------------------------------------------------
    def join(self) -> int:
        """Register this host in the pool under a fresh epoch (one
        more than any epoch this host id ever announced — a restarted
        host fences its own previous incarnation's leases) and write
        the first heartbeat."""
        old = _read_json(self._host_path(self.host))
        self.epoch = int((old or {}).get("epoch", 0)) + 1
        os.makedirs(self._lease_dir(self.host), exist_ok=True)
        self.heartbeat()
        return self.epoch

    def heartbeat(self, **gauges) -> None:
        """Atomically renew this host's liveness record. The stamped
        wall clock is the reference every expiry judgment uses for
        this host's leases."""
        rec = dict(
            host=self.host,
            epoch=self.epoch,
            t=time.time(),
            pid=os.getpid(),
            status="live",
        )
        rec.update(gauges)
        _write_json(self._host_path(self.host), rec)

    def leave(self) -> int:
        """Orderly exit: requeue every lease this host still holds
        (they were claimed, not served — survivors must get them
        without waiting out the TTL) and mark the host record
        ``left``. Returns the number of items released."""
        released = 0
        for rec, lease_path in self._own_leases():
            if self._requeue(rec, lease_path, reason="leave"):
                released += 1
        rec = dict(
            host=self.host,
            epoch=self.epoch,
            t=time.time(),
            pid=os.getpid(),
            status="left",
        )
        _write_json(self._host_path(self.host), rec)
        return released

    # -- submit --------------------------------------------------------
    def _store_array(self, arr: Optional[np.ndarray]) -> Optional[str]:
        if arr is None:
            return None
        arr = np.ascontiguousarray(np.asarray(arr, np.float32))
        sha = payload_sha(arr)
        fpath = os.path.join(self.path, _PAYLOADS, sha + ".npy")
        if os.path.exists(fpath):
            return sha  # content-addressed: identical bytes stored once
        fd, tmp = tempfile.mkstemp(
            prefix=".tmp-", dir=os.path.join(self.path, _PAYLOADS)
        )
        try:
            with os.fdopen(fd, "wb") as f:
                np.save(f, arr)
            os.replace(tmp, fpath)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return sha

    def load_array(self, sha: Optional[str]) -> Optional[np.ndarray]:
        if sha is None:
            return None
        return load_payload(self.path, sha)

    def submit(
        self,
        key: str,
        b: np.ndarray,
        mask: Optional[np.ndarray] = None,
        smooth_init: Optional[np.ndarray] = None,
        x_orig: Optional[np.ndarray] = None,
        trace_id: Optional[str] = None,
        root_span: Optional[str] = None,
        deadline: Optional[float] = None,
    ) -> str:
        """Durably enqueue one request; returns the item file name.
        A spent key (already served or already failed) is refused —
        the key resolved once, ever, anywhere in the pool.

        ``deadline`` is the request's ABSOLUTE wall-clock expiry
        (``time.time()`` seconds, stamped by the client): the item
        record carries the remaining budget across host boundaries,
        so a hand-off SHRINKS what is left instead of resetting it,
        and a claim of an already-expired item resolves it with a
        durable ``deadline`` error result instead of solving it."""
        if os.path.exists(self._spent_path(key)):
            raise ValueError(
                f"idempotency key {key!r} is spent (already served or "
                "failed somewhere in the pool; retry under a fresh "
                "key)"
            )
        self._seq += 1
        name = (
            f"{int(time.time() * 1e3):015d}-{self._seq:06d}-"
            f"{safe_key(key)}.json"
        )
        rec = {
            "schema": _SCHEMA,
            "name": name,
            "key": key,
            "client": self.host,
            "t_submit": time.time(),
            "attempts": 0,
            "max_attempts": self.max_attempts,
            "trace_id": trace_id,
            "root_span": root_span,
            "deadline": (
                None if deadline is None else float(deadline)
            ),
            "b": self._store_array(b),
            "mask": self._store_array(mask),
            "smooth_init": self._store_array(smooth_init),
            "x_orig": self._store_array(x_orig),
        }
        _write_json(os.path.join(self.path, _QUEUE, name), rec)
        self._emit("dqueue_submit", key=key, name=name)
        return name

    # -- seal (end of stream) ------------------------------------------
    def seal(self) -> None:
        """Announce end-of-stream: hosts draining until sealed exit
        once the queue and every lease are empty."""
        _write_json(
            os.path.join(self.path, _SEALED),
            {"t": time.time(), "by": self.host},
        )

    @property
    def sealed(self) -> bool:
        return os.path.exists(os.path.join(self.path, _SEALED))

    # -- claim ---------------------------------------------------------
    def claim(self, limit: int = 1) -> List[Dict[str, Any]]:
        """Claim up to ``limit`` items, oldest first. Exactly-one-
        winner: the rename into this host's lease dir either succeeds
        (the item is ours) or fails with ENOENT (someone else won).
        Requeued copies of spent keys are dropped here instead of
        solved again; a torn item file is quarantined."""
        try:
            names = sorted(os.listdir(os.path.join(self.path, _QUEUE)))
        except OSError:
            return []
        out: List[Dict[str, Any]] = []
        for name in names:
            if len(out) >= limit:
                break
            if not name.endswith(".json"):
                continue
            src = os.path.join(self.path, _QUEUE, name)
            dst = os.path.join(self._lease_dir(self.host), name)
            try:
                os.rename(src, dst)
            except OSError:
                continue  # lost the race (or the file just left)
            rec = _read_json(dst)
            if rec is None:
                # torn item file: unreadable-as-absent for every
                # reader; since we hold it now, quarantine the bytes
                # for forensics instead of requeueing garbage
                self._quarantine(dst)
                continue
            key = rec.get("key")
            if not key:
                self._quarantine(dst)
                continue
            if os.path.exists(self._spent_path(key)):
                # a requeued copy of a key a straggler already
                # resolved — solving it again could only be
                # suppressed at delivery; drop it for free here
                try:
                    os.unlink(dst)
                except OSError:
                    pass
                continue
            dl = rec.get("deadline")
            if dl is not None and time.time() >= float(dl):
                # the client's end-to-end budget expired while the
                # item sat queued/handed-off: resolve it durably as
                # a deadline error INSTEAD of solving — no solve
                # slot is ever spent on a request nobody waits for
                self._resolve_expired(rec, dst)
                continue
            rec["attempts"] = int(rec.get("attempts", 0)) + 1
            rec["lease_host"] = self.host
            rec["lease_epoch"] = self.epoch
            rec["lease_t"] = time.time()
            _write_json(dst, rec)
            self.n_claimed += 1
            out.append(rec)
            self._emit(
                "dqueue_claim",
                key=key,
                host=self.host,
                epoch=self.epoch,
                attempt=rec["attempts"],
            )
        return out

    def _quarantine(self, path: str) -> None:
        try:
            os.replace(
                path,
                os.path.join(
                    self.path, _CORRUPT, os.path.basename(path)
                ),
            )
        except OSError:
            pass

    def _resolve_expired(
        self, rec: Dict[str, Any], lease_path: str
    ) -> None:
        """Durably resolve a claimed-but-expired item: ``deadline``
        error result + spent marker + lease unlink. The result is
        first-wins like every other resolution — a racing owner that
        somehow completed it keeps its record."""
        key = rec["key"]
        dl = float(rec["deadline"])
        err = {
            "schema": _SCHEMA,
            "key": key,
            "status": "deadline",
            "error": (
                f"request {key!r} exceeded its deadline "
                f"({dl:.3f}) before any host could serve it"
            ),
            "deadline": dl,
            "host": self.host,
            "epoch": self.epoch,
            "attempts": int(rec.get("attempts", 0)),
            "t": time.time(),
        }
        _publish_json(self._result_path(key), err)
        if self._mark_spent(key, "deadline"):
            self._emit(
                "deadline_exceeded", where="claim",
                deadline=round(dl, 3), key=key, host=self.host,
            )
            if rec.get("trace_id"):
                # the expiry is this request's terminal ownership
                # story: written start+end together so the trace
                # reassembles complete without a live owner
                trace_util.emit_span(
                    self._emit,
                    trace_id=rec["trace_id"],
                    span="attempt",
                    parent_span=rec.get("root_span"),
                    t_start=time.time(),
                    t_end=time.time(),
                    status="deadline",
                    host=self.host,
                )
        try:
            os.unlink(lease_path)
        except OSError:
            pass

    # -- delivery ------------------------------------------------------
    def _mark_spent(self, key: str, status: str) -> bool:
        """Atomically create the spent marker; False when the key was
        already spent (the one tiebreak of the delivery race)."""
        try:
            fd = os.open(
                self._spent_path(key),
                os.O_WRONLY | os.O_CREAT | os.O_EXCL,
            )
        except FileExistsError:
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "key": key,
                    "status": status,
                    "host": self.host,
                    "epoch": self.epoch,
                    "t": time.time(),
                },
                f,
            )
        return True

    def _fenced(self, item: Dict[str, Any]) -> Optional[str]:
        """Why this host may no longer deliver ``item`` (None = still
        the owner): the lease was reaped/requeued out from under us,
        our epoch went stale (this host id rejoined), or the key is
        already spent."""
        if os.path.exists(self._spent_path(item["key"])):
            # the key resolved elsewhere — any lease copy we still
            # hold is dead weight (e.g. a ghost recreated by our own
            # claim stamp racing a reaper); drop it so `drained` can
            # become true
            try:
                os.unlink(
                    os.path.join(
                        self._lease_dir(self.host), item["name"]
                    )
                )
            except OSError:
                pass
            return "spent"
        if int(item.get("lease_epoch", -1)) != self.epoch:
            return "epoch"
        lease_path = os.path.join(
            self._lease_dir(self.host), item["name"]
        )
        if not os.path.exists(lease_path):
            return "lease_lost"
        return None

    def complete(
        self,
        item: Dict[str, Any],
        recon: np.ndarray,
        psnr: Optional[float] = None,
        latency_ms: Optional[float] = None,
        bucket: Optional[str] = None,
        iters: Optional[int] = None,
    ) -> bool:
        """Deliver one result durably: reconstruction bytes content-
        addressed, digest + metadata in an atomic result record, then
        the spent marker. Returns False when this delivery was FENCED
        — a late straggler whose ownership was reaped away (the
        survivors' result stands; by the determinism contract the
        bytes would have been identical anyway)."""
        key = item["key"]
        why = self._fenced(item)
        if why is not None:
            self.n_suppressed += 1
            self._emit(
                "dqueue_suppressed", key=key, host=self.host,
                reason=why,
            )
            return False
        # cast ONCE, then store and digest the same object: the
        # digest must describe exactly the bytes the frontend will
        # load back (a float64 recon digested uncast would name
        # bytes the store never held), and payload_sha of the stored
        # array IS its content address — one hash, not two
        recon = np.ascontiguousarray(np.asarray(recon, np.float32))
        sha = self._store_array(recon)
        rec = {
            "schema": _SCHEMA,
            "key": key,
            "status": "ok",
            "recon": sha,
            "digest": sha,
            "psnr": None if psnr is None else float(psnr),
            "latency_ms": (
                None if latency_ms is None else float(latency_ms)
            ),
            "bucket": bucket,
            "iters": None if iters is None else int(iters),
            "host": self.host,
            "epoch": self.epoch,
            "attempts": int(item.get("attempts", 0)),
            "t": time.time(),
        }
        # first-wins: a racing resolver that already published an
        # outcome for this key keeps it — we never overwrite a
        # durable result with a contradictory one
        _publish_json(self._result_path(key), rec)
        if not self._mark_spent(key, "ok"):
            # a racing reap handed the item off and the new owner won
            # the marker — at-most-once delivery holds
            self.n_suppressed += 1
            self._emit(
                "dqueue_suppressed", key=key, host=self.host,
                reason="spent_race",
            )
            return False
        self.n_completed += 1
        try:
            os.unlink(
                os.path.join(self._lease_dir(self.host), item["name"])
            )
        except OSError:
            pass
        self._emit(
            "dqueue_complete", key=key, host=self.host,
            digest=rec["digest"], latency_ms=rec["latency_ms"],
            attempts=rec["attempts"],
        )
        return True

    def fail(self, item: Dict[str, Any], error: str) -> bool:
        """Resolve one item with an explicit error (exactly-once-OR-
        error): durable error result + spent marker. Same fencing as
        :meth:`complete`."""
        key = item["key"]
        why = self._fenced(item)
        if why is not None:
            self.n_suppressed += 1
            self._emit(
                "dqueue_suppressed", key=key, host=self.host,
                reason=why,
            )
            return False
        rec = {
            "schema": _SCHEMA,
            "key": key,
            "status": "error",
            "error": str(error)[:500],
            "host": self.host,
            "epoch": self.epoch,
            "attempts": int(item.get("attempts", 0)),
            "t": time.time(),
        }
        _publish_json(self._result_path(key), rec)
        if not self._mark_spent(key, "error"):
            self.n_suppressed += 1
            self._emit(
                "dqueue_suppressed", key=key, host=self.host,
                reason="spent_race",
            )
            return False
        try:
            os.unlink(
                os.path.join(self._lease_dir(self.host), item["name"])
            )
        except OSError:
            pass
        self._emit(
            "dqueue_failed", key=key, attempts=rec["attempts"],
            error=rec["error"],
        )
        if item.get("trace_id") and item.get("lease_t"):
            # a FAILED ownership is still an ownership: the trace
            # contract (every ownership visible) holds for error
            # resolutions too
            trace_util.emit_span(
                self._emit,
                trace_id=item["trace_id"],
                span="attempt",
                parent_span=item.get("root_span"),
                t_start=float(item["lease_t"]),
                t_end=time.time(),
                status="error",
                host=self.host,
                attempt=int(item.get("attempts", 0)),
            )
        return True

    def expire(self, item: Dict[str, Any]) -> None:
        """Resolve one of OUR claimed items as deadline-expired —
        the drain worker's path when the budget runs out after the
        claim (e.g. while the item sat deferred behind an Overloaded
        fleet, or when fleet admission refuses it as already dead)."""
        self._resolve_expired(
            item,
            os.path.join(self._lease_dir(self.host), item["name"]),
        )

    def cancel(self, key: str) -> bool:
        """Durable cooperative cancellation of a still-unresolved
        key: cancelled result record + spent marker. After this, a
        later claim of the (queued or requeued) item drops it at the
        spent-key fence instead of solving it — the cross-host twin
        of the fleet's pre-dispatch cancel sweep. False when the key
        already resolved (the result stands; cancellation lost the
        race, which is the at-most-once contract, not an error)."""
        rec = {
            "schema": _SCHEMA,
            "key": key,
            "status": "cancelled",
            "host": self.host,
            "epoch": self.epoch,
            "t": time.time(),
        }
        # first-wins on the RESULT record, same as complete/fail: if
        # a host already published an outcome, the cancel lost and
        # that outcome stands (its own _mark_spent follows)
        if not _publish_json(self._result_path(key), rec):
            return False
        self._mark_spent(key, "cancelled")
        self._emit(
            "request_cancelled", where="dqueue", key=key,
            host=self.host,
        )
        return True

    def release(self, item: Dict[str, Any]) -> bool:
        """Hand one of our own claimed-but-unserved items back to the
        queue (the clean half of :meth:`leave`)."""
        lease_path = os.path.join(
            self._lease_dir(self.host), item["name"]
        )
        rec = _read_json(lease_path)
        if rec is None:
            return False
        return self._requeue(rec, lease_path, reason="release")

    # -- the reaper ----------------------------------------------------
    def _own_leases(self):
        out = []
        d = self._lease_dir(self.host)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(d, name))
            if rec is not None:
                out.append((rec, os.path.join(d, name)))
        return out

    def _requeue(
        self, rec: Dict[str, Any], lease_path: str, reason: str
    ) -> bool:
        """One atomic rename back into ``queue/`` under the item's
        original (sequence-ordered) name — a hand-off drains at the
        front. An exhausted attempt budget fails the item here
        instead (requeueing it would be silent retry-forever)."""
        key = rec.get("key")
        if not key:
            self._quarantine(lease_path)
            return False
        if os.path.exists(self._spent_path(key)):
            try:
                os.unlink(lease_path)
            except OSError:
                pass
            return False
        budget = int(rec.get("max_attempts", self.max_attempts))
        from_host = rec.get("lease_host")
        attempts = int(rec.get("attempts", 0))
        if attempts >= budget:
            # the cross-host attempt budget is spent: durable error
            # result + spent marker, emitted by WHOEVER reaps it
            err = {
                "schema": _SCHEMA,
                "key": key,
                "status": "error",
                "error": (
                    f"request {key!r} failed after {attempts} "
                    "cross-host ownership(s) (exactly-once-or-error: "
                    "no result was delivered)"
                ),
                "host": self.host,
                "epoch": self.epoch,
                "attempts": attempts,
                "t": time.time(),
            }
            _publish_json(self._result_path(key), err)
            if self._mark_spent(key, "error"):
                self._emit(
                    "dqueue_failed", key=key, attempts=attempts,
                    error=err["error"],
                )
                if rec.get("trace_id") and rec.get("lease_t"):
                    # close the dead owner's final ownership story
                    # too: a budget-exhausted request still
                    # reassembles with every ownership visible
                    trace_util.emit_span(
                        self._emit,
                        trace_id=rec["trace_id"],
                        span="attempt",
                        parent_span=rec.get("root_span"),
                        t_start=float(rec["lease_t"]),
                        t_end=time.time(),
                        status="error",
                        host=from_host,
                        attempt=attempts,
                    )
            try:
                os.unlink(lease_path)
            except OSError:
                pass
            return False
        try:
            os.rename(
                lease_path,
                os.path.join(self.path, _QUEUE, rec["name"]),
            )
        except OSError:
            return False  # a racing reaper won, or the owner woke
        self._emit(
            "dqueue_requeue",
            key=key,
            from_host=from_host,
            by_host=self.host,
            attempt=attempts,
            reason=reason,
        )
        if rec.get("trace_id") and rec.get("lease_t"):
            # the dead owner can no longer close its ownership story:
            # the reaper writes it retrospectively (start + end
            # together — a killed host never orphans a span), so the
            # request's trace still reassembles complete across the
            # host boundary
            trace_util.emit_span(
                self._emit,
                trace_id=rec["trace_id"],
                span="attempt",
                parent_span=rec.get("root_span"),
                t_start=float(rec["lease_t"]),
                t_end=time.time(),
                status="requeued",
                host=from_host,
                reason=reason,
            )
        return True

    def _host_table(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        d = os.path.join(self.path, _HOSTS)
        try:
            names = os.listdir(d)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            rec = _read_json(os.path.join(d, name))
            if rec is not None and rec.get("host"):
                out[rec["host"]] = rec
        return out

    def _expired(
        self,
        rec: Dict[str, Any],
        hosts: Dict[str, Dict[str, Any]],
        now: float,
    ) -> Optional[str]:
        """Why this lease is dead (None = still live). Expiry is
        clock-skew-bounded: the owner's stamped clock and ours may
        disagree by up to ``skew_s`` without consequence — only a
        heartbeat older than ``ttl_s + skew_s`` is death, so a fast
        local clock can never reap a healthy host's lease."""
        owner = rec.get("lease_host")
        hb = hosts.get(owner) if owner else None
        lease_epoch = int(rec.get("lease_epoch", 0))
        if hb is not None:
            if int(hb.get("epoch", 0)) > lease_epoch:
                return "epoch"  # owner rejoined: old incarnation dead
            if (
                hb.get("status") == "left"
                and int(hb.get("epoch", 0)) == lease_epoch
            ):
                return "left"  # owner left without releasing
        t_ref = float(
            (hb or {}).get("t") or rec.get("lease_t") or 0.0
        )
        if now - t_ref > self.ttl_s + self.skew_s:
            return "expired"
        return None

    def reap(self) -> List[Dict[str, Any]]:
        """Requeue (or fail, at attempt-budget exhaustion) every item
        whose owning host died mid-solve. Any host may reap; racing
        reapers are safe (the requeue rename has one winner). Returns
        the records acted on."""
        hosts = self._host_table()
        now = time.time()
        acted: List[Dict[str, Any]] = []
        lease_root = os.path.join(self.path, _LEASES)
        try:
            host_dirs = sorted(os.listdir(lease_root))
        except OSError:
            return acted
        for hdir in host_dirs:
            d = os.path.join(lease_root, hdir)
            if not os.path.isdir(d):
                continue
            try:
                names = sorted(os.listdir(d))
            except OSError:
                continue
            for name in names:
                if not name.endswith(".json"):
                    continue
                fp = os.path.join(d, name)
                rec = _read_json(fp)
                if rec is None:
                    # torn lease: readers treat it as absent; after a
                    # full TTL with no owner able to repair it,
                    # quarantine the bytes
                    try:
                        age = now - os.stat(fp).st_mtime
                    except OSError:
                        continue
                    if age > self.ttl_s + self.skew_s:
                        self._quarantine(fp)
                    continue
                if "lease_host" not in rec:
                    # the claim-rename landed but the ownership stamp
                    # has not yet: the claimer is mid-claim RIGHT NOW
                    # (or died there). Judging this record by its
                    # absent lease fields would read as
                    # expired-since-epoch and steal a healthy host's
                    # fresh claim — judge by file age instead, with
                    # the full TTL grace
                    try:
                        age = now - os.stat(fp).st_mtime
                    except OSError:
                        continue
                    if age <= self.ttl_s + self.skew_s:
                        continue
                    if self._requeue(rec, fp, reason="unstamped"):
                        acted.append(rec)
                    continue
                why = self._expired(rec, hosts, now)
                if why is None:
                    continue
                if self._requeue(rec, fp, reason=why):
                    acted.append(rec)
        return acted

    # -- read side -----------------------------------------------------
    def result(self, key: str) -> Optional[Dict[str, Any]]:
        """The durable result record for ``key`` (None until a host
        delivers or fails it)."""
        return _read_json(self._result_path(key))

    def spent(self, key: str) -> bool:
        return os.path.exists(self._spent_path(key))

    def result_names(self) -> set:
        """Filenames present under ``results/`` — ONE directory scan
        a poller with N pending keys checks membership against
        (``safe_key(key) + ".json"``), instead of N open() round
        trips per tick against a possibly-remote filesystem."""
        try:
            return set(os.listdir(os.path.join(self.path, _RESULTS)))
        except OSError:
            return set()

    def _count(self, sub: str) -> int:
        try:
            return sum(
                1
                for n in os.listdir(os.path.join(self.path, sub))
                if n.endswith(".json")
            )
        except OSError:
            return 0

    def stats(self) -> Dict[str, Any]:
        """Live queue-wide gauges read straight off the directory
        tree (any host or frontend may call this)."""
        leased = 0
        lease_root = os.path.join(self.path, _LEASES)
        try:
            for hdir in os.listdir(lease_root):
                d = os.path.join(lease_root, hdir)
                if os.path.isdir(d):
                    try:
                        leased += sum(
                            1 for n in os.listdir(d)
                            if n.endswith(".json")
                        )
                    except OSError:
                        pass
        except OSError:
            pass
        return {
            "queued": self._count(_QUEUE),
            "leased": leased,
            "results": self._count(_RESULTS),
            "spent": self._count(_SPENT),
            "hosts": self._host_table(),
            "sealed": self.sealed,
        }

    @property
    def drained(self) -> bool:
        """True when nothing is queued and no lease is outstanding —
        with ``sealed``, the hosts' exit condition. Reads only the
        queue and lease dirs (polled every idle drain tick — it must
        not pay the results/spent/hosts listings ``stats`` does)."""
        if self._count(_QUEUE) > 0:
            return False
        lease_root = os.path.join(self.path, _LEASES)
        try:
            host_dirs = os.listdir(lease_root)
        except OSError:
            return True
        for hdir in host_dirs:
            d = os.path.join(lease_root, hdir)
            if not os.path.isdir(d):
                continue
            try:
                if any(n.endswith(".json") for n in os.listdir(d)):
                    return False
            except OSError:
                continue
        return True
