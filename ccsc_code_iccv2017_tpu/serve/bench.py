"""Serving benchmark workload: CodecEngine vs the naive driver loop.

The measured question (ISSUE acceptance): on a stream of small
inpainting requests, does the engine — per-bank plans, shape-bucketed
AOT programs, micro-batched dispatches — beat the reference-shaped
"one ``reconstruct()`` call per request" driver loop
(reconstruct_2D_subsampling.m:35-60), at matching outputs on the
valid region?

The stream is HETEROGENEOUS by default (request sides drawn from
[CCSC_SERVE_SIZE_MIN, CCSC_SERVE_SIZE_MAX]): that is what serving
traffic looks like, and it is where the driver loop's per-shape
retrace+recompile cost lives (~0.5-2 s per new shape on CPU, measured
in PERF.md r7 — vs a <50 ms warm solve). The record also carries the
loop's WARM re-run rate (jit cache hot, i.e. a homogeneous steady
state) so the compile-free comparison is visible next to the headline.

One JSON-able record; scripts/serve_bench.py prints it (plus a latency
histogram), and bench.py emits it as the CCSC_BENCH_SERVE arm in the
standard record format.

Env knobs: CCSC_SERVE_REQUESTS (16), CCSC_SERVE_SIZE_MIN (40) /
CCSC_SERVE_SIZE_MAX (64), CCSC_SERVE_K (32), CCSC_SERVE_SUPPORT (7),
CCSC_SERVE_SLOTS (4), CCSC_SERVE_MAXIT (20), CCSC_SERVE_WAIT_MS (5),
CCSC_SERVE_HOMOG=1 (all requests at the bucket shape — bit-identical
outputs, isolates batching from bucketing), CCSC_COMPILE_CACHE
(persistent XLA cache for the engine warmup), CCSC_SERVE_TUNE
(off|auto|sweep — run a SECOND engine with tuned solve knobs
[ServeConfig.tune] on the same stream and record
tuned_requests_per_sec / speedup_tuned_vs_default / the resolved
knob dict, the serving half of the autotune acceptance: tuned knobs
must beat the f32/xla default at matching valid-region outputs),
CCSC_SERVE_MESH ("BATCH" or "BATCHxFREQ" — run a MESH engine
[ServeConfig.mesh_shape: the bucket's slots sharded over a device
mesh via shard_map] on the same stream through the same
run_engine/max_rel_err protocol and record mesh_requests_per_sec /
speedup_mesh_vs_default; the baseline/tuned engines pin
mesh_shape=() so the env knob cannot leak into them. The mesh
configuration lands in the perf ledger as its OWN knob-digest key —
device count in the knob dict — so mesh-serving history accrues and
gates separately from day one. The mesh record also carries the
warmup collective audit [analysis.comms] per bucket, so the ledger
row's throughput is attributable to a KNOWN communication budget),
CCSC_SERVE_PIPELINE (depth > 1 — run a PIPELINED engine
[ServeConfig.pipeline_depth: the worker holds that many launched
batches in flight, overlapping batch N+1's upload with batch N's
solve] on the same stream and record pipeline_requests_per_sec /
speedup_pipeline_vs_default plus a BITWISE parity verdict against
the default engine's outputs [pipelined dispatch only moves the
fence, never the math]; the other arms pin pipeline_depth=1 so the
env knob cannot leak into them. Its own knob-digest ledger row —
pipeline=depth in the knob dict — accrues and gates separately).
"""
from __future__ import annotations

import tempfile
import time
from typing import Dict


def run_serve_workload() -> Dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..config import ProblemGeom, ServeConfig, SolveConfig
    from ..models.reconstruct import ReconstructionProblem, reconstruct
    from ..utils import memwatch, obs, perfmodel
    from .engine import CodecEngine

    from ..utils import env as _env

    # measured HBM watermark across the whole workload (baseline loop
    # + engine) — rides the record and the perf ledger
    mw = memwatch.MemWatch()

    n_req = _env.env_int("CCSC_SERVE_REQUESTS")
    lo = _env.env_int("CCSC_SERVE_SIZE_MIN")
    hi = _env.env_int("CCSC_SERVE_SIZE_MAX")
    k = _env.env_int("CCSC_SERVE_K")
    sup = _env.env_int("CCSC_SERVE_SUPPORT")
    slots = _env.env_int("CCSC_SERVE_SLOTS")
    max_it = _env.env_int("CCSC_SERVE_MAXIT")
    wait_ms = _env.env_float("CCSC_SERVE_WAIT_MS")
    homog = _env.env_flag("CCSC_SERVE_HOMOG")

    # a malformed mesh spec is USER error, not environment shortage:
    # fail HERE, before the expensive baseline/engine arms run —
    # the same stance as apps/serve.py --mesh (only device shortage
    # and divisibility, which depend on the environment, are
    # recorded as mesh_skipped below)
    mesh_spec = _env.env_str("CCSC_SERVE_MESH")
    mesh_shape_req = None
    if mesh_spec:
        from .engine import parse_mesh_shape

        mesh_shape_req = parse_mesh_shape(mesh_spec)  # raises on typo

    r = np.random.default_rng(0)
    d = r.normal(size=(k, sup, sup)).astype(np.float32)
    d /= np.sqrt((d**2).sum(axis=(1, 2), keepdims=True))
    d = jnp.asarray(d)
    geom = ProblemGeom((sup, sup), k)
    prob = ReconstructionProblem(geom)
    cfg = SolveConfig(
        lambda_residual=5.0, lambda_prior=0.3, max_it=max_it, tol=1e-4,
        verbose="none", track_objective=True,
    )

    # smooth-ish images at heterogeneous sizes, 50% observed
    try:
        from scipy.ndimage import gaussian_filter
    except Exception:  # pragma: no cover - scipy is in the image
        gaussian_filter = lambda x, s: x
    if homog:
        sizes = [hi] * n_req
    else:
        sizes = [int(s) for s in r.integers(lo, hi + 1, n_req)]
    reqs = []
    for i, sz in enumerate(sizes):
        x = gaussian_filter(
            r.normal(size=(sz + 8, sz + 8)), 2.0
        )[4:-4, 4:-4]
        x = ((x - x.min()) / max(x.max() - x.min(), 1e-9)).astype(
            np.float32
        )
        m = (r.random((sz, sz)) < 0.5).astype(np.float32)
        reqs.append({"b": x * m, "mask": m})

    # ---- baseline: the reference driver loop, one reconstruct() per
    # request at its exact shape (per-shape jit retrace+compile is its
    # real, unavoidable serving cost)
    loop_out = []
    t0 = time.perf_counter()
    for q in reqs:
        rr = reconstruct(
            jnp.asarray(q["b"][None]), d, prob, cfg,
            mask=jnp.asarray(q["mask"][None]),
        )
        loop_out.append(np.asarray(rr.recon[0]))
    t_loop = time.perf_counter() - t0
    # warm re-run (jit cache hot): the loop's compile-free steady state
    t0 = time.perf_counter()
    for q in reqs:
        rr = reconstruct(
            jnp.asarray(q["b"][None]), d, prob, cfg,
            mask=jnp.asarray(q["mask"][None]),
        )
        float(rr.trace.num_iters)
    t_loop_warm = time.perf_counter() - t0
    mw.sample()  # post-baseline-loop watermark

    # ---- the engine: two buckets covering the size range, AOT-warmed
    mid = (lo + hi) // 2
    buckets = ((slots, (mid, mid)), (slots, (hi, hi)))
    if homog:
        buckets = ((slots, (hi, hi)),)

    def run_engine(scfg):
        """One engine over the whole stream: build (AOT warmup),
        submit, drain, close. Shared by the default/tuned/mesh/
        pipelined engines so their timing/parity protocol cannot
        drift apart. Returns (results, requests/sec, warmup_s,
        ready_wallclock, knob_dict, comm_counts) — comm_counts is
        the warmup collective audit per bucket label (mesh engines;
        empty otherwise)."""
        t0 = time.perf_counter()
        eng = CodecEngine(d, prob, cfg, scfg)
        warmup_s = time.perf_counter() - t0
        t_ready = time.time()
        t0 = time.perf_counter()
        futs = [eng.submit(**q) for q in reqs]
        results = [f.result(timeout=600) for f in futs]
        t_eng = time.perf_counter() - t0
        knobs = dict(eng._knob_dict)
        comms = {
            f"{s}@" + "x".join(str(x) for x in sp): dict(c)
            for (s, sp), c in eng.comm_counts.items()
        }
        eng.close()
        mw.sample()  # engine drained: peak request-serving state
        rate = len(reqs) / t_eng if t_eng > 0 else 0.0
        return results, rate, warmup_s, t_ready, knobs, comms

    def max_rel_err(results):
        # output parity on the valid region (engine pads to buckets;
        # the loop solved exact shapes — boundary-tolerance equality)
        worst = 0.0
        for le, se in zip(loop_out, results):
            scale = max(float(np.abs(le).max()), 1e-9)
            worst = max(
                worst, float(np.abs(se.recon - le).max()) / scale
            )
        return worst

    metrics_dir = tempfile.mkdtemp(prefix="ccsc_serve_bench_")
    scfg = ServeConfig(
        buckets=buckets, max_wait_ms=wait_ms, metrics_dir=metrics_dir,
        verbose="none",
        compile_cache=_env.env_str("CCSC_COMPILE_CACHE") or None,
        # the baseline engine is PINNED single-device and depth-1:
        # with CCSC_SERVE_MESH / CCSC_SERVE_PIPELINE armed for the
        # arms below, a None default would silently become the very
        # engine it is the baseline for
        mesh_shape=(),
        pipeline_depth=1,
    )
    eng_res, eng_rps, t_warmup, t_ready, _, _ = run_engine(scfg)
    max_rel = max_rel_err(eng_res)

    # zero-recompile assertion from the obs event stream: no backend
    # compile may land after the engine reported ready
    events = obs.read_events(metrics_dir)
    compiles_after_ready = [
        e for e in events
        if e.get("type") == "compile" and e.get("t", 0.0) > t_ready
    ]
    dispatches = [
        e for e in events if e.get("type") == "serve_dispatch"
    ]
    # the single serving percentile implementation (serve.slo): the
    # bench quotes the same log-bucketed histogram numbers as engine
    # stats(), the slo_histogram events, and obs_report
    from . import slo as _slo

    lat_hist = _slo.Histogram.of(
        e["latency_ms"]
        for e in events
        if e.get("type") == "serve_request"
    )
    summary = next(
        (e for e in reversed(events) if e.get("type") == "summary"), {}
    )
    cache_hits = (summary.get("compile") or {}).get(
        "persistent_cache_hits"
    )

    loop_rps = n_req / t_loop if t_loop > 0 else 0.0
    occ = (
        sum(e["occupancy"] for e in dispatches) / len(dispatches)
        if dispatches
        else 0.0
    )

    # ---- the TUNED engine on the same stream (CCSC_SERVE_TUNE):
    # same buckets, same requests; only ServeConfig.tune differs —
    # 'sweep' measures the solve arms on THIS chip first, 'auto'
    # applies a pre-existing store entry. The record carries both
    # rates so the default-vs-tuned gap is the measured number.
    tune_mode = _env.env_str("CCSC_SERVE_TUNE")
    tuned_fields = {}
    if tune_mode != "off":
        metrics2 = tempfile.mkdtemp(prefix="ccsc_serve_tuned_")
        scfg2 = ServeConfig(
            buckets=buckets, max_wait_ms=wait_ms,
            metrics_dir=metrics2, verbose="none",
            compile_cache=_env.env_str("CCSC_COMPILE_CACHE") or None,
            tune=tune_mode,
            # tuned arm stays single-device, depth-1 too
            mesh_shape=(),
            pipeline_depth=1,
        )
        res2, rps2, t_warm2, _, knobs2, _ = run_engine(scfg2)
        max_rel2 = max_rel_err(res2)
        tuned_fields = {
            "tuned_requests_per_sec": round(rps2, 4),
            "speedup_tuned_vs_default": round(
                rps2 / eng_rps if eng_rps else 0.0, 3
            ),
            "tuned_warmup_s": round(t_warm2, 3),
            "tuned_knobs": knobs2,
            "tuned_max_rel_err_vs_loop": round(max_rel2, 6),
            "tuned_event_stream": metrics2,
        }
    # ---- the MESH engine on the same stream (CCSC_SERVE_MESH):
    # same buckets, same requests, same run_engine/max_rel_err
    # protocol — only ServeConfig.mesh_shape differs, so the record's
    # default-vs-mesh gap is the measured value of sharding a
    # bucket's slots over the device mesh. Skipped (with the reason
    # recorded) when the visible device pool cannot back the mesh.
    mesh_fields = {}
    if mesh_shape_req is not None:
        import math as _math

        try:
            mesh_shape = mesh_shape_req
            need = _math.prod(mesh_shape)
            if need > len(jax.devices()):
                raise ValueError(
                    f"mesh {mesh_spec} needs {need} device(s), "
                    f"{len(jax.devices())} visible"
                )
            metrics3 = tempfile.mkdtemp(prefix="ccsc_serve_mesh_")
            # inside the try: a mesh that fails the bucket
            # divisibility check (ServeConfig refuses with the bucket
            # table) must record mesh_skipped like any other
            # unbackable mesh, not crash the bench after the baseline
            # and tuned arms already ran
            scfg3 = ServeConfig(
                buckets=buckets, max_wait_ms=wait_ms,
                metrics_dir=metrics3, verbose="none",
                compile_cache=(
                    _env.env_str("CCSC_COMPILE_CACHE") or None
                ),
                mesh_shape=mesh_shape,
                pipeline_depth=1,  # mesh effect alone
            )
            # build-time refusals surface at engine construction,
            # not config time: the freq axis is checked against the
            # FFT domain's bin count only when build_plan derives it
            # (models.reconstruct.check_mesh_plan) — still inside
            # this try, so it records mesh_skipped like every other
            # unbackable mesh instead of crashing the bench after
            # the baseline and tuned arms already ran
            res3, rps3, t_warm3, _, knobs3, comms3 = run_engine(scfg3)
        except ValueError as e:
            mesh_fields = {"mesh_skipped": str(e)}
        else:
            mesh_fields = {
                "mesh": "x".join(str(a) for a in mesh_shape),
                "mesh_devices": need,
                "mesh_requests_per_sec": round(rps3, 4),
                "speedup_mesh_vs_default": round(
                    rps3 / eng_rps if eng_rps else 0.0, 3
                ),
                "mesh_max_rel_err_vs_loop": round(
                    max_rel_err(res3), 6
                ),
                "mesh_warmup_s": round(t_warm3, 3),
                "mesh_knobs": knobs3,
                # the warmup collective audit per bucket
                # (analysis.comms): the ledger row's throughput is
                # attributable to a KNOWN communication budget —
                # batch-only meshes must show total=0 everywhere
                "mesh_collectives": comms3,
                "mesh_event_stream": metrics3,
            }

    # ---- the PIPELINED engine on the same stream
    # (CCSC_SERVE_PIPELINE > 1): same buckets, same requests — only
    # ServeConfig.pipeline_depth differs, so the record's
    # default-vs-pipelined gap is the measured value of overlapping
    # batch N+1's host work + upload with batch N's in-flight solve.
    # The outputs must be BITWISE the default engine's (the fence
    # only moves later; the programs and their inputs are unchanged)
    # — recorded as pipeline_bit_identical, not assumed.
    pipe_depth = _env.env_int("CCSC_SERVE_PIPELINE")
    pipe_fields = {}
    if pipe_depth and int(pipe_depth) > 1:
        metrics4 = tempfile.mkdtemp(prefix="ccsc_serve_pipe_")
        scfg4 = ServeConfig(
            buckets=buckets, max_wait_ms=wait_ms,
            metrics_dir=metrics4, verbose="none",
            compile_cache=_env.env_str("CCSC_COMPILE_CACHE") or None,
            mesh_shape=(),  # pipelining effect alone
            pipeline_depth=int(pipe_depth),
        )
        res4, rps4, t_warm4, _, knobs4, _ = run_engine(scfg4)
        pipe_fields = {
            "pipeline_depth": int(pipe_depth),
            "pipeline_requests_per_sec": round(rps4, 4),
            "speedup_pipeline_vs_default": round(
                rps4 / eng_rps if eng_rps else 0.0, 3
            ),
            "pipeline_bit_identical": all(
                np.array_equal(a.recon, b.recon)
                and int(a.trace.num_iters) == int(b.trace.num_iters)
                for a, b in zip(eng_res, res4)
            ),
            "pipeline_warmup_s": round(t_warm4, 3),
            "pipeline_knobs": knobs4,
            "pipeline_event_stream": metrics4,
        }

    from ..tune import store as tune_store

    return {
        "serve": True,
        "platform": jax.devices()[0].platform,
        "chip": perfmodel.detect_chip(),
        "shape_key": tune_store.solve_shape_key(
            "solve2d", k=k, support=(sup, sup), spatial=(hi, hi)
        ),
        "peak_hbm_bytes": mw.peak_bytes,
        "n_compiles": (summary.get("compile") or {}).get(
            "n_compiles"
        ),
        "workload": (
            f"2D inpainting serving, {n_req} "
            f"{'homogeneous' if homog else 'heterogeneous'} requests "
            f"{lo}..{hi}^2, k={k} {sup}x{sup}, max_it={max_it}"
        ),
        "engine_requests_per_sec": round(eng_rps, 4),
        "loop_requests_per_sec": round(loop_rps, 4),
        "loop_warm_requests_per_sec": round(
            n_req / t_loop_warm if t_loop_warm > 0 else 0.0, 4
        ),
        "speedup_vs_loop": round(
            eng_rps / loop_rps if loop_rps else 0.0, 3
        ),
        "warmup_s": round(t_warmup, 3),
        "p50_ms": (
            round(lat_hist.percentile(0.50), 3) if lat_hist.n else None
        ),
        "p99_ms": (
            round(lat_hist.percentile(0.99), 3) if lat_hist.n else None
        ),
        "mean_occupancy": round(occ, 4),
        "n_dispatches": len(dispatches),
        "recompiles_after_warmup": len(compiles_after_ready),
        "zero_recompile_ok": not compiles_after_ready,
        "max_rel_err_vs_loop": round(max_rel, 6),
        "persistent_cache_hits": cache_hits,
        "event_stream": metrics_dir,
        # the bench engine is a standalone engine, so CCSC_CAPTURE_DIR
        # arms workload capture on it (serve.capture) — the record
        # names the capture so a bench stream can be replayed
        # (scripts/replay.py) instead of re-generated
        "capture_dir": _env.env_str("CCSC_CAPTURE_DIR"),
        "knobs": {
            "requests": n_req,
            "size_min": lo,
            "size_max": hi,
            "k": k,
            "support": sup,
            "slots": slots,
            "max_it": max_it,
            "max_wait_ms": wait_ms,
            "homog": homog,
            "compile_cache": scfg.compile_cache,
            "tune": tune_mode,
        },
        **tuned_fields,
        **mesh_fields,
        **pipe_fields,
    }
