"""Multi-tenant bank registry: durable bank manifests + a bounded
per-bank :class:`~..models.reconstruct.ReconPlan` LRU.

Serving millions of users means many dictionary banks (four families
already ship self-trained banks in ``artifacts_*``), yet until this
module every :class:`~.engine.CodecEngine` pinned exactly ONE bank for
its lifetime, and publishing a refreshed bank meant a process restart.
The registry is the bank-publication substrate the serving stack (and
ROADMAP item 3's online-learning loop) lands on:

- :class:`BankRegistry` — durable bank manifests on disk. Each
  ``publish`` content-addresses the bank array into ``banks/<sha>.npy``
  (atomic tmp+rename; identical banks across publishes are stored
  once) and appends one manifest record to ``manifest.jsonl`` with the
  ``analysis.ledger`` torn-tail stance: one flushed line per record, a
  reader (:meth:`BankRegistry.resolve`) drops a torn trailing line
  instead of failing the registry. A manifest carries the bank id, the
  sha256 ``d_digest`` (the SAME fingerprint
  ``models.reconstruct.ReconPlan`` refuses stale plans by), the full
  payload sha, the geometry (filter count + support), and free tenant
  metadata — so a consumer can refuse a bank whose geometry does not
  match its pinned problem BEFORE any plan builds. Latest record per
  bank id wins; the full history stays readable
  (:meth:`BankRegistry.history`) for swap forensics.
- :class:`PlanCache` — the per-bank ``ReconPlan`` LRU, keyed by
  ``(d_digest, bucket)`` and bounded in BYTES (summed plan-leaf
  nbytes) against a budget (``CCSC_BANK_PLAN_CACHE_MB``), with the
  measured-HBM watermark (``utils.memwatch``) sampled at every build
  so eviction decisions are recorded next to what the device actually
  holds. A miss rebuilds from the retained bank bytes
  (evict-and-rebuild — the cache can always come back); plans are
  stored with the digest CANONICALIZED out of the pytree aux data
  (``d_digest=""``) so every same-geometry bank shares ONE compiled
  bucket program and a hot-swap never pays a retrace.

Zero-downtime hot-swap rides these two pieces: re-publishing a bank id
under a new digest turns the digest-based plan refusal of
``reconstruct(plan=...)`` into rebuild-and-swap — the engine builds
the new digest's plans off the hot path (a jitted ``build_plan`` call,
no XLA recompile), in-flight requests finish on the old plan (they
bound their digest at admission), and the route flip is one dict write
under the queue lock (serve.engine / serve.fleet).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import env as _env
from ..utils import obs as _obs

__all__ = [
    "BankRegistry",
    "BankManifest",
    "PlanCache",
    "bank_digest",
    "plan_nbytes",
    "resolve_registry_dir",
]

_MANIFEST_NAME = "manifest.jsonl"
_BANK_DIR = "banks"
_SCHEMA = 1


def resolve_registry_dir(explicit: Optional[str]) -> Optional[str]:
    """The one resolution chain for the registry location: an explicit
    path wins, else ``CCSC_BANK_REGISTRY``, else no registry (None).
    Shared by apps/serve.py and any publisher so the two cannot
    diverge (the ``resolve_capture_dir`` convention)."""
    if explicit == "":
        return None
    return explicit or _env.env_str("CCSC_BANK_REGISTRY") or None


def bank_digest(d) -> str:
    """Content fingerprint of a dictionary bank — the exact
    ``d_digest`` every built :class:`~..models.reconstruct.ReconPlan`
    carries and ``reconstruct(plan=...)`` refuses mismatches by, so
    registry routing and plan refusal can never disagree about bank
    identity."""
    from ..models.reconstruct import _bank_digest

    return _bank_digest(d)


def plan_nbytes(plan) -> int:
    """Device bytes a plan pins: summed nbytes over the plan pytree's
    array leaves (spectra + per-frequency solve factors) — the unit
    the :class:`PlanCache` budget is charged in."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(plan):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class BankManifest(Dict[str, Any]):
    """One manifest record (a plain dict subclass so readers can use
    ``rec["digest"]`` / ``rec.get("tenant")`` uniformly); declared
    keys: ``bank_id``, ``digest`` (the plan-refusal ``d_digest``),
    ``sha256`` (full payload sha), ``path`` (bank array on disk),
    ``geometry`` ({num_filters, spatial_support, reduce_shape}),
    ``tenant``, ``seq``, ``t``."""


class BankRegistry:
    """Durable bank manifests + content-addressed bank store.

    Thread-safe: ``publish`` may be called from any thread (an online
    learner publishing while a server resolves); the manifest append
    and the seq counter are ordered by a private lock, the array write
    is atomic (tmp + rename) and happens outside it.

    ``emit`` is an optional obs-event callable (``run.event``-shaped):
    when given, every publish is announced as a ``bank_publish``
    event. The registry itself never routes traffic — engines/fleets
    load banks from it and own the serving-side routing table.
    """

    def __init__(self, path: str, emit=None):
        self.path = path
        self._emit = emit
        self._lock = threading.Lock()
        os.makedirs(os.path.join(path, _BANK_DIR), exist_ok=True)
        # resume-aware: a registry reopened on an existing dir
        # continues the publish sequence after the newest durable
        # record (torn tail dropped by the reader)
        self._seq = max(
            (int(r.get("seq", 0)) for r in self._read_manifest()),
            default=0,
        )
        self._writer = _obs.EventWriter(
            os.path.join(path, _MANIFEST_NAME)
        )

    # -- read side ----------------------------------------------------
    def _read_manifest(self) -> List[BankManifest]:
        return [
            BankManifest(r)
            for r in _obs.read_events(
                os.path.join(self.path, _MANIFEST_NAME)
            )
            if r.get("bank_id") and r.get("digest")
        ]

    def bank_ids(self) -> List[str]:
        """Every bank id ever published, insertion order, deduped."""
        seen: Dict[str, None] = {}
        for rec in self._read_manifest():
            seen.setdefault(rec["bank_id"], None)
        return list(seen)

    def history(self, bank_id: str) -> List[BankManifest]:
        """Every manifest record for ``bank_id``, oldest first — the
        swap history (old -> new digests with publish timestamps)."""
        return [
            r for r in self._read_manifest()
            if r["bank_id"] == bank_id
        ]

    def previous(self, bank_id: str) -> Optional[BankManifest]:
        """The manifest published immediately BEFORE the current one
        for ``bank_id`` — the rollback target a quality demotion
        advisory (``quality_demote_advice``) points back to. Skips
        records carrying the same digest as the head (a refresh
        republish must not become its own rollback target). None when
        the bank has no distinct prior digest."""
        hist = self.history(bank_id)
        if not hist:
            return None
        head = hist[-1]["digest"]
        for rec in reversed(hist[:-1]):
            if rec["digest"] != head:
                return rec
        return None

    def resolve(self, bank_id: str) -> BankManifest:
        """The NEWEST manifest for ``bank_id`` (latest record wins —
        re-publishing a bank id under a new digest is the hot-swap
        trigger). Raises ``CCSCInputError`` for an unknown id, with
        the known ids in the message."""
        from ..utils import validate

        hist = self.history(bank_id)
        if not hist:
            raise validate.CCSCInputError(
                f"bank id {bank_id!r} is not in the registry at "
                f"{self.path} (known: {self.bank_ids() or 'none'})"
            )
        return hist[-1]

    def load(self, bank_id: str) -> Tuple[np.ndarray, BankManifest]:
        """Load the newest published bank array for ``bank_id``
        (refusing a store whose bytes drifted from the manifest
        digest — a torn or hand-edited payload must never serve)."""
        from ..utils import validate

        man = self.resolve(bank_id)
        arr = np.load(os.path.join(self.path, man["path"]))
        if bank_digest(arr) != man["digest"]:
            raise validate.CCSCInputError(
                f"bank {bank_id!r} payload {man['path']} does not "
                f"match its manifest digest {man['digest']} — the "
                "store is corrupt; re-publish the bank"
            )
        return arr, man

    # -- write side ---------------------------------------------------
    def publish(
        self,
        bank_id: str,
        d,
        tenant: Optional[str] = None,
        geom=None,
        **meta,
    ) -> BankManifest:
        """Durably publish (or re-publish) ``bank_id`` as the bank
        array ``d``. Content-addressed: identical bytes are stored
        once; a re-publish under a NEW digest is what downstream
        consumers treat as the hot-swap trigger. ``geom`` (a
        ``ProblemGeom``) pins the recorded reduce/spatial split for
        families with reduce axes; without it the trailing two axes
        are recorded as spatial (the 2D families). Returns the
        appended manifest."""
        import hashlib

        arr = np.ascontiguousarray(np.asarray(d, np.float32))
        digest = bank_digest(arr)
        full = hashlib.sha256(arr.tobytes()).hexdigest()
        rel = os.path.join(_BANK_DIR, f"{digest}.npy")
        fpath = os.path.join(self.path, rel)
        if not os.path.exists(fpath):
            tmp = fpath + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                np.save(f, arr)
            os.replace(tmp, fpath)
        if geom is not None:
            geometry = {
                "num_filters": int(arr.shape[0]),
                "reduce_shape": list(geom.reduce_shape),
                "spatial_support": list(geom.spatial_support),
            }
        else:
            geometry = {
                "num_filters": int(arr.shape[0]),
                "reduce_shape": list(arr.shape[1:-2]),
                "spatial_support": list(arr.shape[-2:]),
            }
        rec = BankManifest(
            schema=_SCHEMA,
            bank_id=str(bank_id),
            digest=digest,
            sha256=full,
            path=rel,
            geometry=geometry,
            tenant=tenant,
            t=time.time(),
            **meta,
        )
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._writer.write(dict(rec))
        if self._emit is not None:
            self._emit(
                "bank_publish",
                bank_id=rec["bank_id"],
                digest=digest,
                seq=rec["seq"],
                tenant=tenant,
                registry=self.path,
            )
        return rec

    def close(self) -> None:
        with self._lock:
            self._writer.close()

    def __enter__(self) -> "BankRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PlanCache:
    """Bounded per-bank :class:`ReconPlan` LRU, keyed by
    ``(d_digest, bucket_key)``.

    ``max_bytes`` bounds the summed device bytes of cached plans
    (default ``CCSC_BANK_PLAN_CACHE_MB``); insertion past the budget
    evicts least-recently-used entries — except entries whose digest
    is ``pin``\\ ned (the engine pins the digests of queued/in-flight
    requests so a dispatch can never lose its plan mid-batch). A miss
    is NOT fatal: the owner rebuilds from retained bank bytes
    (evict-and-rebuild), which costs one jitted ``build_plan`` call,
    never an XLA recompile (plans are stored digest-canonicalized, so
    every same-geometry bank shares one compiled bucket program).

    The measured-HBM watermark (``utils.memwatch.MemWatch``) is
    sampled on every ``put`` and carried in the stats, so the budget
    the cache enforces sits next to what the allocator actually
    reports. Thread-safe (one lock; nothing blocking held under it).
    """

    def __init__(
        self,
        max_bytes: Optional[int] = None,
        memwatch=None,
    ):
        if max_bytes is None:
            mb = _env.env_float("CCSC_BANK_PLAN_CACHE_MB")
            max_bytes = int(float(mb) * 1e6)
        self.max_bytes = max(1, int(max_bytes))
        if memwatch is None:
            from ..utils import memwatch as _memwatch

            memwatch = _memwatch.MemWatch()
        self._memwatch = memwatch
        self._lock = threading.Lock()
        # key -> (plan, nbytes); dict preserves insertion order, and
        # a get() re-inserts to mark recency (the OrderedDict
        # move_to_end idiom without the import)
        self._entries: Dict[Tuple[str, Any], Tuple[Any, int]] = {}
        self.total_bytes = 0
        self.n_hits = 0
        self.n_misses = 0
        self.n_evictions = 0

    def get(self, digest: str, bucket) -> Optional[Any]:
        """The cached plan for ``(digest, bucket)`` or None (the
        caller rebuilds on a miss)."""
        key = (digest, bucket)
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                self.n_misses += 1
                return None
            self._entries[key] = entry  # re-insert: newest
            self.n_hits += 1
            return entry[0]

    def put(
        self, digest: str, bucket, plan,
        pin: Optional[set] = None,
    ) -> List[Tuple[str, Any]]:
        """Insert a plan and evict past the budget; returns the
        evicted ``(digest, bucket)`` keys so the owner can announce
        them (``bank_plan_evict``). ``pin`` is a set of digests that
        must not be evicted (in-flight work)."""
        nbytes = plan_nbytes(plan)
        self._memwatch.sample()
        evicted: List[Tuple[str, Any]] = []
        with self._lock:
            old = self._entries.pop((digest, bucket), None)
            if old is not None:
                self.total_bytes -= old[1]
            self._entries[(digest, bucket)] = (plan, nbytes)
            self.total_bytes += nbytes
            if self.total_bytes > self.max_bytes:
                for key in list(self._entries):
                    if self.total_bytes <= self.max_bytes:
                        break
                    if key == (digest, bucket):
                        continue  # never evict the entry just added
                    if pin and key[0] in pin:
                        continue
                    _plan, nb = self._entries.pop(key)
                    self.total_bytes -= nb
                    self.n_evictions += 1
                    evicted.append(key)
        return evicted

    def drop_digest(self, digest: str) -> List[Tuple[str, Any]]:
        """Evict every bucket's plan for one digest (a retired bank)."""
        dropped: List[Tuple[str, Any]] = []
        with self._lock:
            for key in list(self._entries):
                if key[0] == digest:
                    _plan, nb = self._entries.pop(key)
                    self.total_bytes -= nb
                    self.n_evictions += 1
                    dropped.append(key)
        return dropped

    def digests(self) -> List[str]:
        with self._lock:
            out: Dict[str, None] = {}
            for dg, _bucket in self._entries:
                out.setdefault(dg, None)
            return list(out)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._entries)
            total = self.total_bytes
            hits, misses, ev = (
                self.n_hits, self.n_misses, self.n_evictions
            )
        return {
            "n_plans": n,
            "plan_bytes": total,
            "max_bytes": self.max_bytes,
            "hits": hits,
            "misses": misses,
            "evictions": ev,
            # the measured watermark next to the enforced budget: a
            # reader judging "is the budget honest" compares these
            "measured_peak_hbm_bytes": self._memwatch.peak_bytes,
        }


def render_manifest(rec: BankManifest) -> str:
    """One-line human rendering of a manifest (apps/serve.py and the
    TENANTS report section share it)."""
    geo = rec.get("geometry") or {}
    return (
        f"{rec.get('bank_id')} @ {rec.get('digest')} "
        f"(K={geo.get('num_filters')}, support "
        f"{'x'.join(str(s) for s in geo.get('spatial_support') or [])}"
        + (f", tenant {rec['tenant']}" if rec.get("tenant") else "")
        + f", seq {rec.get('seq')})"
    )


def _json_default(o):  # pragma: no cover - defensive serialization
    return str(o)


def manifest_json(rec: BankManifest) -> str:
    return json.dumps(dict(rec), default=_json_default)
