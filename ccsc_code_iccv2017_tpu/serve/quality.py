"""Quality observatory: served-PSNR telemetry, golden probes, and
the bank quality gate.

The serving stack observes every operational signal — latency SLOs,
traces, HBM watermarks, compiles, the perf ledger — but was blind to
the one thing CCSC actually produces: reconstruction quality. The
per-request valid-region PSNR was computed once (serve.engine) and
dropped on the floor. This module is the quality plane, built on the
proven observatory patterns:

- :func:`valid_region_psnr` — THE one valid-region PSNR
  implementation (psf-radius border crop, request-shaped). The
  engine's dispatch path, the capture outcome records, the replay
  verifier and every scorer below call this exact function, so a
  recorded dB and a recomputed dB can never drift apart.
- :class:`QualityMonitor` — per-(bank_id, tenant, bucket) dB
  histograms (``serve.slo.Histogram`` with dB buckets), declared
  per-tenant quality floors (``TenantSpec.min_psnr_db`` →
  ``quality_breach`` events, the SloMonitor re-fire discipline
  inverted for "provably BELOW the floor"), on-device solve
  diagnostics folded per bucket (the learner ObsExtras pattern
  extended to solves — read back at the EXISTING dispatch fence,
  never an extra dispatch), and AnomalyWatch-style drift detection
  against per-bank ledger history (``quality_drift`` events).
- :class:`ProbeSet` — golden probes: deterministic requests with
  content-addressed reference outcomes (the capture payload-store
  layout), scheduled through idle replicas at
  ``CCSC_PROBE_INTERVAL_S``, scored bit-exact (recon digest match)
  and in dB. A regression emits ``quality_probe_breach`` plus an
  advisory demotion signal (``quality_demote_advice``) the
  registry/controller — or a human — can act on.
- :func:`score_bank` — shadow bank scoring: replay a captured
  segment through a candidate bank OFFLINE and append a
  ``kind=quality`` ledger record keyed by bank (the record carries
  the bank DIGEST); :func:`judge_candidate` — the perf_gate band
  math with an ABSOLUTE dB floor (``CCSC_QUALITY_GATE_DB``; a
  relative frac band at ~30 dB would never catch a -3 dB
  regression) — judges candidate-vs-live history. This is the
  publish guard ROADMAP item 1 (online dictionary learning) needs:
  ``scripts/quality_gate.py`` runs it in CI and
  ``ServeFleet.publish_bank(..., quality_check=True)`` (or
  ``CCSC_QUALITY_GATE=1``) refuses a regressing candidate.

Thread-safety follows serve.slo: ``observe``/``observe_solve`` run on
worker threads, ``tick`` on the monitor thread; all mutation holds
the internal lock and NOTHING is emitted under it — every method
returns records for the caller to emit.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import env as _env
from . import slo as _slo

__all__ = [
    "DB_BOUNDS",
    "PROBE_KEY_PREFIX",
    "ProbeSet",
    "QualityGateError",
    "QualityMonitor",
    "judge_candidate",
    "quality_band",
    "resolve_probe_dir",
    "score_bank",
    "synth_probe",
    "valid_region_psnr",
]

# Idempotency-key prefix of golden-probe requests: probe traffic is
# real traffic (same admission, same solve), but the capture layer
# skips it (a probe must never pollute the replayable workload) and
# stream readers can filter it.
PROBE_KEY_PREFIX = "__probe__"

# The shared dB bucket table: 0.5 dB steps over (0, 80] dB + the
# overflow bucket. Linear, not log — PSNR is already a log-domain
# quantity, and a fixed table means every quality histogram in any
# stream merges (the slo.DEFAULT_BOUNDS_MS stance applied to dB).
DB_BOUNDS: Tuple[float, ...] = tuple(
    round(0.5 * i, 1) for i in range(1, 161)
)


def valid_region_psnr(
    rec: np.ndarray, ref: np.ndarray, radius: Tuple[int, ...]
) -> float:
    """PSNR of the cropped (request-shaped) reconstruction against its
    ground truth, with the same psf-radius border crop as common.psnr —
    the in-solve trace averages over the whole BUCKET canvas, which
    dilutes the MSE of a padded request with unconstrained pad pixels.

    This is THE shared implementation (moved here from serve.engine):
    the engine's per-request ``ServedResult.psnr``, the capture
    outcome records, replay's cross-bucket verification and the
    probe/shadow scorers all quote this exact computation — bit-equal
    by construction, pinned by tests/test_quality.py against recorded
    capture values."""
    rec = np.asarray(rec)
    ref = np.asarray(ref)
    nd = len(radius)
    sl = tuple(
        slice(r, s - r) for r, s in zip(radius, rec.shape[-nd:])
    )
    sl = (Ellipsis, *sl)
    mse = float(np.mean((rec[sl] - ref[sl]) ** 2))
    return float(10.0 * np.log10(1.0 / max(mse, 1e-12)))


def quality_band(
    values: Iterable[float],
    mad_k: Optional[float] = None,
    db: Optional[float] = None,
) -> Optional[Dict[str, float]]:
    """The quality regression band: ``analysis.ledger.robust_band``
    with the relative frac floor replaced by an ABSOLUTE dB floor
    (``CCSC_QUALITY_GATE_DB``). The perf gate's relative band is
    meaningless in dB — 25% of a 30 dB median is 7.5 dB, far past any
    regression worth catching — so quality history is judged as
    ``median - max(mad_k * 1.4826 * MAD, db)``."""
    from ..analysis import ledger as _ledger

    if db is None:
        db = _env.env_float("CCSC_QUALITY_GATE_DB")
    return _ledger.robust_band(
        values, mad_k=mad_k, frac=0.0, abs_floor=float(db)
    )


class QualityGateError(RuntimeError):
    """A candidate bank's shadow-score history regresses below the
    live bank's quality band — raised by ``publish_bank`` when the
    opt-in quality check refuses the swap. Carries the verdict
    list (``.verdicts``) the refusal was based on."""

    def __init__(self, msg: str, verdicts: Optional[List[Dict]] = None):
        super().__init__(msg)
        self.verdicts = verdicts or []


# ---------------------------------------------------------------------
# the quality monitor
# ---------------------------------------------------------------------


class QualityMonitor:
    """Streaming served-quality telemetry for one engine or fleet.

    ``observe`` folds one delivered request's valid-region PSNR into
    the per-(bank_id, tenant, bucket) dB histogram (and the tenant's
    floor histogram, and the per-bank drift watch); ``observe_solve``
    folds one dispatch's on-device solve diagnostics. ``tick`` (check
    cadence ``CCSC_QUALITY_CHECK_S``) returns breach / histogram /
    solve-diagnostic records for the caller to emit as
    ``quality_breach`` / ``quality_histogram`` / ``quality_solve_diag``
    events; ``final`` flushes unconditionally at close.

    Floor breaches mirror SloMonitor's conservatism, INVERTED for a
    lower bound: a breach fires only when the tenant's median-rank
    bucket's UPPER edge sits below ``min_psnr_db`` — the true median
    is then provably below the floor (quality snapshots reuse the
    Histogram snapshot shape, so the ``*_ms`` keys carry dB — the
    ``unit`` field says so). Re-fire dedup is the same ``_last_n``
    discipline: a breached-and-idle tenant does not re-fire every
    tick.

    Drift detection: ``drift_band_for(bank_id, digest)`` (optional) is
    consulted once per (bank_id, digest) pair to build an
    :class:`~..analysis.ledger.AnomalyWatch` from per-bank
    ``kind=quality`` ledger history; a rolling median of served dB
    below the band's lower edge returns one ``quality_drift`` fire
    per excursion (re-arms on recovery)."""

    def __init__(
        self,
        specs=None,
        check_s: Optional[float] = None,
        bounds: Sequence[float] = DB_BOUNDS,
        drift_band_for=None,
        drift_window: Optional[int] = None,
    ):
        self.floors: Dict[str, float] = {}
        for spec in specs or ():
            floor = getattr(spec, "min_psnr_db", None)
            if floor is not None and floor > 0:
                self.floors[spec.tenant] = float(floor)
        if check_s is None:
            check_s = _env.env_float("CCSC_QUALITY_CHECK_S")
        self.check_s = max(0.0, float(check_s))
        self._bounds = tuple(bounds)
        # (bank_id, tenant, bucket) -> dB histogram
        self._hists: Dict[Tuple, _slo.Histogram] = {}
        # tenant -> dB histogram the floor is judged against
        self._tenant_hists: Dict[str, _slo.Histogram] = {}
        # bucket -> solve-diagnostic accumulators
        self._diags: Dict[str, Dict[str, float]] = {}
        self._last_check = 0.0
        self._last_n: Dict[str, int] = {}
        self._breached: set = set()
        self._drift_band_for = drift_band_for
        if drift_window is None:
            drift_window = _env.env_int("CCSC_QUALITY_DRIFT_WINDOW")
        self._drift_window = max(1, int(drift_window))
        self._drift: Dict[Tuple, object] = {}
        self._drift_unbanded: set = set()
        self._lock = threading.Lock()

    # -- observation ---------------------------------------------------
    def observe(
        self,
        db: Optional[float],
        *,
        bank_id: Optional[str] = None,
        tenant: Optional[str] = None,
        bucket: Optional[str] = None,
        digest: Optional[str] = None,
    ) -> List[Dict]:
        """Fold one delivered request's dB (None = untracked request,
        a no-op). Returns ``quality_drift`` fire records for the
        CALLER to emit — nothing is emitted under the lock."""
        if db is None:
            return []
        db = float(db)
        if not math.isfinite(db):
            return []
        fires: List[Dict] = []
        with self._lock:
            key = (bank_id, tenant, bucket)
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _slo.Histogram(self._bounds)
            h.observe(db)
            if tenant is not None and tenant in self.floors:
                th = self._tenant_hists.get(tenant)
                if th is None:
                    th = self._tenant_hists[tenant] = _slo.Histogram(
                        self._bounds
                    )
                th.observe(db)
            watch = self._drift_watch_locked(bank_id, digest)
            if watch is not None:
                rec = watch.observe(db)
                if rec:
                    fires.append(
                        {
                            "bank_id": bank_id,
                            "digest": digest,
                            # AnomalyWatch speaks roofline-frac; the
                            # quality plane renames to dB
                            "rolling_db": rec["rolling_frac"],
                            "band_lo": rec["band_lo"],
                            "median": rec["median"],
                            "n_history": rec["n_history"],
                            "window": rec["window"],
                        }
                    )
        return fires

    def _drift_watch_locked(self, bank_id, digest):
        if self._drift_band_for is None or digest is None:
            return None
        key = (bank_id, digest)
        if key in self._drift_unbanded:
            return None
        watch = self._drift.get(key)
        if watch is None:
            from ..analysis import ledger as _ledger

            try:
                band = self._drift_band_for(bank_id, digest)
            except Exception:
                band = None
            if not band:
                # one lookup per (bank, digest): a bank with no
                # quality history yet is not re-queried per request
                self._drift_unbanded.add(key)
                return None
            watch = self._drift[key] = _ledger.AnomalyWatch(
                band,
                window=self._drift_window,
                key=f"quality|{bank_id or 'default'}|{digest}",
            )
        return watch

    def observe_solve(
        self,
        bucket: str,
        iters,
        max_it: int,
        obj_fid=None,
        obj_l1=None,
        nonfinite=None,
    ) -> None:
        """Fold one dispatch's solve diagnostics: iterations-to-stop
        per filled slot (tol-stop = stopped short of ``max_it``), and
        — when the solve ran with ``SolveConfig.track_diagnostics`` —
        the on-device objective split (data residual vs L1) and
        nonfinite count read back at the EXISTING dispatch fence."""
        its = [int(v) for v in np.atleast_1d(np.asarray(iters))]
        with self._lock:
            d = self._diags.get(bucket)
            if d is None:
                d = self._diags[bucket] = {
                    "n": 0,
                    "iters_sum": 0,
                    "tol_stops": 0,
                    "maxit_stops": 0,
                    "nonfinite": 0,
                    "obj_fid_sum": 0.0,
                    "obj_l1_sum": 0.0,
                    "obj_n": 0,
                }
            for v in its:
                d["n"] += 1
                d["iters_sum"] += v
                if v < int(max_it):
                    d["tol_stops"] += 1
                else:
                    d["maxit_stops"] += 1
            if nonfinite is not None:
                d["nonfinite"] += int(np.sum(np.asarray(nonfinite)))
            if obj_fid is not None and obj_l1 is not None:
                fid = np.atleast_1d(np.asarray(obj_fid, np.float64))
                l1 = np.atleast_1d(np.asarray(obj_l1, np.float64))
                d["obj_fid_sum"] += float(np.sum(fid))
                d["obj_l1_sum"] += float(np.sum(l1))
                d["obj_n"] += int(fid.size)

    # -- checks / snapshots --------------------------------------------
    def _breaches_locked(self) -> List[Dict]:
        out: List[Dict] = []
        for tenant in sorted(self.floors):
            floor = self.floors[tenant]
            h = self._tenant_hists.get(tenant)
            if h is None or h.n == 0:
                continue
            # only re-judge once new observations arrived — a
            # breached-and-idle tenant must not re-fire every tick
            if self._last_n.get(tenant) == h.n:
                continue
            self._last_n[tenant] = h.n
            observed = h.percentile(0.50)
            # conservative, mirrored from SloMonitor: the median-rank
            # bucket's UPPER edge below the floor proves the true
            # median is below it; comparing the lower edge would
            # false-breach whenever the floor merely falls inside
            # the rank bucket
            if observed is not None and observed < floor:
                self._breached.add(tenant)
                out.append(
                    {
                        "tenant": tenant,
                        "min_psnr_db": floor,
                        "observed_db": round(observed, 3),
                        "n": h.n,
                    }
                )
            elif observed is not None:
                self._breached.discard(tenant)
        return out

    def _snapshots_locked(self) -> List[Dict]:
        out: List[Dict] = []
        for key in sorted(
            self._hists, key=lambda k: tuple(str(x) for x in k)
        ):
            h = self._hists[key]
            if h.n == 0:
                continue
            bank_id, tenant, bucket = key
            snap = {
                "bank_id": bank_id,
                "tenant": tenant,
                "bucket": bucket,
                "unit": "db",
            }
            snap.update(h.snapshot())
            out.append(snap)
        return out

    def _diags_locked(self) -> List[Dict]:
        out: List[Dict] = []
        for bucket in sorted(self._diags):
            d = self._diags[bucket]
            if not d["n"]:
                continue
            rec = {
                "bucket": bucket,
                "n": d["n"],
                "iters_mean": round(d["iters_sum"] / d["n"], 3),
                "tol_stop_frac": round(d["tol_stops"] / d["n"], 4),
                "maxit_stop_frac": round(
                    d["maxit_stops"] / d["n"], 4
                ),
                "nonfinite": d["nonfinite"],
            }
            if d["obj_n"]:
                rec["obj_fid_mean"] = round(
                    d["obj_fid_sum"] / d["obj_n"], 6
                )
                rec["obj_l1_mean"] = round(
                    d["obj_l1_sum"] / d["obj_n"], 6
                )
            out.append(rec)
        return out

    def tick(
        self, now: Optional[float] = None
    ) -> Tuple[List[Dict], List[Dict], List[Dict]]:
        """(breaches, histogram snapshots, solve diagnostics) when the
        check cadence elapsed, else ``([], [], [])``. The caller emits
        them (``quality_breach`` / ``quality_histogram`` /
        ``quality_solve_diag``)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if (
                self._last_check
                and now - self._last_check < self.check_s
            ):
                return [], [], []
            self._last_check = now
            return (
                self._breaches_locked(),
                self._snapshots_locked(),
                self._diags_locked(),
            )

    def final(self) -> Tuple[List[Dict], List[Dict], List[Dict]]:
        """Unconditional closing flush — the stream always ends with
        one complete quality histogram per (bank, tenant, bucket)."""
        with self._lock:
            return (
                self._breaches_locked(),
                self._snapshots_locked(),
                self._diags_locked(),
            )

    def raw_snapshots(self) -> List[Dict]:
        """Current dB snapshots WITHOUT touching breach bookkeeping —
        the metricsd scrape source (``ccsc_psnr_db``)."""
        with self._lock:
            return self._snapshots_locked()

    @property
    def n_breached(self) -> int:
        """Tenants currently judged below their declared floor — the
        ``ccsc_quality_breach`` gauge."""
        with self._lock:
            return len(self._breached)


# ---------------------------------------------------------------------
# golden probes
# ---------------------------------------------------------------------


def resolve_probe_dir(explicit: Optional[str]) -> Optional[str]:
    """Probe-dir resolution chain (the capture_dir stance): explicit
    config wins, else ``CCSC_PROBE_DIR``, else probing is off; an
    explicit empty string is off regardless of the env."""
    if explicit == "":
        return None
    return explicit or _env.env_str("CCSC_PROBE_DIR") or None


def synth_probe(
    d, spatial, seed: int, density: float = 0.08
) -> np.ndarray:
    """Deterministic in-distribution probe content: a sparse code
    drawn at ``density`` synthesized through the bank ``d``
    (circular convolution), zero-mean, scaled to unit peak. Content
    a bank can actually represent is the only content whose served
    dB RANKS banks — on generic noise the ordering between two banks
    is arbitrary (a smooth rank-1 bank out-scores a trained one by
    predicting the local mean), which is useless as a rot signal."""
    d = np.asarray(d, np.float32)
    rng = np.random.default_rng(seed)
    k = d.shape[0]
    z = np.zeros((k, *spatial), np.float32)
    nz = rng.random((k, *spatial)) < density
    z[nz] = rng.standard_normal(int(nz.sum())).astype(np.float32)
    dpad = np.zeros((k, *spatial), np.float32)
    dpad[(slice(None), *(slice(0, s) for s in d.shape[1:]))] = d
    x = np.real(
        np.fft.ifftn(
            (
                np.fft.fftn(dpad, axes=range(1, 1 + len(spatial)))
                * np.fft.fftn(z, axes=range(1, 1 + len(spatial)))
            ).sum(axis=0),
            axes=range(len(spatial)),
        )
    )
    return (x / max(float(np.abs(x).max()), 1e-6)).astype(
        np.float32
    )


class ProbeSet:
    """Golden probes with content-addressed reference outcomes.

    Layout is the capture payload store's: ``payloads/<sha256>.npy``
    holds every array (probe inputs AND reference reconstructions,
    deduplicated by content), ``probes.jsonl`` is the append-only
    manifest — ``kind=probe`` rows declare the deterministic inputs,
    ``kind=reference`` rows pin (probe, bank digest) → (recon sha,
    dB). References are SELF-SEALING with one guard: the first
    scored run of a digest with no stored reference records one —
    UNLESS the same (probe, bank id) already holds a reference under
    a DIFFERENT digest and the new digest scores more than
    ``CCSC_PROBE_DB_TOL`` below it. That is the bank-rot case (a
    hot-swap to a degraded bank): sealing would bless the rot as its
    own baseline, so the run is judged ``regressed`` against the
    bank's standing reference instead. Within a digest every later
    run is judged bit-exact first (sha match), then in dB. Swapping
    a bank back to a previously-referenced digest re-judges against
    the ORIGINAL reference, which is what makes "demotion restored
    the old quality" checkable."""

    MANIFEST = "probes.jsonl"
    _PAYLOAD_DIR = "payloads"

    def __init__(self, path: str):
        self.path = path
        self._probes: Dict[str, Dict] = {}
        self._refs: Dict[Tuple[str, str], Dict] = {}
        # (probe, bank id) -> newest reference across ALL digests of
        # that bank — the standing baseline a never-seen digest is
        # judged against before it may seal its own reference
        self._bank_refs: Dict[Tuple[str, str], Dict] = {}
        self._lock = threading.Lock()
        try:
            with open(
                os.path.join(path, self.MANIFEST),
                encoding="utf-8",
                errors="replace",
            ) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("kind") == "probe":
                        self._probes[rec["name"]] = rec
                    elif rec.get("kind") == "reference":
                        # newest wins (append order)
                        self._refs[
                            (rec["probe"], rec["digest"])
                        ] = rec
                        if rec.get("bank"):
                            self._bank_refs[
                                (rec["probe"], rec["bank"])
                            ] = rec
        except OSError:
            pass

    # -- construction --------------------------------------------------
    @classmethod
    def generate(
        cls,
        path: str,
        geom,
        buckets,
        n_per_bucket: int = 1,
        seed: int = 0,
        d=None,
    ) -> "ProbeSet":
        """Create a deterministic probe set for one serving
        geometry: ``n_per_bucket`` probes per configured bucket
        spatial size. With ``d`` (the serving fleet passes its
        pinned bank) probe content is :func:`synth_probe` — sparse
        codes synthesized THROUGH the bank, the only content whose
        served dB ranks banks — served unmasked. Without ``d`` it
        falls back to half-masked uniform noise (still a bit-exact
        determinism witness, but dB-blind to bank identity). Probes
        already present are kept — regenerating is idempotent, so
        references survive."""
        os.makedirs(
            os.path.join(path, cls._PAYLOAD_DIR), exist_ok=True
        )
        ps = cls(path)
        idx = 0
        for slots, spatial in buckets:
            for j in range(n_per_bucket):
                name = (
                    "probe-"
                    + "x".join(str(s) for s in spatial)
                    + f"-{j}"
                )
                idx += 1
                if name in ps._probes:
                    continue
                shape = (*geom.reduce_shape, *spatial)
                if d is not None:
                    x = synth_probe(d, tuple(spatial), seed + idx)
                    x = np.broadcast_to(x, shape).copy()
                    sha_x = ps._store_payload(x)
                    sha_b, sha_m = sha_x, None
                else:
                    rng = np.random.default_rng(seed + idx)
                    x = rng.random(shape, dtype=np.float32)
                    m = (
                        rng.random(shape) < 0.5
                    ).astype(np.float32)
                    sha_x = ps._store_payload(x)
                    sha_b = ps._store_payload(x * m)
                    sha_m = ps._store_payload(m)
                rec = {
                    "kind": "probe",
                    "name": name,
                    "spatial": list(spatial),
                    "psf_radius": list(geom.psf_radius),
                    "seed": seed + idx,
                    "b": sha_b,
                    "mask": sha_m,
                    "x_orig": sha_x,
                }
                ps._append(rec)
                ps._probes[name] = rec
        return ps

    def _store_payload(self, arr: np.ndarray) -> str:
        from . import capture as _capture

        arr = np.ascontiguousarray(arr)
        sha = _capture.payload_sha(arr)
        fpath = os.path.join(
            self.path, self._PAYLOAD_DIR, sha + ".npy"
        )
        if not os.path.exists(fpath):
            tmp = fpath + f".tmp-{os.getpid()}"
            with open(tmp, "wb") as f:
                np.save(f, arr)
            os.replace(tmp, fpath)
        return sha

    def _append(self, rec: Dict) -> None:
        with self._lock:
            with open(
                os.path.join(self.path, self.MANIFEST),
                "a",
                encoding="utf-8",
            ) as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")

    def load(self, sha: str) -> np.ndarray:
        return np.load(
            os.path.join(self.path, self._PAYLOAD_DIR, sha + ".npy")
        )

    def probes(self) -> List[Dict]:
        return [self._probes[n] for n in sorted(self._probes)]

    def reference(
        self, probe: str, digest: str
    ) -> Optional[Dict]:
        return self._refs.get((probe, digest))

    def __len__(self) -> int:
        return len(self._probes)

    # -- scoring -------------------------------------------------------
    def run(
        self,
        target,
        bank_id: Optional[str] = None,
        db_tol: Optional[float] = None,
        key_seq: int = 0,
        timeout: Optional[float] = None,
    ) -> List[Dict]:
        """Serve every probe through ``target`` (a ServeFleet or
        CodecEngine — anything with ``reconstruct``/``bank_digest``)
        and score each against the stored reference for the bank's
        CURRENT digest. Returns one verdict dict per probe —
        ``status`` ∈ ``reference`` (first sighting of this digest,
        reference recorded) | ``exact`` (bit-identical recon) |
        ``db_ok`` (within ``db_tol`` of the reference dB) |
        ``regressed``. The caller emits ``quality_probe`` /
        ``quality_probe_breach`` events from these."""
        import inspect

        from . import capture as _capture

        if db_tol is None:
            db_tol = _env.env_float("CCSC_PROBE_DB_TOL")
        db_tol = float(db_tol)
        takes_key = "key" in inspect.signature(
            target.reconstruct
        ).parameters
        bank_key = bank_id or "default"
        out: List[Dict] = []
        for p in self.probes():
            b = self.load(p["b"])
            x = self.load(p["x_orig"])
            mask = (
                self.load(p["mask"]) if p.get("mask") else None
            )
            kw = {"timeout": timeout} if timeout else {}
            if takes_key:
                kw["key"] = (
                    f"{PROBE_KEY_PREFIX}{p['name']}-{key_seq}"
                )
            digest = target.bank_digest(bank_id)
            res = target.reconstruct(
                b, mask=mask, x_orig=x, bank_id=bank_id, **kw
            )
            recon = np.ascontiguousarray(
                np.asarray(res.recon, np.float32)
            )
            sha = _capture.payload_sha(recon)
            db = valid_region_psnr(
                recon, x, tuple(p["psf_radius"])
            )
            ref = self.reference(p["name"], digest)
            if ref is None:
                # bank-rot guard: a digest this bank has never
                # served may only seal its own reference if it does
                # not regress the bank's STANDING reference (the
                # newest one any prior digest recorded)
                prior = self._bank_refs.get((p["name"], bank_key))
                if prior is not None and db < (
                    float(prior["db"]) - db_tol
                ):
                    out.append(
                        {
                            "probe": p["name"],
                            "bank_id": bank_id,
                            "digest": digest,
                            "status": "regressed",
                            "db": round(db, 4),
                            "ref_db": prior["db"],
                            "db_tol": db_tol,
                        }
                    )
                    continue
                rec = {
                    "kind": "reference",
                    "probe": p["name"],
                    "digest": digest,
                    "bank": bank_key,
                    "recon_sha": self._store_payload(recon),
                    "db": round(db, 6),
                    "t": time.time(),
                }
                self._append(rec)
                self._refs[(p["name"], digest)] = rec
                self._bank_refs[(p["name"], bank_key)] = rec
                status = "reference"
                ref_db = None
            elif sha == ref["recon_sha"]:
                status = "exact"
                ref_db = ref["db"]
            elif db >= float(ref["db"]) - db_tol:
                status = "db_ok"
                ref_db = ref["db"]
            else:
                status = "regressed"
                ref_db = ref["db"]
            if ref is not None and status in ("exact", "db_ok"):
                # A bank that demonstrably serves a referenced digest
                # owns that reference as its standing baseline — even
                # when the reference was first sealed under a different
                # bank id (e.g. the pinned default bank sharing the
                # digest). Without this link a later never-seen digest
                # for the same bank id would self-seal unguarded.
                prev = self._bank_refs.get((p["name"], bank_key))
                if prev is None or prev.get("recon_sha") != ref["recon_sha"]:
                    link = dict(ref, bank=bank_key)
                    self._append(link)
                    self._bank_refs[(p["name"], bank_key)] = link
            out.append(
                {
                    "probe": p["name"],
                    "bank_id": bank_id,
                    "digest": digest,
                    "status": status,
                    "db": round(db, 4),
                    "ref_db": ref_db,
                    "db_tol": db_tol,
                }
            )
        return out


# ---------------------------------------------------------------------
# shadow bank scoring + the quality gate
# ---------------------------------------------------------------------


def _quality_key_fields(geom, buckets) -> Dict[str, str]:
    """chip/workload/shape_key of a quality record — the replay
    ledger-append recipe, so quality history and serving history
    speak the same key dialect."""
    from ..tune import store as tune_store
    from ..utils import perfmodel

    workload = tune_store.solve_workload(geom)
    largest = max(buckets, key=lambda bk: int(np.prod(bk[1])))
    return {
        "chip": perfmodel.detect_chip(),
        "workload": workload,
        "shape_key": tune_store.solve_shape_key(
            workload,
            k=geom.num_filters,
            support=geom.spatial_support,
            spatial=largest[1],
        ),
    }


def score_bank(
    capture_dir: str,
    d,
    bank_id: Optional[str] = None,
    prob=None,
    cfg=None,
    serve_cfg=None,
    ledger_path: Optional[str] = None,
    limit: Optional[int] = None,
    timeout: Optional[float] = None,
) -> Dict:
    """Shadow-score a candidate bank: re-serve a captured segment's
    ground-truthed requests (those recorded with ``x_orig``) through
    a FRESH engine pinned to ``d``, offline — live traffic is never
    touched — and append one ``kind=quality`` ledger record whose
    ``digest`` field is the candidate bank's content digest. The
    record key shares chip|quality|workload|shape_key|knobs(bank)
    with every other score of the same bank id, so
    :func:`judge_candidate` / ``scripts/quality_gate.py`` can split
    one key's history into live-vs-candidate and judge with the
    quality band.

    ``prob``/``cfg``/``serve_cfg`` default to the capture's recorded
    metadata (geometry, solve params, buckets) — the same solve the
    live fleet ran. Returns the appended record (also carrying
    ``n_scored``/``p10_db``/``min_db``)."""
    from ..analysis import ledger as _ledger
    from ..config import (
        ProblemGeom,
        ServeConfig,
        SolveConfig,
    )
    from ..models.reconstruct import ReconstructionProblem
    from . import capture as _capture
    from . import registry as _registry
    from .engine import CodecEngine

    meta = _capture.read_meta(capture_dir)
    entries = [
        e
        for e in _capture.read_workload(capture_dir)
        if e.get("x_orig")
    ]
    if limit:
        entries = entries[: int(limit)]
    if not entries:
        raise ValueError(
            f"no captured requests with x_orig under "
            f"{capture_dir!r} — shadow scoring needs ground truth"
        )
    gmeta = meta.get("geom") or {}
    if prob is None:
        geom = ProblemGeom(
            tuple(gmeta["spatial_support"]),
            int(gmeta["num_filters"]),
        )
        prob = ReconstructionProblem(geom)
    geom = prob.geom
    if cfg is None:
        smeta = meta.get("solve") or {}
        cfg = SolveConfig(
            max_it=int(smeta.get("max_it", 100)),
            tol=float(smeta.get("tol", 1e-3)),
            lambda_residual=float(
                smeta.get("lambda_residual", 5.0)
            ),
            lambda_prior=float(smeta.get("lambda_prior", 2.0)),
            verbose="none",
        )
    if serve_cfg is None:
        buckets = tuple(
            (int(bk["slots"]), tuple(bk["spatial"]))
            for bk in meta.get("buckets") or ()
        )
        if not buckets:
            raise ValueError(
                "capture metadata carries no bucket table — pass "
                "serve_cfg explicitly"
            )
        serve_cfg = ServeConfig(
            buckets=buckets, capture_dir="", verbose="none"
        )
    digest = _registry.bank_digest(d)
    dbs: List[float] = []
    eng = CodecEngine(d, prob, cfg, serve_cfg)
    try:
        futs = []
        for e in entries:
            b = _capture.load_payload(capture_dir, e["b"])
            mask = (
                _capture.load_payload(capture_dir, e["mask"])
                if e.get("mask")
                else None
            )
            smooth = (
                _capture.load_payload(
                    capture_dir, e["smooth_init"]
                )
                if e.get("smooth_init")
                else None
            )
            x = _capture.load_payload(capture_dir, e["x_orig"])
            futs.append(
                (x, eng.submit(b, mask, smooth, x_orig=x))
            )
        for x, fut in futs:
            res = fut.result(timeout=timeout)
            dbs.append(
                valid_region_psnr(
                    res.recon, x, geom.psf_radius
                )
            )
    finally:
        eng.close()
    dbs.sort()
    median = dbs[len(dbs) // 2] if len(dbs) % 2 else 0.5 * (
        dbs[len(dbs) // 2 - 1] + dbs[len(dbs) // 2]
    )
    rec = _ledger.normalize_record(
        kind="quality",
        value=round(median, 4),
        unit="db",
        knobs={"bank": bank_id or "default"},
        source="score_bank",
        **_quality_key_fields(geom, serve_cfg.buckets),
    )
    # the candidate's content digest is a record FIELD, not part of
    # the key: one key holds every score of the bank id, and the gate
    # partitions its history into candidate-vs-live by this field
    rec.update(
        digest=digest,
        n_scored=len(dbs),
        p10_db=round(dbs[max(0, int(0.1 * len(dbs)) - 1)], 4),
        min_db=round(dbs[0], 4),
    )
    led = _ledger.Ledger(ledger_path)
    led.append(rec)
    return rec


def judge_candidate(
    led,
    candidate_digest: str,
    bank_id: Optional[str] = None,
    mad_k: Optional[float] = None,
    db: Optional[float] = None,
    min_history: Optional[int] = None,
) -> List[Dict]:
    """Judge a candidate bank digest's ``kind=quality`` records
    against the LIVE history under the same ledger key (every record
    whose ``digest`` differs — the scores the currently-published
    banks accrued). The perf_gate verdict shape: one dict per key the
    candidate appears under, ``ok`` False only for a judged
    regression, ``skipped`` True while the live history is thinner
    than ``min_history`` (a young observatory passes trivially)."""
    from ..analysis import ledger as _ledger

    if min_history is None:
        min_history = _env.env_int("CCSC_PERF_GATE_MIN_HISTORY")
    bank_key = None if bank_id is None else (bank_id or "default")
    verdicts: List[Dict] = []
    for key, rows in sorted(led.by_key().items()):
        rows = [r for r in rows if r.get("kind") == "quality"]
        cand = [
            r for r in rows if r.get("digest") == candidate_digest
        ]
        if not cand:
            continue
        if bank_key is not None and (
            (cand[-1].get("knobs") or {}).get("bank") != bank_key
        ):
            continue
        live = [
            float(r["value"])
            for r in rows
            if r.get("digest") != candidate_digest
        ]
        newest = cand[-1]
        v = float(newest["value"])
        band = quality_band(live, mad_k=mad_k, db=db)
        if band is None or band["n"] < min_history:
            verdicts.append(
                {
                    "key": key,
                    "digest": candidate_digest,
                    "value": v,
                    "unit": "db",
                    "n_history": 0 if band is None else band["n"],
                    "skipped": True,
                    "ok": True,
                    "reason": f"live history < {min_history} "
                    "record(s)",
                }
            )
            continue
        verdicts.append(
            {
                "key": key,
                "digest": candidate_digest,
                "value": v,
                "unit": "db",
                "n_history": band["n"],
                "median": band["median"],
                "mad": band["mad"],
                "lo": band["lo"],
                "delta_db": round(v - band["median"], 4),
                "skipped": False,
                "ok": v >= band["lo"],
                "t": newest.get("t"),
                "source": newest.get("source"),
            }
        )
    return verdicts


def gate_publish(
    candidate_digest: str,
    bank_id: Optional[str] = None,
    ledger_path: Optional[str] = None,
) -> Optional[List[Dict]]:
    """The opt-in publish guard: judge ``candidate_digest`` against
    the ledger's live quality history and RAISE
    :class:`QualityGateError` on a regression verdict. Returns the
    verdict list (None when the ledger is off/absent — nothing to
    judge is an allow, the young-observatory stance)."""
    from ..analysis import ledger as _ledger

    if ledger_path is None and not _ledger.enabled():
        return None
    led = _ledger.Ledger(ledger_path)
    verdicts = judge_candidate(
        led, candidate_digest, bank_id=bank_id
    )
    bad = [v for v in verdicts if not v["ok"]]
    if bad:
        worst = min(bad, key=lambda v: v.get("delta_db", 0.0))
        raise QualityGateError(
            f"bank {bank_id or '<default>'} candidate "
            f"{candidate_digest} regresses served quality: "
            f"{worst['value']:.2f} dB vs live band lo "
            f"{worst['lo']:.2f} dB (median "
            f"{worst['median']:.2f} dB over {worst['n_history']} "
            "record(s)) — refusing to publish "
            "(quality_check/CCSC_QUALITY_GATE)",
            verdicts=verdicts,
        )
    return verdicts
