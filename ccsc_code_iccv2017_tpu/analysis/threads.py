"""thread-safety analyzer: lock ordering, blocking work under locks,
untracked threads.

Scope: every module that imports ``threading`` (the serving fleet,
the engine, the watchdog, the supervisor — and whatever grows next).
Three rules, all born from real review findings on this tree:

1. **lock-order** — the lock-acquisition graph (``with a: ... with
   b:`` nestings, per module) must be a consistent partial order;
   the pair (a→b, b→a) appearing in both directions is a deadlock
   waiting for the right interleaving.
2. **blocking-under-lock** — no obs emission (``_emit`` / ``.event``
   / ``obs.record`` / ``.console``), ``print``, ``time.sleep``,
   subprocess call, or thread ``join`` inside a held lock: the event
   write can block on the stream file, and every submitter then
   serializes behind file I/O (the exact bug PR 7's review caught in
   ``submit``). ``Condition.wait`` is exempt — it releases the lock.
3. **untracked-thread** — every ``threading.Thread(...)`` must have a
   join path: bound to a name/attribute that is ``.join``\\ ed
   somewhere in the module, or appended to a container the module
   joins in a loop. A fire-and-forget daemon thread mid-XLA-call
   aborts the interpreter at exit ("terminate called without an
   active exception" — the PR 7 leaked-restart-thread bug class).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, Source, dotted, register

# callables that create a lock-like object
_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}

# dotted-call tails that must not run under a held lock
_BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "subprocess.run": "a subprocess",
    "subprocess.Popen": "a subprocess",
    "subprocess.check_output": "a subprocess",
    "os.makedirs": "filesystem work",
}

_EMIT_ATTRS = {"_emit", "event", "console", "record"}


def _imports_threading(src: Source) -> bool:
    if src.tree is None:
        return False
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            if any(a.name == "threading" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "threading":
                return True
    return False


def _lock_names(src: Source) -> Set[str]:
    """Attribute/variable tails assigned from a lock factory."""
    out: Set[str] = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if dotted(node.value.func) not in _LOCK_FACTORIES:
            continue
        for t in node.targets:
            tail = None
            if isinstance(t, ast.Name):
                tail = t.id
            elif isinstance(t, ast.Attribute):
                tail = t.attr
            if tail:
                out.add(tail)
    return out


def _lock_tail(expr: ast.AST, locks: Set[str]) -> Optional[str]:
    """The lock's simple name when ``with <expr>:`` takes a known
    lock (``self._cv``, a bare ``cv`` alias of one, ...)."""
    tail = None
    if isinstance(expr, ast.Attribute):
        tail = expr.attr
    elif isinstance(expr, ast.Name):
        tail = expr.id
    if tail is None:
        return None
    if tail in locks:
        return tail
    # local alias of a lock attribute: cv = getattr(self, "_cv", ...)
    stripped = tail.lstrip("_")
    for lk in locks:
        if lk.lstrip("_") == stripped:
            return lk
    return None


def _thread_targets(src: Source) -> List[Tuple[int, Optional[str], Optional[str]]]:
    """(line, bound name tail, container tail) per Thread creation.
    Both None = created-and-started inline, never bound."""
    out: List[Tuple[int, Optional[str], Optional[str]]] = []
    creations: Dict[int, ast.Call] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) and dotted(node.func) in (
            "threading.Thread",
            "Thread",
        ):
            creations[id(node)] = node

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            # direct assignment or a comprehension building a list of
            # threads — either way the assign target is the handle
            found = [
                sub
                for sub in ast.walk(node.value)
                if id(sub) in creations
            ]
            for sub in found:
                for t in node.targets:
                    tail = (
                        t.id
                        if isinstance(t, ast.Name)
                        else t.attr
                        if isinstance(t, ast.Attribute)
                        else None
                    )
                    out.append((node.lineno, tail, None))
                creations.pop(id(sub))
            self.generic_visit(node)

        def visit_Call(self, node):
            # container.append(threading.Thread(...)) or
            # container.append(t) handled via the Assign path
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"
                and node.args
                and id(node.args[0]) in creations
            ):
                cont = (
                    node.func.value.attr
                    if isinstance(node.func.value, ast.Attribute)
                    else node.func.value.id
                    if isinstance(node.func.value, ast.Name)
                    else None
                )
                out.append((node.lineno, None, cont))
                creations.pop(id(node.args[0]))
            self.generic_visit(node)

    V().visit(src.tree)
    # whatever remains was neither assigned nor appended
    for call in creations.values():
        out.append((call.lineno, None, None))
    return out


def _joined_tails(src: Source) -> Set[str]:
    """Receiver tails of every ``X.join(...)`` call, plus containers
    iterated by a loop whose target gets joined."""
    joined: Set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr == "join":
            recv = node.func.value
            if isinstance(recv, ast.Attribute):
                joined.add(recv.attr)
            elif isinstance(recv, ast.Name):
                joined.add(recv.id)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.For):
            continue
        if not isinstance(node.target, ast.Name):
            continue
        tgt = node.target.id
        body_joins = any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "join"
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id == tgt
            for b in node.body
            for sub in ast.walk(b)
        )
        if not body_joins:
            continue
        it = node.iter
        # for t in container: / for t in list(container): /
        # for t in sorted(container):
        cands = [it]
        if isinstance(it, ast.Call):
            cands.extend(it.args)
        for c in cands:
            if isinstance(c, ast.Attribute):
                joined.add(c.attr)
            elif isinstance(c, ast.Name):
                joined.add(c.id)
    return joined


def _with_lock_regions(
    fn: ast.AST, locks: Set[str]
) -> List[Tuple[str, ast.With]]:
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            tail = _lock_tail(item.context_expr, locks)
            if tail:
                out.append((tail, node))
    return out


@register("thread-safety")
def check_thread_safety(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.sources:
        if src.tree is None or not _imports_threading(src):
            continue
        locks = _lock_names(src)
        findings.extend(_check_lock_order(src, locks))
        findings.extend(_check_blocking(src, locks))
        findings.extend(_check_threads(src))
    return findings


def _check_lock_order(src: Source, locks: Set[str]) -> List[Finding]:
    pairs: Dict[Tuple[str, str], int] = {}

    def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            now = held
            if isinstance(child, ast.With):
                for item in child.items:
                    tail = _lock_tail(item.context_expr, locks)
                    if tail:
                        for outer in now:
                            if outer != tail:
                                pairs.setdefault(
                                    (outer, tail), child.lineno
                                )
                        now = now + (tail,)
            elif isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # a nested def's body runs later, not under the lock
                walk(child, ())
                continue
            walk(child, now)

    walk(src.tree, ())
    out: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    for (a, b), line in sorted(pairs.items(), key=lambda kv: kv[1]):
        if (b, a) in pairs and (b, a) not in seen:
            seen.add((a, b))
            out.append(
                Finding(
                    check="thread-safety",
                    path=src.rel,
                    line=line,
                    message=(
                        f"inconsistent lock order: `{a}` -> `{b}` "
                        f"here but `{b}` -> `{a}` elsewhere in this "
                        "module — deadlock risk"
                    ),
                )
            )
    return out


def _check_blocking(src: Source, locks: Set[str]) -> List[Finding]:
    out: List[Finding] = []

    def scan_body(tail: str, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue  # runs later, not under the lock
            _flag(tail, child)
            scan_body(tail, child)

    def _flag(tail: str, node: ast.AST) -> None:
        if not isinstance(node, ast.Call):
            return
        d = dotted(node.func)
        if d in _BLOCKING_CALLS:
            out.append(
                Finding(
                    check="thread-safety",
                    path=src.rel,
                    line=node.lineno,
                    message=(
                        f"{_BLOCKING_CALLS[d]} under lock "
                        f"`{tail}` — blocking work must not hold "
                        "the mutex"
                    ),
                )
            )
            return
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _EMIT_ATTRS:
                recv = node.func.value
                recv_name = (
                    recv.attr
                    if isinstance(recv, ast.Attribute)
                    else recv.id
                    if isinstance(recv, ast.Name)
                    else ""
                )
                # obs emission points: self._emit, run.event,
                # obs.record/console, run.console — all end in a
                # stream write that can block on file I/O
                if attr == "_emit" or recv_name in (
                    "obs",
                    "run",
                    "_run",
                    "self",
                ) or recv_name.endswith("run"):
                    if attr == "record" and recv_name == "self":
                        return  # e.g. a local bookkeeping method
                    out.append(
                        Finding(
                            check="thread-safety",
                            path=src.rel,
                            line=node.lineno,
                            message=(
                                f"obs emission `{recv_name}."
                                f"{attr}(...)` under lock "
                                f"`{tail}` — the stream write can "
                                "block every thread contending "
                                "for the mutex"
                            ),
                        )
                    )
            elif attr == "join" and node.keywords is not None:
                recv = node.func.value
                recv_name = (
                    recv.attr
                    if isinstance(recv, ast.Attribute)
                    else recv.id
                    if isinstance(recv, ast.Name)
                    else None
                )
                # joining a thread while holding a lock the thread
                # may need is a deadlock; string ''.join is filtered
                # by requiring a thread-ish receiver
                if recv_name and (
                    "thread" in recv_name.lower()
                    or recv_name in ("t", "_worker", "_monitor")
                ):
                    out.append(
                        Finding(
                            check="thread-safety",
                            path=src.rel,
                            line=node.lineno,
                            message=(
                                f"thread join `{recv_name}.join` "
                                f"under lock `{tail}` — the joined "
                                "thread may need the same lock"
                            ),
                        )
                    )
        elif isinstance(node.func, ast.Name) and node.func.id == "print":
            out.append(
                Finding(
                    check="thread-safety",
                    path=src.rel,
                    line=node.lineno,
                    message=(
                        f"print under lock `{tail}` — console I/O "
                        "must not hold the mutex"
                    ),
                )
            )

    for tail, node in _with_lock_regions(src.tree, locks):
        for stmt in node.body:
            _flag(tail, stmt)
            scan_body(tail, stmt)
    return out


def _check_threads(src: Source) -> List[Finding]:
    out: List[Finding] = []
    joined = _joined_tails(src)
    for line, tail, container in _thread_targets(src):
        if tail is not None and tail in joined:
            continue
        if container is not None and container in joined:
            continue
        what = (
            f"thread bound to `{tail}`"
            if tail
            else f"thread appended to `{container}`"
            if container
            else "fire-and-forget thread"
        )
        out.append(
            Finding(
                check="thread-safety",
                path=src.rel,
                line=line,
                message=(
                    f"{what} has no join path in this module — an "
                    "unjoined thread alive at interpreter exit "
                    "aborts the process mid-XLA-call"
                ),
            )
        )
    return out
