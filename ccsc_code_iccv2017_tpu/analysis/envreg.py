"""env-registry analyzer: every ``CCSC_*`` env read goes through the
shared never-crash helper (``utils.env``) and is declared in its
registry.

This generalizes the tune space's NON_TUNED drift guard to every
config surface: the environment is a config surface too, and an env
read that bypasses the helper gets raw ``int()``/``float()`` parsing
(a typo'd knob crashes a production run) and is invisible to the
generated ``docs/ENV_KNOBS.md``. Writes (``os.environ[...] = ...``,
subprocess env dicts) are exempt — only reads are knob reads.
"""
from __future__ import annotations

import ast
import importlib.util
import os
from typing import List, Optional, Set

from .core import Finding, Project, dotted, register

# the helper module itself is the one sanctioned reader
_HELPER_REL = "ccsc_code_iccv2017_tpu/utils/env.py"
_HELPER_FNS = {
    "env_str",
    "env_int",
    "env_float",
    "env_flag",
    "env_int_list",
}

_ENV_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "utils",
    "env.py",
)


def _load_env_module():
    """``utils/env.py`` loaded BY FILE PATH — the package
    ``__init__`` imports jax, and the linter must stay import-light.
    (Registered in sys.modules for the duration of the exec:
    dataclass introspection looks itself up there.)"""
    import sys

    name = "_ccsc_env_standalone"
    spec = importlib.util.spec_from_file_location(name, _ENV_PATH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(name, None)
    return mod


def load_registry() -> dict:
    return dict(_load_env_module().REGISTRY)


def render_env_docs() -> str:
    return _load_env_module().render_docs()


def _ccsc_literal(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value.startswith("CCSC_")
    ):
        return node.value
    return None


@register("env-registry")
def check_env_registry(project: Project) -> List[Finding]:
    registry = load_registry()
    findings: List[Finding] = []
    for src in project.sources:
        if src.tree is None or src.rel == _HELPER_REL:
            continue
        helper_aliases = _helper_aliases(src.tree)
        os_aliases = _os_aliases(src.tree)
        raw_reads = {
            f"{a}.environ.get" for a in os_aliases
        } | {f"{a}.getenv" for a in os_aliases}
        environ_names = {f"{a}.environ" for a in os_aliases}
        for node in ast.walk(src.tree):
            # raw reads: os.environ.get("CCSC_X"), os.getenv("CCSC_X")
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d in raw_reads and node.args:
                    name = _ccsc_literal(node.args[0])
                    if name:
                        findings.append(
                            Finding(
                                check="env-registry",
                                path=src.rel,
                                line=node.lineno,
                                message=(
                                    f"raw env read of `{name}` — "
                                    "route it through the never-"
                                    "crash helper utils.env "
                                    "(env_str/env_int/env_float/"
                                    "env_flag)"
                                ),
                            )
                        )
                        continue
                # helper calls with an undeclared name
                fn_name = None
                if isinstance(node.func, ast.Name):
                    fn_name = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    if isinstance(
                        node.func.value, ast.Name
                    ) and node.func.value.id in helper_aliases:
                        fn_name = node.func.attr
                if fn_name in _HELPER_FNS and node.args:
                    name = _ccsc_literal(node.args[0])
                    if name and name not in registry:
                        findings.append(
                            Finding(
                                check="env-registry",
                                path=src.rel,
                                line=node.lineno,
                                message=(
                                    f"env knob `{name}` is read via "
                                    "utils.env but not declared in "
                                    "its REGISTRY — declare it "
                                    "(type, default, help) so "
                                    "docs/ENV_KNOBS.md stays "
                                    "complete"
                                ),
                            )
                        )
            # subscript read: os.environ["CCSC_X"] in Load context
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                if dotted(node.value) in environ_names:
                    name = _ccsc_literal(node.slice)
                    if name:
                        findings.append(
                            Finding(
                                check="env-registry",
                                path=src.rel,
                                line=node.lineno,
                                message=(
                                    f"raw env read of `{name}` — "
                                    "route it through the never-"
                                    "crash helper utils.env "
                                    "(env_str/env_int/env_float/"
                                    "env_flag)"
                                ),
                            )
                        )
    return findings


def _os_aliases(tree: ast.Module) -> Set[str]:
    """Names the os module is imported under (``import os as _os``
    must not hide a raw read from the check)."""
    out: Set[str] = {"os"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "os":
                    out.add(a.asname or "os")
    return out


def _helper_aliases(tree: ast.Module) -> Set[str]:
    """Local names under which utils.env is addressed (``env`` from
    ``from ..utils import env`` / ``from . import env``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "env":
                    out.add(a.asname or "env")
    return out
